"""Churn tolerance: the quorum window is a 7th-power availability filter.

The equivocation sweep (`examples/equivocation_threshold.py`) located the
protocol's one genuine liveness *attack* (the metastable preference
loop).  This study quantifies the cost of *benign* dynamism — membership
churn (`config.churn_probability`: nodes toggle dead<->alive per round,
the `Connman` add/remove plane of `net.go:3-31` exercised continuously)
— by testing three analytic models against the simulator:

1. **Own-uptime budget**: a node ingests k conclusive votes per alive
   round; finality = first-passage to ceil(134/k) alive-rounds.
2. **Two-factor dilution**: under uniform sampling the peer draw ignores
   aliveness — querying a departed peer times out to a NEUTRAL vote
   (faithful to the reference's request-expiry semantics,
   `processor.go:21,40`, neutral err `vote.go:56`) — so an alive node
   gains Binomial(k, a_r) conclusive votes per round, where
   a_r = 1/2 + (1-2c)^r/2 is the mean-field alive fraction.
3. **Quorum-window filter** (exact kernel semantics, `vote.go:54-75` /
   `ops/voterecord._apply_vote_bits`): EVERY vote shifts the 8-slot
   window and a neutral vote occupies a slot with its consider bit off;
   confidence bumps only when >= 7 of the last 8 slots are
   considered-yes (and pauses — does not reset — otherwise).  Model: DP
   over (alive, consider-window pattern, bumps) with consider bits
   Bernoulli(a_r), absorbing at 128 bumps.

Measured result (see RESULTS.md "Churn" section): in the DEFAULT vote
semantics, models 1 and 2 fail badly above ~1% churn — votes ARE
applied at exactly the two-factor rate (verified via telemetry), yet
finality lags by 2x and collapses at the round budget — while model 3
tracks the simulator across the whole grid to within ~0.05 completeness
(the others are off by up to 1.0; the residual exceeds per-node
binomial noise and is the DP's mean-field error — within-round draws
share one realized alive fraction, and convexity of the ~a^7 rate makes
fluctuations help — erring conservative everywhere).

**The finding exposed a semantic choice, now a config knob.**  The
batched default delivers a NON-response as a window-shifting neutral
vote — `vote.go:54-75` semantics for a vote that exists.  But in the
reference HOST path a dead peer's query simply expires
(`response.go:5-51`) and never reaches RegisterVotes: no shift at all.
`config.skip_absent_votes=True` implements that host semantics (kernel
mode `register_packed_votes(absent_is_skip=True)`), and under it the
measured trajectories match the two-factor DP essentially exactly
(medians coincide across the grid) — churn cost collapses from ~a^7 to
linear dilution, e.g. at c=0.1 the skip mode finalizes ~99% by round 54
where the default finalizes nothing by round 128.  The default stays
window-shifting for two reasons: it is the conservative reading of the
wire protocol (a timed-out query IS evidence of unavailability, and the
window is the protocol's recency filter), and it keeps the flagship
bench graph byte-identical to the recorded hardware measurements.  The protocol content: the 8-window/7-quorum rule makes finality
throughput scale like P[Bin(8, a) >= 7] = a^8 + 8 a^7 (1-a), i.e.
**~8 a^7 for a < 1**: the chit pipeline degrades with the SEVENTH power
of response availability, not linearly.  The 8 a^7 (1-a) term is the
filter's forgiveness: an ISOLATED neutral slot costs nothing (7
considered-yes of 8 still bumps), so at low churn the window model even
beats the two-factor model (which forfeits every neutral vote); the
cost begins at >= 2 neutrals per window and then compounds.  Churn
never stalls consensus (confidence pauses rather than resets — no
metastability, unlike equivocation), but sustained availability below
~85% makes finality latency explode multiplicatively.  The same filter applies to any
source of neutral responses (`drop_probability`, request expiry), which
is why the latency-weighted/clustered sampling families mask dead peers
in their draw weights instead of paying it.

Usage:
    python examples/churn_tolerance.py [--nodes 4096] [--txs 32]
        [--rounds 128] [--json-out examples/out/churn_tolerance.json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.models import avalanche as av

CHURN_GRID = (0.0, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 0.5)
DROP_GRID = (0.05, 0.1, 0.2, 0.3)
CUTOFFS = (17, 20, 25, 34, 50, 128)
VOTES_NEEDED = 134      # 6 warm-up + 128 bumps at k=8 (golden-pinned)
BUMPS_NEEDED = 128      # finalization_score
WINDOW, QUORUM = 8, 7


def alive_fraction(c: float, r: int) -> float:
    """Mean-field alive fraction at round r (0-based), all-alive start."""
    return 0.5 + 0.5 * (1.0 - 2.0 * c) ** r


def uptime_dp(c: float, k: int, max_rounds: int) -> np.ndarray:
    """Model 1: P[>= ceil(134/k) alive-rounds by round r] (1-based r)."""
    threshold = -(-VOTES_NEEDED // k)
    dist = np.zeros((2, threshold))
    dist[1, 0] = 1.0
    done = np.zeros(max_rounds)
    absorbed = 0.0
    for r in range(max_rounds):
        new = np.zeros_like(dist)
        absorbed += dist[1, threshold - 1]
        new[1, 1:] = dist[1, :-1]
        new[0] = dist[0]
        dist[1] = new[1] * (1 - c) + new[0] * c
        dist[0] = new[0] * (1 - c) + new[1] * c
        done[r] = absorbed
    return done


def _votes_fp_dp(avail_fn, toggle_c: float, k: int,
                 max_rounds: int) -> np.ndarray:
    """First-passage DP to 134 votes: an alive node gains
    Binomial(k, avail_fn(r)) conclusive votes per round, then everything
    toggles dead<->alive with prob `toggle_c` (0 = always alive)."""
    needed = VOTES_NEEDED
    js = np.arange(k + 1)
    comb = np.array([math.comb(k, j) for j in js], dtype=np.float64)
    dist = np.zeros((2, needed))
    dist[1, 0] = 1.0
    done = np.zeros(max_rounds)
    absorbed = 0.0
    c = toggle_c
    for r in range(max_rounds):
        a = avail_fn(r)
        pmf = comb * a ** js * (1.0 - a) ** (k - js)
        alive_row = dist[1]
        acc = pmf[0] * alive_row
        for j in range(1, k + 1):
            absorbed += pmf[j] * alive_row[needed - j:].sum()
            shifted = np.zeros(needed)
            shifted[j:] = alive_row[: needed - j]
            acc = acc + pmf[j] * shifted
        dist = np.stack([dist[0] * (1 - c) + acc * c,
                         acc * (1 - c) + dist[0] * c])
        done[r] = absorbed
    return done


def two_factor_dp(c: float, k: int, max_rounds: int) -> np.ndarray:
    """Model 2: P[>= 134 conclusive votes by round r] (1-based r)."""
    return _votes_fp_dp(lambda r: alive_fraction(c, r), c, k, max_rounds)


def drop_two_factor_dp(d: float, k: int, max_rounds: int) -> np.ndarray:
    """Skip-semantics DP for drops: Binomial(k, 1-d) conclusive votes per
    round, always-alive, first-passage to 134."""
    return _votes_fp_dp(lambda r: 1.0 - d, 0.0, k, max_rounds)


def _window_fp_dp(avail_fn, toggle_c: float, k: int,
                  max_rounds: int) -> np.ndarray:
    """Exact kernel DP — P[finalized by round r] (1-based r).

    State (alive in {0,1}, consider-window pattern in 2^8, bumps<128);
    per vote-slot an ALIVE node shifts a Bernoulli(avail_fn(r)) consider
    bit in and bumps iff the new window has >= QUORUM considered (all
    conclusive votes are honest YES here, so considered ==
    considered-yes); dead nodes' windows freeze, and everything toggles
    dead<->alive with prob `toggle_c` (0 = always alive) after each
    round.  Mean-field over peers, exact in everything else.
    """
    n_w = 1 << WINDOW
    half = n_w >> 1
    popcount = np.array([bin(w).count("1") for w in range(n_w)])
    # Shift map: w -> ((w & 127) << 1) | b; pairs (w, w+128) merge.
    targets0 = (np.arange(half) << 1)           # b = 0 (neutral slot)
    targets1 = targets0 | 1                     # b = 1 (considered yes)
    dist = np.zeros((2, n_w, BUMPS_NEEDED))
    dist[1, 0, 0] = 1.0
    done = np.zeros(max_rounds)
    absorbed = 0.0
    c = toggle_c
    for r in range(max_rounds):
        a = avail_fn(r)
        for _ in range(k):
            mass = dist[1]
            merged = mass[:half] + mass[half:]              # [half, B]
            new = np.zeros_like(mass)
            for b, p, targets in ((0, 1 - a, targets0), (1, a, targets1)):
                bumped = popcount[targets] >= QUORUM
                t_nb, t_b = targets[~bumped], targets[bumped]
                new[t_nb] += p * merged[~bumped]
                src = merged[bumped]
                absorbed += p * src[:, -1].sum()
                new[t_b, 1:] += p * src[:, :-1]
            dist[1] = new
        done[r] = absorbed
        # Toggle: windows and bump counts ride along dead<->alive.
        dead, alive_m = dist[0], dist[1]
        dist = np.stack([dead * (1 - c) + alive_m * c,
                         alive_m * (1 - c) + dead * c])
    return done


def window_dp(c: float, k: int, max_rounds: int) -> np.ndarray:
    """Model 3 under churn: quorum-window DP at the mean-field alive
    fraction, with own-aliveness toggling."""
    return _window_fp_dp(lambda r: alive_fraction(c, r), c, k, max_rounds)


def drop_window_dp(d: float, k: int, max_rounds: int) -> np.ndarray:
    """Default-semantics DP for response DROPS: constant availability
    a = 1-d, node always alive — the clean constant-a validation of the
    C(a) = P[Bin(8,a) >= 7] bump rate (per-slot absences are iid, so no
    trajectory realization noise)."""
    return _window_fp_dp(lambda r: 1.0 - d, 0.0, k, max_rounds)


def measure_cell(n_nodes: int, n_txs: int, rounds: int, c: float,
                 seed: int, skip_absent: bool = False,
                 n_seeds: int = 1, drop: float = 0.0) -> np.ndarray:
    """Per-node finality rounds (1-based; -1 if unfinalized), pooled over
    `n_seeds` alive-trajectory realizations.

    Pooling matters because every node in one run shares a single
    realized alive trajectory: at knife-edge cutoffs (e.g. round 17 at
    low churn, where finality needs >= 134 of 136 slots conclusive) the
    across-run spread dwarfs per-node binomial noise.  Extra seeds reuse
    the compiled function (same shapes, same static cfg).
    """
    cfg = AvalancheConfig(churn_probability=c, gossip=False,
                          drop_probability=drop,
                          skip_absent_votes=skip_absent)
    run = av.run_scan   # self-jitting (static cfg/n_rounds)
    out = []
    for s in range(seed, seed + n_seeds):
        state = av.init(jax.random.key(s), n_nodes, n_txs, cfg)
        final, _ = run(state, cfg, rounds)
        fin_at = np.asarray(jax.device_get(final.finalized_at))  # [N, T]
        node_round = fin_at.max(axis=1)      # a node's slowest target
        out.append(np.where((fin_at >= 0).all(axis=1), node_round + 1, -1))
    return np.concatenate(out)


def _median_round(done: np.ndarray) -> int | None:
    idx = int(np.searchsorted(done, 0.5))
    return idx + 1 if idx < len(done) else None


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4096)
    ap.add_argument("--txs", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n-seeds", type=int, default=3,
                    help="alive-trajectory realizations pooled per cell "
                    "(see measure_cell)")
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin the CPU backend (the jax.config route — a "
                    "JAX_PLATFORMS env var cannot override the axon "
                    "sitecustomize)")
    ap.add_argument("--json-out", type=str,
                    default="examples/out/churn_tolerance.json")
    args = ap.parse_args(argv)
    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    k = AvalancheConfig().k
    t0 = time.time()

    def sweep(grid, key_name, dps_for, pairings, measure_kw_for):
        """Run one (grid value -> both-semantics measurement + DPs) sweep.

        `dps_for(v)` returns the {model: done-array} dict; `pairings`
        maps a gap name to its (model, mode) comparison; `measure_kw_for`
        returns extra measure_cell kwargs per (value, skip) so churn and
        drop sweeps share every line of accounting (the cross-sweep gap
        comparison in RESULTS.md relies on identical definitions).
        """
        cells = []
        worst = {p: 0.0 for p in pairings}
        for v in grid:
            measured = {
                mode: measure_cell(args.nodes, args.txs, args.rounds,
                                   seed=args.seed, n_seeds=args.n_seeds,
                                   skip_absent=skip,
                                   **measure_kw_for(v, skip))
                for mode, skip in (("default", False), ("skip", True))}
            dps = dps_for(v)
            row = {key_name: v,
                   "model_medians": {m: _median_round(d)
                                     for m, d in dps.items()},
                   "completeness": {}}
            for mode, node_round in measured.items():
                fin = node_round >= 0
                row[mode] = {
                    "finalized_fraction": round(float(fin.mean()), 4),
                    "median_final_round": (int(np.median(node_round[fin]))
                                           if fin.any() else None)}
            for r in CUTOFFS:
                if r > args.rounds:
                    continue
                entry = {}
                for mode, node_round in measured.items():
                    fin = node_round >= 0
                    entry[mode] = round(float((node_round[fin] <= r).sum()
                                              / len(node_round)), 4)
                for m, d in dps.items():
                    entry[m] = round(float(d[r - 1]), 4)
                for pairing, (a, b) in pairings.items():
                    worst[pairing] = max(worst[pairing],
                                         abs(entry[a] - entry[b]))
                row["completeness"][str(r)] = entry
            cells.append(row)
            print(f"{key_name}={v:<6} "
                  f"default={row['default']['finalized_fraction']:<7}"
                  f"@{row['default']['median_final_round']} "
                  f"skip={row['skip']['finalized_fraction']:<7}"
                  f"@{row['skip']['median_final_round']} "
                  f"models={row['model_medians']}", flush=True)
        return cells, worst

    cells, worst = sweep(
        CHURN_GRID, "churn",
        lambda c: {"uptime": uptime_dp(c, k, args.rounds),
                   "two_factor": two_factor_dp(c, k, args.rounds),
                   "window": window_dp(c, k, args.rounds)},
        {"uptime_vs_default": ("uptime", "default"),
         "two_factor_vs_default": ("two_factor", "default"),
         "window_vs_default": ("window", "default"),
         "two_factor_vs_skip": ("two_factor", "skip")},
        lambda c, skip: {"c": c})

    # Drop sweep: the same two semantics under per-slot iid response
    # drops (constant availability a = 1-d) — the trajectory-noise-free
    # validation of the C(a) rate and its collapse under the knob.
    drop_cells, drop_worst = sweep(
        DROP_GRID, "drop",
        lambda d: {"window": drop_window_dp(d, k, args.rounds),
                   "two_factor": drop_two_factor_dp(d, k, args.rounds)},
        {"window_vs_default": ("window", "default"),
         "two_factor_vs_skip": ("two_factor", "skip")},
        lambda d, skip: {"c": 0.0, "drop": d})

    # Worst-case 3-sigma band on a measured fraction (p=1/2) over the
    # pooled sample (nodes x seeds); per-node finality events are
    # positively correlated through each run's shared alive trajectory,
    # so treat this as a floor, not the expected residual — the window
    # model's residual above it is mean-field error (see module
    # docstring), conservative side.
    noise = 1.5 / np.sqrt(args.nodes * args.n_seeds)
    result = {
        "config": {"nodes": args.nodes, "txs": args.txs,
                   "rounds": args.rounds, "k": k, "seed": args.seed,
                   "n_seeds": args.n_seeds,
                   "votes_needed": VOTES_NEEDED,
                   "backend": jax.devices()[0].platform},
        "cells": cells,
        "drop_cells": drop_cells,
        "worst_gap_per_pairing": {m: round(v, 4) for m, v in worst.items()},
        "drop_worst_gap_per_pairing": {m: round(v, 4)
                                       for m, v in drop_worst.items()},
        "noise_floor_3sigma": round(float(noise), 4),
        "rate_factor_note": "default-mode bump rate per slot = "
                            "P[Bin(8,a)>=7] = a^8 + 8 a^7 (1-a) "
                            "(~8 a^7 for a<1); skip_absent_votes mode "
                            "recovers linear dilution (two-factor DP)",
        "elapsed_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
    with open(args.json_out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"\ndrop-sweep worst gaps: {result['drop_worst_gap_per_pairing']}")
    print(f"worst |measured-model| per pairing: "
          f"{result['worst_gap_per_pairing']} "
          f"(3-sigma binomial noise floor "
          f"{result['noise_floor_3sigma']}; the window model's residual "
          f"above it is mean-field error, conservative side)")
    print(f"artifact: {args.json_out} ({result['elapsed_s']}s)")
    return result


if __name__ == "__main__":
    main()
