"""Partition outage: stalled-then-recovered finality under the async engine.

The asynchronous query lifecycle (`ops/inflight.py`, PR 3) exists to ask
availability questions the synchronous ideal cannot express.  This study
asks the canonical one: **what does finality do through a network
partition?**  A 50/50 cluster-aligned split is scheduled for rounds
``[start, end)`` (`cfg.partition_spec`); during it every cross-partition
query TIMES OUT — the query sits in the querier's in-flight ring for
`cfg.timeout_rounds()` rounds and then expires unanswered, exactly the
host Processor's reaping (`processor.py:262-269`) — rather than silently
vanishing.  After `end` the partition heals, but queries issued just
before the heal still expire: recovery trails the heal by the timeout,
the tail a memoryless drop model cannot produce.

Since PR 5 the whole story is visible in the round telemetry itself —
this script is the worked example for the observability layer
(`go_avalanche_tpu/obs`, docs/observability.md), host-side streaming
mode: the run's stacked `SimTelemetry` is streamed to a JSONL trace
with `MetricsSink.write_stacked` and a run manifest is written next to
it.  The trace shows, per round:

* ``partition_blocked`` jumping to ~N*k/2 at the cut and back to 0 at
  the heal (the cut severs half of each node's draws);
* ``expiries`` echoing that curve `timeout_rounds` later (every blocked
  query is reaped exactly once — nothing vanishes silently);
* ``ring_occupancy`` swelling while blocked queries sit out their
  timeout, then draining;
* ``finalizations`` stalling through the window and recovering after
  heal + timeout (neutral semantics) or merely slowing (skip).

What the measurement shows (RESULTS-style summary printed per mode):

* **default (delivered-neutral) semantics** — an expired query shifts the
  vote window with its consider bit off, so during the partition every
  node sees only ~half its window considered and the 7-of-8 quorum rule
  (`vote.go:58`) almost never fires: finalization STALLS (the ~8 a^7
  availability filter of the churn study, here with a ~= 0.5), then
  recovers after heal + timeout.
* **skip semantics** (`cfg.skip_absent_votes=True`, the reference-HOST
  reading where an expired response never reaches RegisterVotes) — the
  cost is linear dilution: finality slows through the partition instead
  of stalling, because each side's intra-side quorums still fire.

Liveness under partial synchrony is exactly where Snowball's behavior
diverges from the synchronous analysis ("Quantifying Liveness and Safety
of Avalanche's Snowball", arXiv:2409.02217); this script is the minimal
reproduction of that divergence on the batched simulator.

    python examples/partition_outage.py
    python examples/partition_outage.py --metrics /tmp/outage.jsonl
    python examples/partition_outage.py --nodes 2048 --txs 256 \
        --partition-start 10 --partition-end 60 --timeout-rounds 6

The JSONL trace is sorted-by-construction (host-side streaming); the
in-graph tap variant of the same trace is `run_sim.py --metrics` /
`bench.py --metrics` (unordered io_callback, sort by `round`).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def measure(
    nodes: int = 512,
    txs: int = 64,
    partition_start: int = 5,
    partition_end: int = 60,
    timeout_rounds: int = 4,
    latency_rounds: int = 1,
    finalization_score: int = 48,
    n_rounds: int = 130,
    skip_absent: bool = False,
    seed: int = 0,
    metrics_path: str | None = None,
) -> dict:
    """One partition-outage run; returns per-round telemetry + summary.

    Contested priors (per-node 50/50) so the network must genuinely
    converge per tx; fixed `latency_rounds` response latency inside each
    side; the partition splits the nodes 50/50 for
    ``[partition_start, partition_end)``.  With `metrics_path`, the
    stacked telemetry streams to that JSONL file (one line per round,
    tagged with the engine config) and a manifest lands next to it.
    """
    import jax
    import numpy as np

    from go_avalanche_tpu import obs
    from go_avalanche_tpu.config import AvalancheConfig
    from go_avalanche_tpu.models import avalanche as av
    from go_avalanche_tpu.ops import voterecord as vr

    cfg = AvalancheConfig(
        finalization_score=finalization_score,
        latency_mode="fixed",
        latency_rounds=latency_rounds,
        partition_spec=(partition_start, partition_end, 0.5),
        time_step_s=1.0,
        request_timeout_s=float(timeout_rounds - 1),
        skip_absent_votes=skip_absent,
    )
    state = av.init(jax.random.key(seed), nodes, txs, cfg,
                    init_pref=av.contested_init_pref(seed, nodes, txs))
    final, tel = av.run_scan(state, cfg, n_rounds=n_rounds)
    fins = np.asarray(jax.device_get(tel.finalizations))       # [rounds]
    blocked = np.asarray(jax.device_get(tel.partition_blocked))
    expiries = np.asarray(jax.device_get(tel.expiries))
    occupancy = np.asarray(jax.device_get(tel.ring_occupancy))
    fin_frac = float(np.asarray(jax.device_get(vr.has_finalized(
        final.records.confidence, cfg))).mean())

    if metrics_path:
        # Host-side streaming: ONE device_get for the whole stacked
        # pytree, one JSON line per round, manifest next to the file.
        mode_tag = obs.tag_from_config(cfg) + (
            ", skip-absent" if skip_absent else "")
        with obs.metrics_sink(metrics_path, tag=mode_tag) as sink:
            sink.write_stacked(tel)
        obs.write_manifest(metrics_path, cfg, extra={
            "study": "partition_outage",
            "mode": "skip" if skip_absent else "neutral",
            "workload": {"nodes": nodes, "txs": txs, "rounds": n_rounds,
                         "seed": seed},
        })

    # The stall window: expiry semantics take one timeout to kick in
    # after the cut, and recovery trails the heal by the timeout too.
    stall_lo = partition_start + cfg.timeout_rounds()
    stall_hi = partition_end
    cum = np.cumsum(fins) / (nodes * txs)
    return {
        "mode": "skip" if skip_absent else "neutral",
        "per_round_finalizations": fins.tolist(),
        "per_round_blocked": blocked.tolist(),
        "per_round_expiries": expiries.tolist(),
        "per_round_ring_occupancy": occupancy.tolist(),
        "finalized_fraction_final": fin_frac,
        "finalized_fraction_at_cut": float(cum[partition_start - 1]),
        "finalized_fraction_at_heal": float(cum[stall_hi - 1]),
        "stall_window_finalizations": int(fins[stall_lo:stall_hi].sum()),
        "post_heal_finalizations": int(fins[stall_hi:].sum()),
        "blocked_total": int(blocked.sum()),
        "expiries_total": int(expiries.sum()),
        "peak_ring_occupancy": int(occupancy.max()),
        "timeout_rounds": cfg.timeout_rounds(),
        "metrics_file": metrics_path,
        "config": {
            "nodes": nodes, "txs": txs,
            "partition": [partition_start, partition_end, 0.5],
            "latency_rounds": latency_rounds,
            "finalization_score": finalization_score,
            "rounds": n_rounds,
        },
    }


def _strip(series) -> str:
    peak = max(max(series), 1)
    return "".join(
        " .:-=+*#@"[min(8, (9 * f) // (peak + 1))] for f in series)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=512)
    parser.add_argument("--txs", type=int, default=64)
    parser.add_argument("--partition-start", type=int, default=5)
    parser.add_argument("--partition-end", type=int, default=60)
    parser.add_argument("--timeout-rounds", type=int, default=4)
    parser.add_argument("--latency-rounds", type=int, default=1)
    parser.add_argument("--finalization-score", type=int, default=48)
    parser.add_argument("--rounds", type=int, default=130)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--metrics", type=str, default=None, metavar="PATH",
                        help="stream each mode's per-round telemetry to "
                             "PATH.<mode>.jsonl (JSONL, one line per "
                             "round) with a manifest next to each — the "
                             "host-side streaming mode of the metrics "
                             "sink (docs/observability.md)")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw per-mode dicts as JSON")
    args = parser.parse_args()

    results = []
    for skip in (False, True):
        metrics_path = None
        if args.metrics:
            p = Path(args.metrics)
            mode = "skip" if skip else "neutral"
            metrics_path = str(p.with_name(f"{p.stem}.{mode}{p.suffix}"))
        r = measure(nodes=args.nodes, txs=args.txs,
                    partition_start=args.partition_start,
                    partition_end=args.partition_end,
                    timeout_rounds=args.timeout_rounds,
                    latency_rounds=args.latency_rounds,
                    finalization_score=args.finalization_score,
                    n_rounds=args.rounds, skip_absent=skip,
                    seed=args.seed, metrics_path=metrics_path)
        results.append(r)

    if args.json:
        print(json.dumps(results))
        return

    for r in results:
        fins = r["per_round_finalizations"]
        ps, pe = r["config"]["partition"][0], r["config"]["partition"][1]
        print(f"\n== {r['mode']} absence semantics "
              f"(timeout {r['timeout_rounds']} rounds) ==")
        print(f"finalized fraction: at cut {r['finalized_fraction_at_cut']:.3f}"
              f" | at heal {r['finalized_fraction_at_heal']:.3f}"
              f" | final {r['finalized_fraction_final']:.3f}")
        print(f"finalizations inside stall window: "
              f"{r['stall_window_finalizations']}; after heal: "
              f"{r['post_heal_finalizations']}")
        print(f"blocked queries: {r['blocked_total']} "
              f"(all reaped: {r['expiries_total']} expiries); "
              f"peak ring occupancy {r['peak_ring_occupancy']}")
        # Coarse per-round strip charts: one char per round.  The
        # blocked strip is a square pulse over [start, end); expiries
        # echo it one timeout later; finalization dips between them.
        print(f"rounds 0..{len(fins) - 1} (partition [{ps}, {pe})):")
        print(f"finalizations |{_strip(fins)}|")
        print(f"blocked       |{_strip(r['per_round_blocked'])}|")
        print(f"expiries      |{_strip(r['per_round_expiries'])}|")
        if r["metrics_file"]:
            print(f"trace: {r['metrics_file']} "
                  f"(+ .manifest.json)")


if __name__ == "__main__":
    main()
