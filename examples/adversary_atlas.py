"""The adaptive-adversary atlas: the 2409.02217 phase boundary as a
fleet phase diagram.

"Quantifying Liveness and Safety of Avalanche's Snowball"
(arXiv 2409.02217) derives Snowball's liveness/safety failure
probabilities as functions of (byzantine fraction, k, quorum); "An
Analysis of Avalanche Consensus" (arXiv 2401.02811) constructs the
adversary that realizes the liveness half: choose votes *as a function
of observed network state* so the honest population never leaves its
even split.  This study runs that adversary — `adversary_policy =
"split_vote"` (`ops/adversary.py`) — over the (byzantine_fraction, k,
quorum) cube as ONE fleet phase grid (`fleet.run_phase_grid`: one
vmapped Monte-Carlo fleet per point, re-jit per point) and maps BOTH
failure modes with Wilson CIs:

  * **P(stall)** — the in-graph liveness detector
    (`fleet.liveness_stalled`): honest-majority exists yet no honest
    record finalized by the horizon.  The paper's prediction, and what
    this atlas checks point-blank: monotone-INCREASING in byzantine
    fraction at fixed (k, quorum), with a sharp boundary (the
    metastable band) between the always-settles and never-settles
    phases; a larger quorum margin (window - quorum) pushes the
    boundary right.
  * **P(safety violation)** — the PR-7 quorum-divergence detector:
    two honest nodes finalizing opposite colors.  split_vote is a
    LIVENESS attack; its safety row stays near zero below the
    boundary, which is itself a claim worth the CI.

The run ends with a **detector spot-check** at the most hostile point:
the fleet re-runs with the on-device trace plane (`cfg.trace_every=1`,
obs/trace.py) and every trial's stall verdict is checked against its
trace-plane finality curve — a stalled trial's cumulative
`finalizations` counter can only carry byzantine rows (at most
round(byz * N)); a trial with any honest finalization must show a
non-zero curve.  Two independent measurement paths (final-state
reduction vs per-round telemetry) agreeing per trial is what makes the
detector a detector rather than a restatement.

CPU-shape defaults (64 nodes, 48-trial fleets) finish in a few
minutes; the same script is the TPU-window atlas at paper scale
(--fleet 1024 --nodes 1024).

Usage:
    python examples/adversary_atlas.py [--nodes 64] [--fleet 48]
        [--rounds 120] [--json-out examples/out/adversary_atlas.json]
        [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu import fleet

BYZ_GRID = (0.05, 0.15, 0.25, 0.35, 0.45)
K_GRID = (4, 8)
QUORUM_GRID = (5, 7)


def run_atlas(nodes: int, fleet_size: int, rounds: int, fin_score: int,
              byz_grid, k_grid, quorum_grid, seed: int = 0):
    """One `run_phase_grid` over the (byz, k, quorum) cube; returns the
    phase rows.  The base config carries the policy and a non-zero
    byzantine fraction (the grid overrides it per point — an all-zero
    base would reject the policy as inert)."""
    base = AvalancheConfig(finalization_score=fin_score,
                           byzantine_fraction=byz_grid[0],
                           adversary_policy="split_vote")
    grid = {"byzantine_fraction": list(byz_grid),
            "k": list(k_grid),
            "quorum": list(quorum_grid)}
    return fleet.run_phase_grid("snowball", base, grid, fleet=fleet_size,
                                n_nodes=nodes, n_rounds=rounds, seed=seed,
                                yes_fraction=0.5)


def monotonicity_report(rows, byz_grid, k_grid, quorum_grid):
    """Per-(k, quorum) curve of P(stall) vs byzantine fraction, with the
    monotone-increase check the 2409.02217 boundary predicts.  A dip is
    only counted as a violation when the Wilson CIs are disjoint —
    finite fleets wobble inside their intervals."""
    by_point = {(r["point"]["k"], r["point"]["quorum"],
                 r["point"]["byzantine_fraction"]): r for r in rows}
    curves = []
    for k in k_grid:
        for q in quorum_grid:
            pts = [by_point[(k, q, b)] for b in byz_grid]
            violations = [
                (byz_grid[i], byz_grid[i + 1])
                for i in range(len(pts) - 1)
                # a genuine decrease: the later CI sits wholly below
                # the earlier one
                if pts[i + 1]["stall_ci"][1] < pts[i]["stall_ci"][0]]
            curves.append({
                "k": k, "quorum": q,
                "byz": list(byz_grid),
                "p_stall": [p["p_stall"] for p in pts],
                "stall_ci": [p["stall_ci"] for p in pts],
                "p_violation": [p["p_violation"] for p in pts],
                "monotone": not violations,
                "monotonicity_violations": violations,
            })
    return curves


def spot_check(nodes: int, fleet_size: int, rounds: int, fin_score: int,
               byz: float, seed: int = 0):
    """Re-run the most hostile point with the per-trial trace plane and
    check every trial's stall verdict against its trace finality curve
    (see module docstring).  Returns the per-trial comparison; raises
    on any disagreement — the atlas must not ship with a detector that
    contradicts the telemetry it summarizes."""
    cfg = AvalancheConfig(finalization_score=fin_score,
                          byzantine_fraction=byz,
                          adversary_policy="split_vote",
                          trace_every=1)
    res = fleet.run_fleet("snowball", cfg, fleet=fleet_size,
                          n_nodes=nodes, n_rounds=rounds, seed=seed,
                          yes_fraction=0.5)
    records = res.trace_records()
    n_byz = int(round(byz * nodes))
    trials = []
    for i in range(fleet_size):
        total_fin = sum(rec["finalizations"][i] for rec in records)
        stalled = bool(res.stalled[i])
        # Stalled: no HONEST row finalized, so the all-rows trace
        # counter can only carry byzantine finalizations.  Not stalled
        # with any finalized fraction: the curve must be non-zero.
        if stalled:
            ok = total_fin <= n_byz
        elif res.finalized_fraction[i] > 0:
            ok = total_fin > 0
        else:
            ok = True   # honest-minority trials: detector abstains
        trials.append({"trial": i, "stalled": stalled,
                       "trace_finalizations": int(total_fin),
                       "agrees": ok})
        if not ok:
            raise AssertionError(
                f"stall detector disagrees with the trace-plane "
                f"finality curve on trial {i}: stalled={stalled}, "
                f"cumulative finalizations={total_fin} (n_byz={n_byz})")
    return {"byz": byz, "n_byz": n_byz, "p_stall": res.p_stall,
            "trials_checked": fleet_size, "trials": trials}


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=64)
    ap.add_argument("--fleet", type=int, default=48)
    ap.add_argument("--rounds", type=int, default=120)
    ap.add_argument("--finalization-score", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--quick", action="store_true",
                    help="3-point byz grid at (k=8, quorum=7) only — "
                         "the smoke spelling the test suite runs")
    ap.add_argument("--json-out", type=str,
                    default="examples/out/adversary_atlas.json")
    args = ap.parse_args(argv)

    byz_grid = (0.05, 0.25, 0.45) if args.quick else BYZ_GRID
    k_grid = (8,) if args.quick else K_GRID
    quorum_grid = (7,) if args.quick else QUORUM_GRID

    t0 = time.time()
    rows = run_atlas(args.nodes, args.fleet, args.rounds,
                     args.finalization_score, byz_grid, k_grid,
                     quorum_grid, seed=args.seed)
    curves = monotonicity_report(rows, byz_grid, k_grid, quorum_grid)

    print(f"# adversary atlas — split_vote on snowball, {args.nodes} "
          f"nodes, {args.fleet}-trial fleets, {args.rounds}-round "
          f"horizon, finalization {args.finalization_score}")
    for c in curves:
        print(f"\nk={c['k']} quorum={c['quorum']}   "
              f"(monotone: {c['monotone']})")
        print(f"{'byz':>6} {'P(stall)':>9} {'stall CI':>18} "
              f"{'P(violation)':>13}")
        for b, p, ci, v in zip(c["byz"], c["p_stall"], c["stall_ci"],
                               c["p_violation"]):
            print(f"{b:>6} {p:>9.3f} [{ci[0]:.3f}, {ci[1]:.3f}]"
                  f"{v:>12.3f}")

    check = spot_check(args.nodes, min(args.fleet, 16), args.rounds,
                       args.finalization_score, byz_grid[-1],
                       seed=args.seed)
    print(f"\nspot-check @ byz={check['byz']}: stall verdicts agree "
          f"with the trace-plane finality curves on all "
          f"{check['trials_checked']} trials "
          f"(P(stall) = {check['p_stall']:.3f})")

    result = {"nodes": args.nodes, "fleet": args.fleet,
              "rounds": args.rounds,
              "finalization_score": args.finalization_score,
              "curves": curves, "rows": rows, "spot_check": check,
              "elapsed_s": round(time.time() - t0, 1)}
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out), exist_ok=True)
        with open(args.json_out, "w") as fh:
            json.dump(result, fh, indent=1)
        print(f"wrote {args.json_out}")
    return result


if __name__ == "__main__":
    main()
