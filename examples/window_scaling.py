"""Streaming-scheduler scaling: settle throughput vs window and node count.

RESULTS.md's 1M-tx row (config 5) demonstrates the backlog scheduler at
1,024 nodes; the north star wants 100k.  The retire/refill cadence and the
``[N, W]`` window footprint both change with N and W, so this sweep
measures settled-txs/sec across that grid for the plain backlog and the
streaming conflict-DAG, producing the scaling datum that a single
full-size run cannot: does throughput hold as the window widens and the
node axis grows toward 100k?

Method note: each cell streams a backlog sized `fill * W` (a fixed number
of window generations, default 8) rather than a fixed B, so every cell
does comparable *scheduler* work per slot and wall-clock differences
isolate the per-round cost of the window itself.

    python examples/window_scaling.py                    # full grid (TPU)
    python examples/window_scaling.py --nodes 1024,16384 --windows 1024,4096
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.models import backlog as bl
from go_avalanche_tpu.models import streaming_dag as sdg


def cell_backlog(n_nodes: int, window: int, fill: int, seed: int) -> dict:
    cfg = AvalancheConfig(gossip=False, max_element_poll=window)
    b = fill * window
    backlog = bl.make_backlog(
        jax.random.randint(jax.random.key(seed + 1), (b,), 0, 1 << 20))
    state = bl.init(jax.random.key(seed), n_nodes, window, backlog, cfg)
    run = jax.jit(bl.run, static_argnames=("cfg", "max_rounds"))
    run.lower(state, cfg, 500_000).compile()   # keep compile out of the timing
    t0 = time.time()
    final = run(state, cfg, 500_000)
    rounds = int(jax.device_get(final.sim.round))
    wall = time.time() - t0
    settled = np.asarray(jax.device_get(final.outputs.settled))
    return {
        "model": "backlog", "nodes": n_nodes, "window": window, "txs": b,
        "rounds": rounds, "settled_fraction": float(settled.mean()),
        "txs_per_sec": round(float(settled.sum()) / wall, 1),
        "wall_s": round(wall, 2),
    }


def cell_streaming_dag(n_nodes: int, window: int, fill: int,
                       seed: int) -> dict:
    c = 2
    if window % c:
        raise ValueError(f"window ({window}) must divide by the conflict-set "
                         f"capacity ({c}) so both models run the same width")
    w_sets = window // c
    cfg = AvalancheConfig(gossip=False, max_element_poll=window)
    b_sets = fill * w_sets
    backlog = sdg.make_set_backlog(
        jax.random.randint(jax.random.key(seed + 1), (b_sets, c), 0, 1 << 20))
    state = sdg.init(jax.random.key(seed), n_nodes, w_sets, backlog, cfg)
    run = jax.jit(sdg.run, static_argnames=("cfg", "max_rounds"))
    run.lower(state, cfg, 500_000).compile()   # keep compile out of the timing
    t0 = time.time()
    final = run(state, cfg, 500_000)
    rounds = int(jax.device_get(final.dag.base.round))
    wall = time.time() - t0
    summary = sdg.resolution_summary(final)
    return {
        "model": "streaming_dag", "nodes": n_nodes, "window": window,
        "txs": b_sets * c, "rounds": rounds,
        "settled_fraction": summary["sets_settled_fraction"],
        "one_winner_fraction": summary["sets_one_winner_fraction"],
        "txs_per_sec": round(summary["txs_settled"] / wall, 1),
        "wall_s": round(wall, 2),
    }


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=str, default="1024,8192,32768,100000")
    ap.add_argument("--windows", type=str, default="1024,4096")
    ap.add_argument("--fill", type=int, default=8,
                    help="backlog = fill * window txs per cell")
    ap.add_argument("--models", type=str, default="backlog,streaming_dag")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json-out", type=str,
                    default="examples/out/window_scaling.json")
    args = ap.parse_args(argv)

    runners = {"backlog": cell_backlog, "streaming_dag": cell_streaming_dag}
    cells = []
    for model in args.models.split(","):
        for n in (int(x) for x in args.nodes.split(",")):
            for w in (int(x) for x in args.windows.split(",")):
                cell = runners[model](n, w, args.fill, args.seed)
                cells.append(cell)
                print(json.dumps(cell), flush=True)

    result = {"backend": jax.devices()[0].platform, "fill": args.fill,
              "cells": cells}
    os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
    with open(args.json_out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"artifact: {args.json_out}")
    return cells


if __name__ == "__main__":
    main()
