"""Basic pre-consensus simulation — the reference example, batched.

The TPU-native rendition of `examples/basic-preconcensus/main.go`: N nodes
reconcile T transactions (every node fed every tx up front, `main.go:49-53`),
poll random peers each round, and the run reports wall-clock, how many nodes
fully finalized (`main.go:63-64`), and the throughput/finality metrics the
reference never had.

    python examples/basic_preconsensus.py --nodes 100 --txs 100 --logging

Instead of 100 goroutines and mutexes, the whole network is one jitted
round_step scanned to convergence — the same workload scales to 100k x 1M by
changing the flags (and sharding over a mesh via parallel/).
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.utils import metrics


def run_host_api(args) -> None:
    """The reference example verbatim through the host `Processor` API.

    One Python `Processor` per node (`main.go:73-87`), synchronous peer
    `query` with gossip-on-poll admission and honest own-acceptance votes
    (`main.go:168-193`), round-robin peer selection (`main.go:111-116`),
    counting nodes whose every tx finalized (`main.go:159-161`).  Object-
    per-record and O(nodes^2 * txs) in Python — the workload the batched
    path above does in one fused step; keep sizes modest here.
    """
    import random

    from go_avalanche_tpu import Connman, Processor
    from go_avalanche_tpu.types import Response, Status, Tx, Vote

    rng = random.Random(args.seed)
    n, t = args.nodes, args.txs
    connman = Connman()
    for i in range(n):
        connman.add_node(i)
    processors = [Processor(connman) for _ in range(n)]
    txs = {h: Tx(h) for h in range(t)}

    t0 = time.time()
    for h in rng.sample(range(t), t):        # shuffled feed (`main.go:49-53`)
        for p in processors:
            p.add_target_to_reconcile(txs[h])

    finalized = [0] * n
    fully = 0
    for rnd in range(args.max_rounds):
        for i, p in enumerate(processors):
            if finalized[i] >= t:
                continue
            # Round-robin over the OTHER n-1 peers: the reference skips
            # itself and immediately queries the next node
            # (`main.go:113-116`), so a self-hit advances one further
            # instead of idling the round.
            peer = (i + 1 + rnd) % n
            if peer == i:
                peer = (peer + 1) % n
            invs = p.get_invs_for_next_poll()
            if not invs:
                continue
            votes = []
            for inv in invs:                  # the peer's `query`
                target = txs[inv.target_hash]
                processors[peer].add_target_to_reconcile(target)  # gossip
                err = 0 if processors[peer].is_accepted(target) else 1
                votes.append(Vote(err, inv.target_hash))
            updates: list = []
            p.register_votes(peer, Response(p.get_round(), 0, votes),
                             updates)
            for u in updates:
                if u.status is Status.FINALIZED:
                    finalized[i] += 1
                    if finalized[i] == t:
                        fully += 1
        if fully == n:
            break
    dt = time.time() - t0
    print(f"Finished in {dt:f}s")
    print(f"Nodes fully finalized: {fully}/{n} "
          f"in {rnd + 1} rounds (host API, pure Python)")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=100)
    parser.add_argument("--txs", type=int, default=100)
    parser.add_argument("--k", type=int, default=8)
    parser.add_argument("--byzantine", type=float, default=0.0,
                        help="fraction of adversarial voters")
    parser.add_argument("--drop", type=float, default=0.0,
                        help="response drop probability")
    parser.add_argument("--max-rounds", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--logging", action="store_true")
    parser.add_argument("--host-api", action="store_true",
                        help="run through the per-node host Processor API "
                             "instead of the batched simulator")
    args = parser.parse_args()

    if args.host_api:
        run_host_api(args)
        return

    cfg = AvalancheConfig(k=args.k, byzantine_fraction=args.byzantine,
                          drop_probability=args.drop)
    state = av.init(jax.random.key(args.seed), args.nodes, args.txs, cfg)

    t0 = time.time()
    final = av.run(state, cfg, max_rounds=args.max_rounds)
    rounds = int(final.round)  # fetch synchronizes
    dt = time.time() - t0

    fin = np.asarray(vr.has_finalized(final.records.confidence))
    fully = int(fin.all(axis=1).sum())
    votes = args.nodes * args.txs * cfg.k * rounds  # upper bound (pre-freeze)

    print(f"Finished in {dt:f}s")
    print(f"Nodes fully finalized: {fully}/{args.nodes} "
          f"in {rounds} rounds on {jax.devices()[0].platform}")
    if args.logging:
        stats = metrics.rounds_to_finality(final.finalized_at)
        print(f"rounds-to-finality: {stats}")
        print(f"~{metrics.votes_per_second(votes, dt):.3g} votes/sec "
              f"(upper bound incl. compile)")


if __name__ == "__main__":
    main()
