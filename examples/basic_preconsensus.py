"""Basic pre-consensus simulation — the reference example, batched.

The TPU-native rendition of `examples/basic-preconcensus/main.go`: N nodes
reconcile T transactions (every node fed every tx up front, `main.go:49-53`),
poll random peers each round, and the run reports wall-clock, how many nodes
fully finalized (`main.go:63-64`), and the throughput/finality metrics the
reference never had.

    python examples/basic_preconsensus.py --nodes 100 --txs 100 --logging

Instead of 100 goroutines and mutexes, the whole network is one jitted
round_step scanned to convergence — the same workload scales to 100k x 1M by
changing the flags (and sharding over a mesh via parallel/).
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.utils import metrics


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=100)
    parser.add_argument("--txs", type=int, default=100)
    parser.add_argument("--k", type=int, default=8)
    parser.add_argument("--byzantine", type=float, default=0.0,
                        help="fraction of adversarial voters")
    parser.add_argument("--drop", type=float, default=0.0,
                        help="response drop probability")
    parser.add_argument("--max-rounds", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--logging", action="store_true")
    args = parser.parse_args()

    cfg = AvalancheConfig(k=args.k, byzantine_fraction=args.byzantine,
                          drop_probability=args.drop)
    state = av.init(jax.random.key(args.seed), args.nodes, args.txs, cfg)

    t0 = time.time()
    final = av.run(state, cfg, max_rounds=args.max_rounds)
    rounds = int(final.round)  # fetch synchronizes
    dt = time.time() - t0

    fin = np.asarray(vr.has_finalized(final.records.confidence))
    fully = int(fin.all(axis=1).sum())
    votes = args.nodes * args.txs * cfg.k * rounds  # upper bound (pre-freeze)

    print(f"Finished in {dt:f}s")
    print(f"Nodes fully finalized: {fully}/{args.nodes} "
          f"in {rounds} rounds on {jax.devices()[0].platform}")
    if args.logging:
        stats = metrics.rounds_to_finality(final.finalized_at)
        print(f"rounds-to-finality: {stats}")
        print(f"~{metrics.votes_per_second(votes, dt):.3g} votes/sec "
              f"(upper bound incl. compile)")


if __name__ == "__main__":
    main()
