"""Rounds-to-finality curves — the Avalanche paper's headline fidelity plot.

The BASELINE.json north star asks the framework to "reproduce paper
rounds-to-finality curves" (the Avalanche paper is linked from the reference
README, `README.md:15`).  The paper's key qualitative claims:

  * finality latency grows ~logarithmically with network size, and
  * it degrades gracefully as Byzantine fraction rises toward the
    ~O(sqrt(n)) safety threshold.

This sweep measures both on the batched simulator: for each (network size,
byzantine fraction) it runs the multi-target model to settlement and prints
the rounds-to-finality percentiles plus the cumulative finality curve.

    python examples/finality_curves.py                  # quick sweep
    python examples/finality_curves.py --sizes 256,1024,4096 --txs 64
    python examples/finality_curves.py --json > curves.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.utils import metrics


def run_point(n_nodes: int, n_txs: int, byzantine: float, seed: int,
              max_rounds: int, adversary: str = "flip",
              contested: bool = False) -> dict:
    # The strategy knob rides along only when byzantine > 0 — at the
    # honest-baseline point it is inert and the config validator
    # rejects it (PR 13's inert-knob rule).
    cfg = AvalancheConfig(
        byzantine_fraction=byzantine,
        **(dict(adversary_strategy=AdversaryStrategy(adversary))
           if byzantine > 0 else {}))
    # Per-NODE 50/50 priors: the paper's experimental setup, where the
    # network must actually converge on a value (a unanimous network's
    # finality is size-independent — a flat line that proves nothing).
    init_pref = (av.contested_init_pref(seed, n_nodes, n_txs)
                 if contested else None)
    state = av.init(jax.random.key(seed), n_nodes, n_txs, cfg,
                    init_pref=init_pref)
    t0 = time.perf_counter()
    state = av.run(state, cfg, max_rounds, donate=True)  # self-jitting
    stats = metrics.rounds_to_finality(state.finalized_at)
    fa = np.asarray(jax.device_get(state.finalized_at))
    n_rounds = int(jax.device_get(state.round))
    # Cumulative finality curve: fraction of (node, tx) records finalized
    # by the end of each round — the paper's plot, from finalized_at stamps.
    per_round = np.bincount(fa[fa >= 0].ravel(), minlength=max(n_rounds, 1))
    curve = metrics.finality_curve(per_round, fa.size)
    return {
        "nodes": n_nodes,
        "txs": n_txs,
        "byzantine": byzantine,
        "rounds": n_rounds,
        "elapsed_s": round(time.perf_counter() - t0, 3),
        **{k: round(v, 2) for k, v in stats.items()},
        "curve": [round(float(c), 4) for c in curve],
    }


def fit_log_n(points: list) -> dict:
    """Least-squares fit median = a + b*log2(n) over honest sweep points.

    Quantifies the paper's "finality latency grows ~logarithmically with
    network size" claim: reports slope b (rounds per doubling), intercept,
    R^2 of the log fit, per-size residuals, and — as the falsification
    check — the R^2 of a LINEAR-in-n fit, which must be visibly worse for
    the logarithmic reading to stand.
    """
    ns = np.array([p["nodes"] for p in points], float)
    med = np.array([p["median"] for p in points], float)
    x = np.log2(ns)
    b, a = np.polyfit(x, med, 1)
    pred = a + b * x
    ss_res = float(((med - pred) ** 2).sum())
    ss_tot = float(((med - med.mean()) ** 2).sum())
    r2_log = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    bl, al = np.polyfit(ns, med, 1)
    pred_lin = al + bl * ns
    ss_res_l = float(((med - pred_lin) ** 2).sum())
    r2_lin = 1.0 - ss_res_l / ss_tot if ss_tot > 0 else 1.0
    return {
        "model": "median = a + b*log2(n)",
        "a": round(float(a), 3),
        "b_rounds_per_doubling": round(float(b), 3),
        "r2_log": round(r2_log, 4),
        "r2_linear_in_n": round(r2_lin, 4),
        "points": [
            {"nodes": int(n), "measured": float(m),
             "fitted": round(float(p), 2),
             "residual": round(float(m - p), 2)}
            for n, m, p in zip(ns, med, pred)
        ],
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=str, default="128,512,2048")
    parser.add_argument("--txs", type=int, default=32)
    parser.add_argument("--byzantine", type=str, default="0.0,0.1,0.2")
    parser.add_argument("--adversary", type=str, default="flip",
                        choices=[s.value for s in AdversaryStrategy])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--max-rounds", type=int, default=4000)
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--json-out", type=str, default=None,
                        help="write results + log(n) fit artifact here")
    parser.add_argument("--contested", action="store_true",
                        help="per-node 50/50 initial preferences (the "
                             "paper's setup; unanimous networks give a "
                             "flat, size-independent line)")
    args = parser.parse_args()

    sizes = [int(s) for s in args.sizes.split(",")]
    byz_fracs = [float(b) for b in args.byzantine.split(",")]

    results = [run_point(n, args.txs, b, args.seed, args.max_rounds,
                         args.adversary, contested=args.contested)
               for n in sizes for b in byz_fracs]

    honest_pts = [r for r in results if r["byzantine"] == 0.0
                  and "median" in r]
    fit = fit_log_n(honest_pts) if len(honest_pts) >= 3 else None

    if args.json_out:
        import os
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump({"results": results, "log_n_fit": fit}, f, indent=1)

    if args.json:
        print(json.dumps({"results": results, "log_n_fit": fit}, indent=2))
        return

    hdr = (f"{'nodes':>7} {'byz':>5} {'median':>7} {'p90':>7} {'max':>7} "
           f"{'unfinal%':>9} {'secs':>7}")
    print(hdr)
    print("-" * len(hdr))
    for r in results:
        print(f"{r['nodes']:>7} {r['byzantine']:>5.2f} "
              f"{r.get('median', float('nan')):>7.1f} "
              f"{r.get('p90', float('nan')):>7.1f} "
              f"{r.get('max', float('nan')):>7.0f} "
              f"{100 * r['unfinalized_fraction']:>8.2f}% "
              f"{r['elapsed_s']:>7.2f}")

    # The paper's check, quantified: fit median vs log2(n) for honest runs.
    if fit is not None:
        print(f"\nlog(n) fit: median = {fit['a']} + "
              f"{fit['b_rounds_per_doubling']}*log2(n)   "
              f"R^2(log)={fit['r2_log']}  vs R^2(linear-in-n)="
              f"{fit['r2_linear_in_n']}")
        for p in fit["points"]:
            print(f"  n={p['nodes']:>6}  measured={p['measured']:>6.1f}  "
                  f"fitted={p['fitted']:>6.1f}  residual={p['residual']:+.2f}")
    elif len(honest_pts) == 2:
        lo, hi = honest_pts[0], honest_pts[-1]
        growth = (hi["median"] - lo["median"]) / max(
            np.log2(hi["nodes"] / lo["nodes"]), 1e-9)
        print(f"\nhonest-median growth: {growth:+.2f} rounds per doubling "
              f"of network size ({lo['nodes']} -> {hi['nodes']} nodes)")


if __name__ == "__main__":
    main()
