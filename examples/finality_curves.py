"""Rounds-to-finality curves — the Avalanche paper's headline fidelity plot.

The BASELINE.json north star asks the framework to "reproduce paper
rounds-to-finality curves" (the Avalanche paper is linked from the reference
README, `README.md:15`).  The paper's key qualitative claims:

  * finality latency grows ~logarithmically with network size, and
  * it degrades gracefully as Byzantine fraction rises toward the
    ~O(sqrt(n)) safety threshold.

This sweep measures both on the batched simulator: for each (network size,
byzantine fraction) it runs the multi-target model to settlement and prints
the rounds-to-finality percentiles plus the cumulative finality curve.

    python examples/finality_curves.py                  # quick sweep
    python examples/finality_curves.py --sizes 256,1024,4096 --txs 64
    python examples/finality_curves.py --json > curves.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.utils import metrics


def run_point(n_nodes: int, n_txs: int, byzantine: float, seed: int,
              max_rounds: int, adversary: str = "flip") -> dict:
    cfg = AvalancheConfig(byzantine_fraction=byzantine,
                          adversary_strategy=AdversaryStrategy(adversary))
    state = av.init(jax.random.key(seed), n_nodes, n_txs, cfg)
    t0 = time.perf_counter()
    state = jax.jit(av.run, static_argnames=("cfg", "max_rounds"))(
        state, cfg, max_rounds)
    stats = metrics.rounds_to_finality(state.finalized_at)
    fa = np.asarray(jax.device_get(state.finalized_at))
    n_rounds = int(jax.device_get(state.round))
    # Cumulative finality curve: fraction of (node, tx) records finalized
    # by the end of each round — the paper's plot, from finalized_at stamps.
    per_round = np.bincount(fa[fa >= 0].ravel(), minlength=max(n_rounds, 1))
    curve = metrics.finality_curve(per_round, fa.size)
    return {
        "nodes": n_nodes,
        "txs": n_txs,
        "byzantine": byzantine,
        "rounds": n_rounds,
        "elapsed_s": round(time.perf_counter() - t0, 3),
        **{k: round(v, 2) for k, v in stats.items()},
        "curve": [round(float(c), 4) for c in curve],
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=str, default="128,512,2048")
    parser.add_argument("--txs", type=int, default=32)
    parser.add_argument("--byzantine", type=str, default="0.0,0.1,0.2")
    parser.add_argument("--adversary", type=str, default="flip",
                        choices=[s.value for s in AdversaryStrategy])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--max-rounds", type=int, default=4000)
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args()

    sizes = [int(s) for s in args.sizes.split(",")]
    byz_fracs = [float(b) for b in args.byzantine.split(",")]

    results = [run_point(n, args.txs, b, args.seed, args.max_rounds,
                         args.adversary)
               for n in sizes for b in byz_fracs]

    if args.json:
        print(json.dumps(results, indent=2))
        return

    hdr = (f"{'nodes':>7} {'byz':>5} {'median':>7} {'p90':>7} {'max':>7} "
           f"{'unfinal%':>9} {'secs':>7}")
    print(hdr)
    print("-" * len(hdr))
    for r in results:
        print(f"{r['nodes']:>7} {r['byzantine']:>5.2f} "
              f"{r.get('median', float('nan')):>7.1f} "
              f"{r.get('p90', float('nan')):>7.1f} "
              f"{r.get('max', float('nan')):>7.0f} "
              f"{100 * r['unfinalized_fraction']:>8.2f}% "
              f"{r['elapsed_s']:>7.2f}")

    # The paper's qualitative check: latency ~log(n) for the honest runs.
    honest = [r for r in results if r["byzantine"] == 0.0 and "median" in r]
    if len(honest) >= 2:
        lo, hi = honest[0], honest[-1]
        growth = (hi["median"] - lo["median"]) / max(
            np.log2(hi["nodes"] / lo["nodes"]), 1e-9)
        print(f"\nhonest-median growth: {growth:+.2f} rounds per doubling "
              f"of network size ({lo['nodes']} -> {hi['nodes']} nodes)")


if __name__ == "__main__":
    main()
