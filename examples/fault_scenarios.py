"""Fault-scenario library: scripted outages with machine-checked recovery.

The scheduled fault-script engine (`cfg.fault_script`, PR 6) turned the
single static partition of PR 3 into a schedule of timed events —
partitions that heal, regional outages, latency spikes, churn bursts —
and `obs/recovery.py` turned "does the network recover, and how fast"
from a chart into a machine-checked property.  This script is both at
work: a small library of named scenarios, each a fault script with a
story, each run emitting (optionally) a flight-recorder JSONL trace and
always ending in a RECOVERY VERDICT — the `obs.verify_recovery` report
checked against the very script that ran.

Scenarios (`--list` for the one-liners):

  partition_heal    — the PR 3 canonical study kept verbatim
                      (`measure()`, both absence semantics): a 50/50
                      cluster-aligned split that heals; finality stalls
                      (neutral) or merely slows (skip), recovery trails
                      the heal by the timeout.
  cascading_outage  — two regional outages overlapping in time
                      (cluster 0 drops at round 10, cluster 1 at 20,
                      staggered heals): the recovery checker merges the
                      overlapping cuts into ONE composite window —
                      occupancy cannot return to baseline between two
                      cuts that share rounds.
  flaky_isp         — a topology-coupled latency story, no cut at all:
                      an `rtt_matrix` makes cluster 2's links slow
                      (3 rounds vs 1 intra-cluster), and two scheduled
                      latency spikes push exactly those slow links past
                      the timeout — an EXPIRY STORM with zero
                      partition-blocked queries, the signature that
                      tells "slow" from "severed" in a trace.
  eclipse           — eclipse-style isolation of a small node fraction
                      (a 12.5% split for 30 rounds): the eclipsed
                      minority stalls — it can't reach quorum alone —
                      while the majority barely notices; after the heal
                      the minority catches up within one timeout.
  monte_carlo       — the Monte-Carlo fleet (PR 7, trace-backed since
                      PR 11): a STOCHASTIC partition whose length (and
                      split fraction) is drawn per trial from the init
                      key (`cfg.fault_script` stochastic_partition
                      ranges, `go_avalanche_tpu/fleet.py`), a whole
                      fleet of sims vmapped into one program with the
                      on-device trace plane on (`cfg.trace_every=1` —
                      per-trial [F, S, M] round-by-round traces,
                      obs/trace.py), each trial's recovery checked from
                      ITS OWN trace against ITS realized window
                      (`FleetResult.cut_windows`) — ending in a printed
                      P(recovery) ± Wilson-CI verdict instead of one
                      anecdote, with a realized-length breakdown.

    python examples/fault_scenarios.py                    # all scenarios
    python examples/fault_scenarios.py eclipse flaky_isp
    python examples/fault_scenarios.py --metrics /tmp/faults.jsonl
    python examples/fault_scenarios.py --json

With `--metrics PATH`, each scenario streams its per-round telemetry to
`PATH.<scenario>.jsonl` (host-side `obs.MetricsSink.write_stacked`, one
line per round, manifest next to it) and the recovery verdict is then
checked FROM THE FILE — trace out, verdict in, the full loop the tier-1
recovery tests drive.  The same traces come out of
`run_sim --fault-script script.json --metrics trace.jsonl` (in-graph
tap; sort by `round`).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def measure(
    nodes: int = 512,
    txs: int = 64,
    partition_start: int = 5,
    partition_end: int = 60,
    timeout_rounds: int = 4,
    latency_rounds: int = 1,
    finalization_score: int = 48,
    n_rounds: int = 130,
    skip_absent: bool = False,
    seed: int = 0,
    metrics_path: str | None = None,
) -> dict:
    """One partition-outage run; returns per-round telemetry + summary.

    The PR 3 canonical study, API kept verbatim (tests/test_inflight.py
    pins its numbers): contested priors (per-node 50/50) so the network
    must genuinely converge per tx; fixed `latency_rounds` response
    latency inside each side; the partition splits the nodes 50/50 for
    ``[partition_start, partition_end)`` — spelled `partition_spec`,
    the one-event fault-script sugar.  With `metrics_path`, the stacked
    telemetry streams to that JSONL file (one line per round, tagged
    with the engine config) and a manifest lands next to it.
    """
    import jax
    import numpy as np

    from go_avalanche_tpu import obs
    from go_avalanche_tpu.config import AvalancheConfig
    from go_avalanche_tpu.models import avalanche as av
    from go_avalanche_tpu.ops import voterecord as vr

    cfg = AvalancheConfig(
        finalization_score=finalization_score,
        latency_mode="fixed",
        latency_rounds=latency_rounds,
        partition_spec=(partition_start, partition_end, 0.5),
        time_step_s=1.0,
        request_timeout_s=float(timeout_rounds - 1),
        skip_absent_votes=skip_absent,
    )
    state = av.init(jax.random.key(seed), nodes, txs, cfg,
                    init_pref=av.contested_init_pref(seed, nodes, txs))
    final, tel = av.run_scan(state, cfg, n_rounds=n_rounds)
    fins = np.asarray(jax.device_get(tel.finalizations))       # [rounds]
    blocked = np.asarray(jax.device_get(tel.partition_blocked))
    expiries = np.asarray(jax.device_get(tel.expiries))
    occupancy = np.asarray(jax.device_get(tel.ring_occupancy))
    fin_frac = float(np.asarray(jax.device_get(vr.has_finalized(
        final.records.confidence, cfg))).mean())

    if metrics_path:
        # Host-side streaming: ONE device_get for the whole stacked
        # pytree, one JSON line per round, manifest next to the file.
        mode_tag = obs.tag_from_config(cfg) + (
            ", skip-absent" if skip_absent else "")
        with obs.metrics_sink(metrics_path, tag=mode_tag) as sink:
            sink.write_stacked(tel)
        obs.write_manifest(metrics_path, cfg, extra={
            "study": "fault_scenarios.partition_heal",
            "mode": "skip" if skip_absent else "neutral",
            "workload": {"nodes": nodes, "txs": txs, "rounds": n_rounds,
                         "seed": seed},
        })

    # The stall window: expiry semantics take one timeout to kick in
    # after the cut, and recovery trails the heal by the timeout too.
    stall_lo = partition_start + cfg.timeout_rounds()
    stall_hi = partition_end
    cum = np.cumsum(fins) / (nodes * txs)
    return {
        "mode": "skip" if skip_absent else "neutral",
        "per_round_finalizations": fins.tolist(),
        "per_round_blocked": blocked.tolist(),
        "per_round_expiries": expiries.tolist(),
        "per_round_ring_occupancy": occupancy.tolist(),
        "finalized_fraction_final": fin_frac,
        "finalized_fraction_at_cut": float(cum[partition_start - 1]),
        "finalized_fraction_at_heal": float(cum[stall_hi - 1]),
        "stall_window_finalizations": int(fins[stall_lo:stall_hi].sum()),
        "post_heal_finalizations": int(fins[stall_hi:].sum()),
        "blocked_total": int(blocked.sum()),
        "expiries_total": int(expiries.sum()),
        "peak_ring_occupancy": int(occupancy.max()),
        "timeout_rounds": cfg.timeout_rounds(),
        "metrics_file": metrics_path,
        "config": {
            "nodes": nodes, "txs": txs,
            "partition": [partition_start, partition_end, 0.5],
            "latency_rounds": latency_rounds,
            "finalization_score": finalization_score,
            "rounds": n_rounds,
        },
    }


# ----------------------------------------------------------- scenarios

def _cascading_outage(timing: dict) -> tuple:
    """Two regions fail in cascade: cluster 0 at round 10, cluster 1 at
    20, heals staggered at 30 and 40.  The windows OVERLAP, so the
    recovery checker verifies them as one composite [10, 40) outage."""
    from go_avalanche_tpu.config import AvalancheConfig

    cfg = AvalancheConfig(
        finalization_score=48,
        n_clusters=4,
        latency_mode="fixed", latency_rounds=1,
        fault_script=(("regional_outage", 10, 30, 0),
                      ("regional_outage", 20, 40, 1)),
        **timing,
    )
    return cfg, 70, ("cluster 0 dark rounds [10, 30), cluster 1 "
                     "[20, 40): one merged recovery window [10, 40)")


def _flaky_isp(timing: dict) -> tuple:
    """No cut anywhere — cluster 2 just sits behind a slow ISP
    (cluster-pair RTT 3 vs 1 intra-cluster), and two latency spikes
    push those slow links past the timeout: expiries WITHOUT blocked
    queries, the trace signature separating 'slow' from 'severed'."""
    from go_avalanche_tpu.config import AvalancheConfig

    slow = 2
    rtt = tuple(tuple(3 if slow in (i, j) and i != j else 1
                      for j in range(4)) for i in range(4))
    cfg = AvalancheConfig(
        finalization_score=48,
        n_clusters=4,
        latency_mode="rtt", rtt_matrix=rtt,
        fault_script=(("latency_spike", 12, 16, 2),
                      ("latency_spike", 30, 34, 2)),
        **timing,
    )
    # rtt 3 + spike 2 == 5 >= timeout 4 -> the slow links' draws become
    # the never-delivers sentinel during each spike; intra-cluster
    # draws (1 + 2 == 3 < 4) keep delivering.
    return cfg, 60, ("cluster 2 at RTT 3 (others 1); spikes [12, 16) "
                     "and [30, 34) push only its links past the "
                     "timeout — expiry storms, zero blocked")


def _eclipse(timing: dict) -> tuple:
    """Eclipse-style isolation: a 12.5% node fraction is split off for
    rounds [15, 45).  The eclipsed minority cannot reach quorum alone
    (k-of-N draws mostly cross the cut and expire); the majority loses
    only 1-in-8 draws and barely slows."""
    from go_avalanche_tpu.config import AvalancheConfig

    cfg = AvalancheConfig(
        finalization_score=48,
        latency_mode="fixed", latency_rounds=1,
        fault_script=(("partition", 15, 45, 0.125),),
        **timing,
    )
    return cfg, 80, ("12.5% of nodes eclipsed rounds [15, 45): the "
                     "minority stalls, the majority shrugs, the "
                     "minority catches up within one timeout of heal")


SCENARIOS = {
    "cascading_outage": _cascading_outage,
    "flaky_isp": _flaky_isp,
    "eclipse": _eclipse,
}


def run_monte_carlo(
    nodes: int = 128,
    txs: int = 32,
    fleet: int = 48,
    timeout_rounds: int = 4,
    n_rounds: int = 70,
    seed: int = 0,
    metrics_path: str | None = None,
) -> dict:
    """The Monte-Carlo scenario: a stochastic partition-length sweep.

    One `cfg.fault_script` stochastic_partition event — start drawn from
    rounds [5, 10], LENGTH from [6, 28] rounds, split fraction from
    [0.35, 0.65] — realized independently per trial from the init key
    (`ops/inflight.draw_fault_params`), a fleet of whole sims vmapped
    into one compiled program (`fleet.run_fleet`) with the ON-DEVICE
    TRACE PLANE on (`cfg.trace_every=1`, obs/trace.py — the vmap lifts
    each trial's ``[S, M]`` buffer to per-trial ``[F, S, M]`` traces,
    the tap the io_callback flight recorder could never provide under
    vmap).  Every trial's recovery invariants are then checked against
    ITS OWN realized ``[start, heal)`` window
    (`obs.check_recovery(cfg, res.trace_records(),
    windows=res.cut_windows)`).  The verdict is a POPULATION number:
    P(recovery) with a Wilson CI, plus the recovery rate bucketed by
    realized outage length — short cuts always heal, cuts approaching
    the horizon run out of rounds to drain their expiry tail.

    With `metrics_path`, the decoded fleet-stacked trace streams to
    that JSONL file (per-round rows whose counters are per-trial
    LISTS — the fleet-trace format, docs/observability.md) and the
    verdicts are then checked FROM the file.
    """
    from go_avalanche_tpu import fleet as fl
    from go_avalanche_tpu import obs
    from go_avalanche_tpu.config import AvalancheConfig

    cfg = AvalancheConfig(
        finalization_score=48,
        latency_mode="fixed", latency_rounds=1,
        fault_script=(
            ("stochastic_partition", (5, 10), (6, 28), (0.35, 0.65)),),
        time_step_s=1.0,
        request_timeout_s=float(timeout_rounds - 1),
        trace_every=1,
    )
    res = fl.run_fleet("avalanche", cfg, fleet=fleet, n_nodes=nodes,
                       n_txs=txs, n_rounds=n_rounds, seed=seed)
    records = res.trace_records()

    if metrics_path:
        with obs.metrics_sink(metrics_path,
                              tag=obs.tag_from_config(cfg)) as sink:
            for rec in records:
                sink.write(rec)
        obs.write_manifest(metrics_path, cfg, extra={
            "study": "fault_scenarios.monte_carlo",
            "workload": {"nodes": nodes, "txs": txs, "rounds": n_rounds,
                         "fleet": fleet, "seed": seed},
        })
        records = obs.recovery.load_trace(metrics_path)

    # One verdict per trial, each against its own realized window —
    # check_recovery returns the vector (no raise) on a fleet trace.
    reports = obs.check_recovery(cfg, records, windows=res.cut_windows)
    oks = [r.ok for r in reports]
    recovered = sum(oks)
    ci = fl.wilson_interval(recovered, fleet)

    # Recovery rate by realized outage length (the sweep's x-axis).
    lengths = (res.cut_windows[:, 0, 1] - res.cut_windows[:, 0, 0])
    by_length: dict = {}
    for lo, hi in ((6, 12), (12, 20), (20, 29)):
        sel = [i for i in range(fleet) if lo <= int(lengths[i]) < hi]
        if sel:
            by_length[f"[{lo}, {hi})"] = {
                "trials": len(sel),
                "recovered": sum(oks[i] for i in sel),
            }
    return {
        "scenario": "monte_carlo",
        "fleet": fleet,
        "recovered": int(recovered),
        "p_recovery": recovered / fleet,
        "recovery_ci": list(ci),
        "p_settled": res.p_settled,
        "settled_ci": list(res.settled_ci),
        "violations": int(res.violations.sum()),
        "by_length": by_length,
        "realized_windows": res.cut_windows[:, 0, :].tolist(),
        "failed_trials": [i for i, ok in enumerate(oks) if not ok],
        "metrics_file": metrics_path,
        "rounds": n_rounds,
    }


def _print_monte_carlo(r: dict) -> None:
    lo, hi = r["recovery_ci"]
    print("\n== monte_carlo ==")
    print(f"stochastic partition: start ~ U[5, 10], length ~ U[6, 28] "
          f"rounds, split ~ U[0.35, 0.65] — realized per trial, "
          f"{r['fleet']} trials in one vmapped program")
    print(f"P(recovery) = {r['recovered']}/{r['fleet']} "
          f"= {r['p_recovery']:.3f}  (95% Wilson CI "
          f"[{lo:.3f}, {hi:.3f}])")
    print(f"P(settled)  = {r['p_settled']:.3f}  (CI "
          f"[{r['settled_ci'][0]:.3f}, {r['settled_ci'][1]:.3f}]); "
          f"{r['violations']} safety violations")
    print("recovery by realized outage length:")
    for bucket, b in r["by_length"].items():
        print(f"  length {bucket:>9}: {b['recovered']}/{b['trials']} "
              f"recovered")
    if r["failed_trials"]:
        print(f"unrecovered trials: {r['failed_trials']}")
    if r["metrics_file"]:
        print(f"trace: {r['metrics_file']} (+ .manifest.json; "
              f"fleet-stacked rows — per-trial LISTS per counter)")


def run_scenario(
    name: str,
    nodes: int = 512,
    txs: int = 64,
    timeout_rounds: int = 4,
    seed: int = 0,
    metrics_path: str | None = None,
) -> dict:
    """Run one named scenario end-to-end: simulate, (optionally) emit
    the flight-recorder trace + manifest, verify the recovery
    invariants against the script that ran, return summary + verdict.
    """
    import jax
    import numpy as np

    from go_avalanche_tpu import obs
    from go_avalanche_tpu.models import avalanche as av
    from go_avalanche_tpu.obs.sink import _flatten_telemetry
    from go_avalanche_tpu.ops import voterecord as vr

    timing = dict(time_step_s=1.0,
                  request_timeout_s=float(timeout_rounds - 1))
    cfg, n_rounds, story = SCENARIOS[name](timing)
    state = av.init(jax.random.key(seed), nodes, txs, cfg,
                    init_pref=av.contested_init_pref(seed, nodes, txs))
    final, tel = av.run_scan(state, cfg, n_rounds=n_rounds)

    if metrics_path:
        with obs.metrics_sink(metrics_path,
                              tag=obs.tag_from_config(cfg)) as sink:
            sink.write_stacked(tel)
        obs.write_manifest(metrics_path, cfg, extra={
            "study": f"fault_scenarios.{name}",
            "workload": {"nodes": nodes, "txs": txs, "rounds": n_rounds,
                         "seed": seed},
        })
        records = obs.recovery.load_trace(metrics_path)
    else:
        host = _flatten_telemetry(jax.device_get(tel), {})
        records = [{"round": r,
                    **{k: int(np.asarray(v[r])) for k, v in host.items()}}
                   for r in range(n_rounds)]

    report = obs.verify_recovery(cfg, records)
    fin_frac = float(np.asarray(jax.device_get(vr.has_finalized(
        final.records.confidence, cfg))).mean())
    return {
        "scenario": name,
        "story": story,
        "recovered": report.ok,
        "violations": report.violations,
        "windows": report.windows,
        "totals": report.totals,
        "finalized_fraction_final": fin_frac,
        "per_round_finalizations": [int(r["finalizations"])
                                    for r in records],
        "per_round_blocked": [int(r["partition_blocked"])
                              for r in records],
        "per_round_expiries": [int(r["expiries"]) for r in records],
        "metrics_file": metrics_path,
        "rounds": n_rounds,
    }


def _strip(series) -> str:
    peak = max(max(series), 1)
    return "".join(
        " .:-=+*#@"[min(8, (9 * f) // (peak + 1))] for f in series)


def _print_partition_heal(results: list) -> None:
    for r in results:
        fins = r["per_round_finalizations"]
        ps, pe = r["config"]["partition"][0], r["config"]["partition"][1]
        print(f"\n== partition_heal / {r['mode']} absence semantics "
              f"(timeout {r['timeout_rounds']} rounds) ==")
        print(f"finalized fraction: at cut "
              f"{r['finalized_fraction_at_cut']:.3f}"
              f" | at heal {r['finalized_fraction_at_heal']:.3f}"
              f" | final {r['finalized_fraction_final']:.3f}")
        print(f"blocked queries: {r['blocked_total']} "
              f"(all reaped: {r['expiries_total']} expiries); "
              f"peak ring occupancy {r['peak_ring_occupancy']}")
        print(f"rounds 0..{len(fins) - 1} (partition [{ps}, {pe})):")
        print(f"finalizations |{_strip(fins)}|")
        print(f"blocked       |{_strip(r['per_round_blocked'])}|")
        print(f"expiries      |{_strip(r['per_round_expiries'])}|")
        if r["metrics_file"]:
            print(f"trace: {r['metrics_file']} (+ .manifest.json)")


def _print_scenario(r: dict) -> None:
    verdict = "RECOVERED" if r["recovered"] else "VIOLATED"
    print(f"\n== {r['scenario']} ==")
    print(r["story"])
    print(f"recovery verdict: {verdict}"
          + (f" — {len(r['violations'])} violation(s)"
             if r["violations"] else ""))
    for v in r["violations"]:
        print(f"  ! {v}")
    for w in r["windows"]:
        rec = (f"recovered {w['recovery_rounds']} round(s) after heal"
               if w["recovery_rounds"] is not None else "NOT recovered")
        print(f"  cut [{w['start']}, {w['heal']}): {w['blocked']} draws "
              f"blocked, {rec} (baseline occupancy "
              f"{w['baseline_occupancy']})")
    t = r["totals"]
    print(f"totals: {t['blocked_total']} blocked, "
          f"{t['expiries_total']} expiries, "
          f"{t['deliveries_total']} deliveries, peak occupancy "
          f"{t['peak_occupancy']}; finalized fraction "
          f"{r['finalized_fraction_final']:.3f}")
    print(f"rounds 0..{r['rounds'] - 1}:")
    print(f"finalizations |{_strip(r['per_round_finalizations'])}|")
    print(f"blocked       |{_strip(r['per_round_blocked'])}|")
    print(f"expiries      |{_strip(r['per_round_expiries'])}|")
    if r["metrics_file"]:
        print(f"trace: {r['metrics_file']} (+ .manifest.json)")


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("scenarios", nargs="*",
                        choices=[[], *SCENARIOS, "partition_heal",
                                 "monte_carlo"],
                        help="scenarios to run (default: all)")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios and exit")
    parser.add_argument("--nodes", type=int, default=512)
    parser.add_argument("--txs", type=int, default=64)
    parser.add_argument("--timeout-rounds", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    # partition_heal-only knobs (the old partition_outage.py CLI): vary
    # the cut window / response latency / horizon without editing source.
    parser.add_argument("--partition-start", type=int, default=5)
    parser.add_argument("--partition-end", type=int, default=60)
    parser.add_argument("--latency-rounds", type=int, default=1)
    parser.add_argument("--finalization-score", type=int, default=48)
    parser.add_argument("--rounds", type=int, default=130,
                        help="partition_heal horizon (other scenarios "
                             "fix their own)")
    parser.add_argument("--fleet", type=int, default=48,
                        help="monte_carlo trial count (one vmapped "
                             "program; Wilson CI tightens as 1/sqrt(F))")
    parser.add_argument("--metrics", type=str, default=None,
                        metavar="PATH",
                        help="stream each scenario's per-round telemetry "
                             "to PATH.<scenario>.jsonl with a manifest "
                             "next to each; the recovery verdict is then "
                             "checked FROM the file")
    parser.add_argument("--json", action="store_true",
                        help="emit the raw per-scenario dicts as JSON")
    args = parser.parse_args()

    if args.list:
        print("partition_heal: the PR 3 canonical 50/50 split, both "
              "absence semantics (measure())")
        for name, fn in SCENARIOS.items():
            print(f"{name}: {fn.__doc__.splitlines()[0].strip()}")
        print("monte_carlo: stochastic partition-length sweep — a "
              "vmapped fleet, per-trial realized windows, "
              "P(recovery) ± Wilson CI (run_monte_carlo())")
        return

    names = args.scenarios or ["partition_heal", *SCENARIOS,
                               "monte_carlo"]
    out = []
    for name in names:
        metrics_path = None
        if args.metrics:
            p = Path(args.metrics)
            metrics_path = str(p.with_name(f"{p.stem}.{name}{p.suffix}"))
        if name == "partition_heal":
            results = []
            for skip in (False, True):
                mp = None
                if metrics_path:
                    q = Path(metrics_path)
                    mode = "skip" if skip else "neutral"
                    mp = str(q.with_name(f"{q.stem}.{mode}{q.suffix}"))
                results.append(measure(
                    nodes=args.nodes, txs=args.txs,
                    partition_start=args.partition_start,
                    partition_end=args.partition_end,
                    timeout_rounds=args.timeout_rounds,
                    latency_rounds=args.latency_rounds,
                    finalization_score=args.finalization_score,
                    n_rounds=args.rounds,
                    skip_absent=skip, seed=args.seed, metrics_path=mp))
            out.extend(results)
            if not args.json:
                _print_partition_heal(results)
        elif name == "monte_carlo":
            r = run_monte_carlo(nodes=args.nodes, txs=args.txs,
                                fleet=args.fleet,
                                timeout_rounds=args.timeout_rounds,
                                seed=args.seed,
                                metrics_path=metrics_path)
            out.append(r)
            if not args.json:
                _print_monte_carlo(r)
        else:
            r = run_scenario(name, nodes=args.nodes, txs=args.txs,
                             timeout_rounds=args.timeout_rounds,
                             seed=args.seed, metrics_path=metrics_path)
            out.append(r)
            if not args.json:
                _print_scenario(r)

    if args.json:
        print(json.dumps(out))


if __name__ == "__main__":
    main()
