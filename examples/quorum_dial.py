"""The quorum dial: availability vs liveness vs SAFETY, per quorum.

The protocol fixes quorum = 7 of an 8-vote window (`vote.go:55,58`);
this framework makes both sweepable (`config.window` / `config.quorum`).
The churn/drop study pinned the availability side (bump rate
C_Q(a) = P[Bin(8,a) >= Q], validated to sub-noise precision at Q=7) and
the equivocation study pinned the liveness side at Q=7.  This study
turns the quorum into the independent variable and measures all three
axes per Q:

1. **availability** (closed form from the validated C_Q law): a50 where
   the steady-state bump rate halves, and the latency multiplier
   1/C_Q(a) at representative availabilities;
2. **liveness under equivocation** (measured,
   `equivocation_threshold.sweep_cell(quorum=Q)`): the stall threshold
   eps*(Q) on the conflict DAG;
3. **safety under contested priors** (measured, `agreement_cell`): a
   50/50-split network (half the nodes initially prefer each lane of
   every double-spend) under equivocation/drop pressure — counting sets
   where two HONEST nodes finalize DIFFERENT winners.  Conflicting
   finalization is the protocol's one unforgivable outcome.

Measured finding (RESULTS.md "The quorum dial"): lowering the quorum
buys availability (a50: 0.56 @Q5 vs 0.80 @Q7 vs 0.92 @Q8) and an
apparently HIGHER equivocation stall threshold — but the residual
liveness under attack below Q=7 is partially UNSAFE.  With eps=0.05
equivocators and contested priors, Q=5 finalizes different winners on
different honest nodes in every probed trajectory (up to ~60% of
conflict sets when drops compound), and Q=6 does so in 2 of 3
trajectories (3-4 of 32 sets; adding drops pushes Q=6 into a full stall
instead, which is the safe failure).  Q=7 and Q=8 show ZERO conflicts
across every cell and seed — they fail SAFE by stalling, exactly the
Avalanche paper's scope (rogue double-spends may stay undecided forever
but are never finalized inconsistently).  The reference's 7-of-8 is
therefore the MINIMAL measured-safe quorum, and unanimity is dominated:
no safety gain over 7, a 2.3x latency multiplier at 90% availability,
and a LOWER stall threshold (one equivocator poisons any window).

Usage:
    python examples/quorum_dial.py [--nodes 512] [--txs 64]
        [--rounds 600] [--json-out examples/out/quorum_dial.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import jax.numpy as jnp
import numpy as np

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.models import dag
from go_avalanche_tpu.ops import voterecord as vr

QUORUM_GRID = (5, 6, 7, 8)
EPS_GRID = (0.025, 0.05, 0.075, 0.1, 0.15, 0.2, 0.3)
SAFETY_CELLS = ((0.0, 0.2), (0.05, 0.0), (0.05, 0.2))   # (eps, drop)
WINDOW = 8
# (window, quorum) pairs for the ratio-law extension: margin 1 and 2 at
# every window size the uint8 packing admits down to 4.
WINDOW_PAIRS = ((8, 7), (8, 6), (7, 6), (7, 5), (6, 5), (6, 4), (5, 4),
                (4, 3))


def c_q(a: float, quorum: int, window: int = WINDOW) -> float:
    """Bump rate per vote slot: P[Bin(window, a) >= quorum]."""
    return float(sum(math.comb(window, j) * a ** j * (1 - a) ** (window - j)
                     for j in range(quorum, window + 1)))


def a50(quorum: int, window: int = WINDOW) -> float:
    """Availability where the bump rate halves: C_Q(a50) = 1/2."""
    lo, hi = 0.0, 1.0
    for _ in range(60):
        mid = (lo + hi) / 2
        if c_q(mid, quorum, window) < 0.5:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2


def agreement_cell(n_nodes: int, n_txs: int, set_size: int, rounds: int,
                   quorum: int, eps: float, drop: float,
                   seed: int = 0, n_seeds: int = 1,
                   window: int = WINDOW) -> dict:
    """Contested-priors safety probe: half the nodes initially prefer
    each lane of every conflict set; count sets finalized INCONSISTENTLY
    across honest nodes (the safety violation) and the honest resolution
    fraction (the liveness of whatever survives).  With `n_seeds` > 1
    the probe repeats over independent trajectories (compile shared) and
    reports per-seed conflict counts — a zero-conflicts claim should
    rest on more than one realization."""
    per_seed = [_agreement_one(n_nodes, n_txs, set_size, rounds, quorum,
                               eps, drop, s, window)
                for s in range(seed, seed + n_seeds)]
    out = dict(per_seed[0])
    out["conflicting_sets_per_seed"] = [p["conflicting_sets"]
                                        for p in per_seed]
    out["conflicting_sets"] = max(out["conflicting_sets_per_seed"])
    out["both_lane_nodes"] = max(p["both_lane_nodes"] for p in per_seed)
    out["honest_resolved"] = round(
        float(np.mean([p["honest_resolved"] for p in per_seed])), 4)
    return out


def _agreement_one(n_nodes: int, n_txs: int, set_size: int, rounds: int,
                   quorum: int, eps: float, drop: float,
                   seed: int, window: int = WINDOW) -> dict:
    cs = jnp.arange(n_txs, dtype=jnp.int32) // set_size
    lane0 = (jnp.arange(n_txs) % set_size) == 0
    even_rows = (jnp.arange(n_nodes)[:, None] % 2) == 0
    init_pref = jnp.where(even_rows, lane0[None, :], ~lane0[None, :])
    # The adversary knobs only ride along when eps > 0 — at eps == 0
    # they are inert and the config validator rejects them (PR 13's
    # inert-knob rule); the (0, drop) safety cell measures drops alone.
    adv = (dict(flip_probability=1.0,
                adversary_strategy=AdversaryStrategy.EQUIVOCATE)
           if eps > 0 else {})
    cfg = AvalancheConfig(window=window, quorum=quorum,
                          byzantine_fraction=eps,
                          drop_probability=drop, **adv)
    state = dag.init(jax.random.key(seed), n_nodes, cs, cfg,
                     init_pref=init_pref)
    # eps enters only `init` (byzantine mask is state); pinning it at a
    # shared non-zero constant in the jitted cfg shares one compile across
    # the eps > 0 cells (see equivocation_threshold.sweep_cell — zero
    # would reject as an inert-knob config).  The eps == 0 cell keeps its
    # own knob-free config (a separate, equally shared compile key).
    run_cfg = (dataclasses.replace(cfg, byzantine_fraction=1.0)
               if eps > 0 else cfg)
    final, _ = jax.jit(dag.run_scan, static_argnames=("cfg", "n_rounds"))(
        state, run_cfg, rounds)
    conf = final.base.records.confidence
    fin_acc = np.asarray(jax.device_get(
        vr.has_finalized(conf, cfg) & vr.is_accepted(conf)))
    honest = ~np.asarray(final.base.byzantine)
    n_sets = n_txs // set_size
    by_set = fin_acc.reshape(n_nodes, n_sets, set_size)
    counts = dag.winners_per_set(fin_acc, set_size)
    resolved = (counts == 1) & honest[:, None]
    # A single honest node finalize-accepting BOTH lanes of a set is the
    # most direct double-spend finalization — count it as a conflict in
    # its own right, not only cross-node winner disagreement (a
    # counts>=2 node has no single "winner" and would otherwise drop out
    # of the comparison entirely).
    both = (counts >= 2) & honest[:, None]
    winner = by_set.argmax(2)
    conflicts = 0
    for s in range(n_sets):
        ws = winner[resolved[:, s], s]
        cross = len(ws) > 0 and ws.min() != ws.max()
        if cross or both[:, s].any():
            conflicts += 1
    return {"quorum": quorum, "eps": eps, "drop": drop,
            "honest_resolved": round(float(resolved[honest].mean()), 4),
            "both_lane_nodes": int(both.sum()),
            "conflicting_sets": conflicts, "n_sets": n_sets}


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=512)
    ap.add_argument("--txs", type=int, default=64)
    ap.add_argument("--conflict-size", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=600)
    ap.add_argument("--n-seeds", type=int, default=3,
                    help="independent trajectories per safety cell (the "
                    "zero-conflicts claim is a max over seeds)")
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin the CPU backend (jax.config route; a "
                    "JAX_PLATFORMS env var cannot override the axon "
                    "sitecustomize)")
    ap.add_argument("--json-out", type=str,
                    default="examples/out/quorum_dial.json")
    args = ap.parse_args(argv)
    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    from examples.equivocation_threshold import sweep_cell

    rows = []
    t0 = time.time()
    for quorum in QUORUM_GRID:
        # Liveness side: smallest eps that stalls (resolved < 0.5) under
        # full-rate equivocation, at this quorum.
        cells = []
        for eps in EPS_GRID:
            cell = sweep_cell(args.nodes, args.txs, args.conflict_size,
                              args.rounds, eps=eps, p=1.0,
                              strategy=AdversaryStrategy.EQUIVOCATE,
                              quorum=quorum)
            cells.append(cell)
            print(f"Q={quorum} eps={eps:<6} resolved={cell['resolved']}",
                  flush=True)
        stalled = [c["eps"] for c in cells if c["resolved"] < 0.5]
        # Safety side: contested priors under (eps, drop) pressure.
        safety = [agreement_cell(args.nodes, args.txs, args.conflict_size,
                                 args.rounds, quorum, eps, drop,
                                 n_seeds=args.n_seeds)
                  for eps, drop in SAFETY_CELLS]
        for sc in safety:
            print(f"Q={quorum} SAFETY eps={sc['eps']} drop={sc['drop']}: "
                  f"resolved={sc['honest_resolved']} "
                  f"conflicts={sc['conflicting_sets']}/{sc['n_sets']}",
                  flush=True)
        row = {
            "quorum": quorum,
            "a50": round(a50(quorum), 4),
            "latency_factor_a090": round(1.0 / c_q(0.9, quorum), 2),
            "latency_factor_a075": round(1.0 / c_q(0.75, quorum), 2),
            "equivocation_stall_eps": min(stalled) if stalled else None,
            "max_conflicting_sets": max(sc["conflicting_sets"]
                                        for sc in safety),
            "cells": cells,
            "safety": safety,
        }
        rows.append(row)
        print(f"Q={quorum}: a50={row['a50']} "
              f"1/C(0.9)={row['latency_factor_a090']} "
              f"stall_eps={row['equivocation_stall_eps']} "
              f"max_conflicts={row['max_conflicting_sets']}", flush=True)

    # --- ratio-law extension: the SAME safety probe across (window,
    # quorum) pairs at margin 1 and 2.  The organizing quantity is the
    # quorum RATIO Q/W, not the absolute margin W-Q: 3-of-4 has margin 1
    # yet violates grossly (ratio 0.75), while 5-of-6 (0.83) is clean.
    pair_rows = []
    for window, quorum in WINDOW_PAIRS:
        cell = agreement_cell(args.nodes, args.txs, args.conflict_size,
                              args.rounds, quorum, eps=0.05, drop=0.0,
                              n_seeds=args.n_seeds, window=window)
        # Liveness axis for the pair: stall threshold under full-rate
        # equivocation (eps shares one compile per pair — it only enters
        # init).
        stalled = []
        for eps in EPS_GRID:
            c = sweep_cell(args.nodes, args.txs, args.conflict_size,
                           args.rounds, eps=eps, p=1.0,
                           strategy=AdversaryStrategy.EQUIVOCATE,
                           quorum=quorum, window=window)
            if c["resolved"] < 0.5:
                stalled.append(eps)
        pair = {"window": window, "quorum": quorum,
                "ratio": round(quorum / window, 4),
                "margin": window - quorum,
                "a50": round(a50(quorum, window), 4),
                "equivocation_stall_eps": min(stalled) if stalled else None,
                "conflicting_sets_per_seed":
                    cell["conflicting_sets_per_seed"],
                "max_conflicting_sets": cell["conflicting_sets"],
                "n_sets": cell["n_sets"]}
        pair_rows.append(pair)
        print(f"W={window} Q={quorum} ratio={pair['ratio']} "
              f"margin={pair['margin']} "
              f"stall_eps={pair['equivocation_stall_eps']}: conflicts "
              f"{pair['conflicting_sets_per_seed']}", flush=True)

    result = {
        "config": {"nodes": args.nodes, "txs": args.txs,
                   "conflict_size": args.conflict_size,
                   "rounds": args.rounds, "window": WINDOW,
                   "safety_cells": list(SAFETY_CELLS),
                   "safety_n_seeds": args.n_seeds,
                   "backend": jax.devices()[0].platform},
        "rows": rows,
        "window_pairs": pair_rows,
        "elapsed_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
    with open(args.json_out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"artifact: {args.json_out} ({result['elapsed_s']}s)")
    return result


if __name__ == "__main__":
    main()
