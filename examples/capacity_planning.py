"""Capacity planning: max sustained offered load meeting a p99 finality SLO.

The question the live-traffic service mode (`go_avalanche_tpu/traffic.py`)
exists to answer: **what sustained tx/s does an N-node network absorb at
p99 finality latency < X rounds?**  This example sweeps offered load
(poisson `arrival_rate`) over the streaming backlog scheduler, reads the
IN-GRAPH finality-latency percentiles from the traffic plane's histogram,
cross-checks them against a host-side recomputation from the per-tx
outputs (`traffic.latency_percentiles_host` — must match BIT-FOR-BIT, the
acceptance check of the percentile machinery), and prints the highest
rate whose p99 meets the SLO with the whole backlog drained.

    python examples/capacity_planning.py
    python examples/capacity_planning.py --rates 4,8,16,32 --slo 40 \
        --nodes 128 --slots 64 --backpressure 0.7,0.95

Reading the table: as offered load approaches the window's drain
capacity (roughly slots / per-tx settle time), occupancy saturates and
latency climbs from the queueing delay — the classic hockey stick.  With
`--backpressure`, closed-loop admission caps occupancy, trading arrival
throttling (a longer drain) for bounded in-window latency.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import jax.numpy as jnp
import numpy as np

from go_avalanche_tpu import traffic as tf
from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.models import backlog as bl


def measure_rate(rate: float, n_nodes: int, slots: int, txs: int,
                 seed: int = 0, max_rounds: int = 20_000,
                 backpressure=None, finalization_score: int = 32) -> dict:
    """One offered-load point: stream `txs` backlog txs at `rate`/round
    until drained; return the drain stats with in-graph AND host-side
    percentiles (asserted identical)."""
    cfg = AvalancheConfig(arrival_mode="poisson", arrival_rate=float(rate),
                          arrival_backpressure=backpressure,
                          finalization_score=finalization_score,
                          gossip=False, max_element_poll=max(4096, slots))
    backlog = bl.make_backlog(jnp.arange(txs, dtype=jnp.int32))
    state = bl.init(jax.random.key(seed), n_nodes, slots, backlog, cfg)
    final = jax.jit(bl.run, static_argnames=("cfg", "max_rounds"))(
        state, cfg, max_rounds)
    out = jax.device_get(final.outputs)
    settled = np.asarray(out.settled)

    in_graph = tf.latency_percentiles(final.traffic)
    host = tf.latency_percentiles_host(
        np.asarray(jax.device_get(final.traffic.arrival_round)),
        np.asarray(out.settle_round), settled.astype(np.int64),
        cfg.arrival_latency_buckets)
    for k in ("count", "p50", "p99", "p999"):
        key = f"finality_latency_{k}"
        if in_graph[key] != host[key]:
            raise AssertionError(
                f"in-graph {key}={in_graph[key]} != host recomputation "
                f"{host[key]} at rate {rate} — the percentile planes "
                f"disagree")
    return {
        "rate": rate,
        "rounds": int(jax.device_get(final.sim.round)),
        "drained": bool(settled.all()),
        "settled_fraction": float(settled.mean()),
        **in_graph,
    }


def measure(rates, n_nodes: int = 64, slots: int = 32, txs: int = 2048,
            slo_p99: int = 48, seed: int = 0, max_rounds: int = 20_000,
            backpressure=None) -> dict:
    """Sweep offered load; the verdict is the max rate whose p99 meets
    the SLO with the backlog fully drained within the horizon."""
    rows = [measure_rate(r, n_nodes, slots, txs, seed=seed,
                         max_rounds=max_rounds, backpressure=backpressure)
            for r in rates]
    meeting = [row["rate"] for row in rows
               if row["drained"] and 0 <= row["finality_latency_p99"]
               <= slo_p99]
    return {
        "nodes": n_nodes, "slots": slots, "txs": txs,
        "slo_p99_rounds": slo_p99,
        "backpressure": backpressure,
        "rows": rows,
        "max_sustained_rate": max(meeting) if meeting else None,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rates", type=str, default="2,4,8,16,24",
                        help="comma-separated offered loads (tx/round)")
    parser.add_argument("--nodes", type=int, default=64)
    parser.add_argument("--slots", type=int, default=32)
    parser.add_argument("--txs", type=int, default=2048)
    parser.add_argument("--slo", type=int, default=48,
                        help="p99 finality-latency SLO in rounds")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--max-rounds", type=int, default=20_000)
    parser.add_argument("--backpressure", type=str, default=None,
                        metavar="LO,HI",
                        help="closed-loop admission occupancy fractions")
    parser.add_argument("--out", type=str, default=None,
                        help="also write the sweep as JSON here")
    args = parser.parse_args()

    rates = [float(r) for r in args.rates.split(",")]
    bp = (tuple(float(x) for x in args.backpressure.split(","))
          if args.backpressure else None)
    res = measure(rates, n_nodes=args.nodes, slots=args.slots,
                  txs=args.txs, slo_p99=args.slo, seed=args.seed,
                  max_rounds=args.max_rounds, backpressure=bp)

    print(f"capacity sweep: {args.nodes} nodes, {args.slots}-slot window, "
          f"{args.txs}-tx backlog, SLO p99 <= {args.slo} rounds"
          + (f", backpressure {bp}" if bp else ""))
    print(f"{'rate':>8} {'rounds':>8} {'drained':>8} {'p50':>6} "
          f"{'p99':>6} {'p999':>6}  verdict")
    for row in res["rows"]:
        ok = (row["drained"]
              and 0 <= row["finality_latency_p99"] <= args.slo)
        print(f"{row['rate']:>8g} {row['rounds']:>8} "
              f"{str(row['drained']):>8} "
              f"{row['finality_latency_p50']:>6} "
              f"{row['finality_latency_p99']:>6} "
              f"{row['finality_latency_p999']:>6}  "
              f"{'MEETS SLO' if ok else 'violates SLO'}")
    if res["max_sustained_rate"] is None:
        print("no swept rate meets the SLO — lower the load or raise "
              "the window")
    else:
        print(f"max sustained arrival rate meeting p99 <= {args.slo}: "
              f"{res['max_sustained_rate']:g} tx/round "
              f"(in-graph percentiles == host recomputation, bit-for-bit)")
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(res, fh, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
