"""Protocol-family comparison — the Avalanche paper's figs. 2-4 workload.

Runs Slush, Snowflake, and Snowball side by side on identical networks
(same sizes, same seeds, same fault mix) and reports, per protocol x
byzantine fraction:

  * convergence — final agreement fraction (slush) / fraction of honest
    nodes decided (snowflake, snowball);
  * latency — median rounds to decision;
  * safety — count of runs where two honest nodes decided opposite values
    (`utils/metrics.safety_failure`), the paper's safety-failure event.

The qualitative shape to expect (and what the defaults show): Slush drifts
with adversarial noise (memoryless), Snowflake decides but its one counter
is slow under faults, Snowball's confidence makes it both faster and more
stable — which is why the reference implements Snowball (`vote.go:24-98`).

Measured on a v5e (512 nodes, k=8, always-lying FLIP adversaries): honest
networks decide at ~137 rounds (snowflake) vs ~23 (snowball); at 10-20%
byzantine, snowball still decides in 26-38 rounds while snowflake's
*consecutive*-success counter cannot reach beta=128 at all (P ~ p^128) —
use `--beta 20` for the paper's snowflake operating regime, where it
decides at ~250 rounds vs snowball's ~10. Zero safety failures in all
cells.

    python examples/family_curves.py
    python examples/family_curves.py --nodes 1024 --byzantine 0.0,0.1,0.2 \
        --seeds 5 --adversary oppose_majority --json
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.models import family, snowball
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.utils import metrics


def run_slush(key, n, cfg, m_rounds):
    state = family.slush_init(key, n, cfg, yes_fraction=0.5)
    final, _ = jax.jit(family.slush_run,
                       static_argnames=("cfg", "m_rounds"))(
        state, cfg, m_rounds)
    colors = np.asarray(jax.device_get(final.color))
    honest = ~np.asarray(jax.device_get(final.byzantine))
    agree = max(colors[honest].mean(), 1 - colors[honest].mean())
    # Slush never "decides"; report agreement after m rounds. No safety
    # event is defined for it (nothing is irreversible).
    return {"decided_fraction": float(agree), "rounds": m_rounds,
            "safety_failure": False}


def run_snowflake(key, n, cfg, max_rounds):
    state = family.snowflake_init(key, n, cfg, yes_fraction=0.5)
    final = jax.jit(family.snowflake_run,
                    static_argnames=("cfg", "max_rounds"))(
        state, cfg, max_rounds)
    acc_at = np.asarray(jax.device_get(final.accepted_at))
    colors = np.asarray(jax.device_get(final.color))
    honest = ~np.asarray(jax.device_get(final.byzantine))
    decided = acc_at >= 0
    return {
        "decided_fraction": float(decided[honest].mean()),
        "rounds": (float(np.median(acc_at[decided & honest]))
                   if (decided & honest).any() else None),
        "safety_failure": metrics.safety_failure(decided, colors, honest),
    }


def run_snowball(key, n, cfg, max_rounds):
    state = snowball.init(key, n, cfg, yes_fraction=0.5)
    final = jax.jit(snowball.run, static_argnames=("cfg", "max_rounds"))(
        state, cfg, max_rounds)
    fin = np.asarray(jax.device_get(
        vr.has_finalized(final.records.confidence, cfg)))
    pref = np.asarray(jax.device_get(
        vr.is_accepted(final.records.confidence)))
    fin_at = np.asarray(jax.device_get(final.finalized_at))
    honest = ~np.asarray(jax.device_get(final.byzantine))
    return {
        "decided_fraction": float(fin[honest].mean()),
        "rounds": (float(np.median(fin_at[fin & honest]))
                   if (fin & honest).any() else None),
        "safety_failure": metrics.safety_failure(fin, pref, honest),
    }


PROTOCOLS = {"slush": run_slush, "snowflake": run_snowflake,
             "snowball": run_snowball}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=512)
    parser.add_argument("--byzantine", type=str, default="0.0,0.1,0.2")
    parser.add_argument("--adversary", type=str, default="flip",
                        choices=[s.value for s in AdversaryStrategy])
    parser.add_argument("--seeds", type=int, default=3,
                        help="independent runs per cell")
    parser.add_argument("--max-rounds", type=int, default=2000,
                        help="round budget (slush runs exactly 1/10 of it)")
    parser.add_argument("--beta", type=int, default=128,
                        help="snowflake/snowball decision threshold")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args()

    byz_fracs = [float(b) for b in args.byzantine.split(",")]
    rows = []
    for byz in byz_fracs:
        # The strategy knob rides along only when byz > 0 — at the
        # honest-baseline 0.0 point it is inert and the config
        # validator rejects it (PR 13's inert-knob rule).
        adv = (dict(flip_probability=1.0,
                    adversary_strategy=AdversaryStrategy(args.adversary))
               if byz > 0 else {})
        cfg = AvalancheConfig(byzantine_fraction=byz,
                              finalization_score=args.beta, **adv)
        for name, runner in PROTOCOLS.items():
            budget = (args.max_rounds // 10 if name == "slush"
                      else args.max_rounds)
            t0 = time.perf_counter()
            per_seed = [runner(jax.random.key(s), args.nodes, cfg, budget)
                        for s in range(args.seeds)]
            decided = [r["decided_fraction"] for r in per_seed]
            rounds = [r["rounds"] for r in per_seed
                      if r["rounds"] is not None]
            rows.append({
                "protocol": name,
                "byzantine": byz,
                "decided_fraction_mean": round(float(np.mean(decided)), 4),
                "rounds_median": (round(float(np.median(rounds)), 1)
                                  if rounds else None),
                "safety_failures": sum(r["safety_failure"]
                                       for r in per_seed),
                "seeds": args.seeds,
                "elapsed_s": round(time.perf_counter() - t0, 2),
            })

    if args.json:
        print(json.dumps(rows, indent=1))
        return
    hdr = (f"{'protocol':>10} {'byz':>5} {'decided':>8} {'rounds':>7} "
           f"{'safety_fail':>11} {'secs':>6}")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        rounds = "—" if r["rounds_median"] is None else r["rounds_median"]
        print(f"{r['protocol']:>10} {r['byzantine']:>5.2f} "
              f"{r['decided_fraction_mean']:>8.3f} {rounds:>7} "
              f"{r['safety_failures']:>8}/{r['seeds']:<2} "
              f"{r['elapsed_s']:>6.2f}")


if __name__ == "__main__":
    main()
