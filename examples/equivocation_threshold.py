"""Locate the DAG liveness threshold under the equivocation adversary.

RESULTS.md records that 20% per-target equivocators stall conflict-set
resolution completely (the canonical Avalanche liveness attack), while 20%
FLIP liars are simply out-voted.  This sweep turns that single observation
into a threshold map: byzantine_fraction (eps) x flip_probability (p) on
the conflict-DAG model, for both EQUIVOCATE and FLIP, measuring the
fraction of (honest node, conflict set) pairs resolved within a round
budget.

Sweep economics: eps only enters at `init` (the byzantine mask is sim
*state*), so the grid costs one XLA compile per distinct p per strategy —
not per cell.

The quantity that organizes the result is the **effective lie rate**
q = eps * p: the probability that any one sampled response is adversarial.
For the winner lane of a set, an equivocator answers yes with prob 1/2, so
the per-vote yes-probability seen by an honest node is 1 - q/2 and a
window (8) needs quorum (7) yes bits to bump confidence once
(`vote.go:55-69`).  A conclusive-NO needs >= 7 of 8 lying-no bits —
vanishing for small q — so the first-order stall mechanism is not
preference flipping but *chit starvation on the losers*: equivocators feed
the losing lanes conclusive-yes runs, the losers' confidence words rise,
`preferred_in_set` ties break differently on different nodes, and honest
voters stop agreeing which lane to support (the votes-own-preference
coupling).  The empirical threshold below is therefore far lower than the
binomial chit-starvation bound P[Bin(8, 1-q/2) >= 7], and THAT is the
finding: the adversary attacks the metastable preference loop, not the
vote window.

Usage:
    python examples/equivocation_threshold.py [--nodes 512] [--txs 64]
        [--rounds 600] [--json-out examples/out/equivocation_threshold.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import jax.numpy as jnp
import numpy as np

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.models import dag
from go_avalanche_tpu.ops import voterecord as vr

EPS_GRID = (0.025, 0.05, 0.075, 0.1, 0.15, 0.2, 0.3)
P_GRID = (0.25, 0.5, 0.75, 1.0)


def resolved_fraction(state: dag.DagSimState, cfg: AvalancheConfig,
                      set_size: int) -> float:
    """Fraction of (honest live node, set) pairs with exactly one
    finalized-accepted winner."""
    conf = state.base.records.confidence
    fin_acc = np.asarray(jax.device_get(
        vr.has_finalized(conf, cfg) & vr.is_accepted(conf)))
    honest = np.asarray(jax.device_get(
        jnp.logical_not(state.base.byzantine) & state.base.alive))
    winners = dag.winners_per_set(fin_acc, set_size)
    return float((winners[honest] == 1).mean()) if honest.any() else 0.0


def sweep_cell(n_nodes: int, n_txs: int, set_size: int, rounds: int,
               eps: float, p: float, strategy: AdversaryStrategy,
               seed: int = 0, quorum: int = 7, window: int = 8) -> dict:
    """One (eps, p, strategy) cell.  `quorum`/`window` sweep the vote
    window's conclusiveness rule (default = the protocol's 7-of-8,
    `vote.go:55,58`) — used by `examples/quorum_dial.py` to measure how
    the stall threshold moves with the quorum and window."""
    cfg = AvalancheConfig(byzantine_fraction=eps, flip_probability=p,
                          adversary_strategy=strategy, quorum=quorum,
                          window=window)
    cs = jnp.arange(n_txs, dtype=jnp.int32) // set_size
    state = dag.init(jax.random.key(seed), n_nodes, cs, cfg)
    # eps only enters `init` (the byzantine mask is STATE); pin it at a
    # shared non-zero constant in the jitted config so all eps cells share
    # one compile per (strategy, p) — without this the static cfg hash
    # retraces the 600-round scan per cell.  (Non-zero because the config
    # validator rejects adversary knobs with byzantine_fraction == 0 as
    # inert — here the byzantine mask rides the state, not the config.)
    run_cfg = dataclasses.replace(cfg, byzantine_fraction=1.0)
    final, _ = jax.jit(dag.run_scan, static_argnames=("cfg", "n_rounds"))(
        state, run_cfg, rounds)
    frac = resolved_fraction(final, cfg, set_size)
    return {"eps": eps, "p": p, "q": round(eps * p, 4),
            "strategy": strategy.value, "resolved": round(frac, 4)}


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=512)
    ap.add_argument("--txs", type=int, default=64)
    ap.add_argument("--conflict-size", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=600)
    ap.add_argument("--json-out", type=str,
                    default="examples/out/equivocation_threshold.json")
    args = ap.parse_args(argv)

    cells = []
    t0 = time.time()
    for strategy in (AdversaryStrategy.EQUIVOCATE, AdversaryStrategy.FLIP):
        for p in P_GRID:
            for eps in EPS_GRID:
                cell = sweep_cell(args.nodes, args.txs, args.conflict_size,
                                  args.rounds, eps, p, strategy)
                cells.append(cell)
                print(f"{strategy.value:>12} eps={eps:<5} p={p:<4} "
                      f"q={cell['q']:<6} resolved={cell['resolved']}",
                      flush=True)

    # Threshold per (strategy, p): smallest eps with resolved < 0.5.
    thresholds = {}
    for strategy in ("equivocate", "flip"):
        for p in P_GRID:
            col = [c for c in cells
                   if c["strategy"] == strategy and c["p"] == p]
            stalled = [c["eps"] for c in col if c["resolved"] < 0.5]
            thresholds[f"{strategy}_p{p}"] = min(stalled) if stalled else None

    result = {
        "config": {"nodes": args.nodes, "txs": args.txs,
                   "conflict_size": args.conflict_size,
                   "rounds": args.rounds,
                   "backend": jax.devices()[0].platform},
        "cells": cells,
        "stall_threshold_eps": thresholds,
        "elapsed_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
    with open(args.json_out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"\nthresholds (smallest eps with resolved<0.5): {thresholds}")
    print(f"artifact: {args.json_out} ({result['elapsed_s']}s)")
    return result


if __name__ == "__main__":
    main()
