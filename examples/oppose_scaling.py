"""OPPOSE_MAJORITY: the metastability threshold shrinks as ~1/sqrt(n).

The third adversary strategy (`ops/adversary.py` OPPOSE_MAJORITY — lie
with the current global minority color) is the Avalanche paper's
metastability adversary: against a 50/50-split single-decree Snowball
network it tries to HOLD the tie forever.  The physics prediction is a
square-root law: the honest network's per-round random drift moves the
color balance by ~sqrt(n) nodes, while the adversary can push back
~eps*n votes, so holding the tie needs eps*n >~ sqrt(n), i.e. the stall
threshold falls as

    eps*(n) ~ c / sqrt(n)

— LARGER networks are EASIER to keep split, the opposite intuition from
the byzantine-fraction bounds of classical BFT (and the opposite
direction from the equivocation threshold, which is n-independent: it
attacks per-set preference coupling, not global drift).

This study measures eps*(n) by bisection (honest finalized fraction
within a round budget, averaged over seeds; byzantine_fraction is part
of the jitted static config, so each probe point compiles — Snowball's
[n]-scalar state keeps that the dominant but affordable cost) and fits
log2 eps* vs log2 n.
Measured result (RESULTS.md "Metastability scaling"): fitted slope
-0.44 with R^2 0.99 across a 256x size range (256 -> 65536 nodes,
eps* 0.215 -> 0.021) — the square-root law holds (the slightly shallow
slope is the finite round budget: bigger networks get proportionally
fewer drift excursions per budget).  Extrapolated to the north-star
100k-node network the threshold is ~1.7%: at fleet scale the OPPOSE
adversary needs only ~2% of nodes to freeze a contested decree, an
order of magnitude below its small-network threshold — the binding
liveness constraint at scale, and a scaling behavior the reference
could never have measured single-process.

Usage:
    python examples/oppose_scaling.py [--rounds 400] [--seeds 3]
        [--json-out examples/out/oppose_scaling.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")  # allow running from the repo root

import jax
import numpy as np

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.models import snowball as sb
from go_avalanche_tpu.ops import voterecord as vr

N_GRID = (256, 1024, 4096, 16384, 65536)


def live_fraction(n: int, eps: float, rounds: int, seeds: int) -> float:
    """Mean honest finalized fraction over `seeds` runs."""
    cfg = AvalancheConfig(byzantine_fraction=eps, flip_probability=1.0,
                          adversary_strategy=AdversaryStrategy.OPPOSE_MAJORITY)
    out = []
    for s in range(seeds):
        st = sb.init(jax.random.key(s), n, cfg, yes_fraction=0.5)
        fin = jax.jit(sb.run, static_argnames=("cfg", "max_rounds"))(
            st, cfg, rounds)
        f = np.asarray(jax.device_get(
            vr.has_finalized(fin.records.confidence, cfg)))
        byz = np.asarray(fin.byzantine)
        out.append(float(f[~byz].mean()))
    return float(np.mean(out))


def bisect_threshold(n: int, rounds: int, seeds: int,
                     lo: float = 0.005, hi: float = 0.45,
                     steps: int = 7) -> dict:
    """Smallest eps with live fraction < 0.5, to grid resolution.

    NOTE: byzantine_fraction is static in the jitted config here (it
    participates in cfg's hash), so each probe point compiles; Snowball
    state is [n] scalars and the compiles dominate the runtime — steps
    is kept small and the bracket tight.
    """
    probes = []
    f_lo = live_fraction(n, lo, rounds, seeds)
    f_hi = live_fraction(n, hi, rounds, seeds)
    probes += [{"eps": lo, "live": round(f_lo, 4)},
               {"eps": hi, "live": round(f_hi, 4)}]
    if f_lo < 0.5:
        # Stalled even at the floor: the threshold is only known to be
        # <= lo.  Censored — must NOT enter the power-law fit as a
        # measured point (it would silently flatten the slope).
        return {"n": n, "eps_star": lo, "censored_at_floor": True,
                "bracket": [0.0, lo], "probes": probes}
    if f_hi >= 0.5:       # live even at the ceiling
        return {"n": n, "eps_star": None, "bracket": [hi, 1.0],
                "probes": probes}
    for _ in range(steps):
        mid = (lo + hi) / 2
        f_mid = live_fraction(n, mid, rounds, seeds)
        # Record the EXACT eps used: snowball.init rounds eps*n to a
        # byzantine count, so a display-rounded eps can produce a
        # different trajectory and break artifact reproduction.
        probes.append({"eps": mid, "live": round(f_mid, 4)})
        if f_mid >= 0.5:
            lo = mid
        else:
            hi = mid
        print(f"  n={n} bracket=({lo:.4f}, {hi:.4f})", flush=True)
    return {"n": n, "eps_star": round((lo + hi) / 2, 5),
            "bracket": [round(lo, 5), round(hi, 5)], "probes": probes}


def fit_power_law(points: list) -> dict:
    """Least-squares slope of log2(eps*) vs log2(n) with R^2."""
    xs = np.log2([p["n"] for p in points])
    ys = np.log2([p["eps_star"] for p in points])
    slope, intercept = np.polyfit(xs, ys, 1)
    pred = slope * xs + intercept
    ss_res = float(((ys - pred) ** 2).sum())
    ss_tot = float(((ys - ys.mean()) ** 2).sum())
    return {"slope": round(float(slope), 4),
            "intercept": round(float(intercept), 4),
            "r2": round(1 - ss_res / ss_tot, 4) if ss_tot else 1.0,
            "eps_star_at_100k": round(
                float(2 ** (slope * np.log2(100_000) + intercept)), 5)}


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=400)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin the CPU backend (jax.config route; a "
                    "JAX_PLATFORMS env var cannot override the axon "
                    "sitecustomize)")
    ap.add_argument("--json-out", type=str,
                    default="examples/out/oppose_scaling.json")
    args = ap.parse_args(argv)
    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    t0 = time.time()
    rows = []
    for n in N_GRID:
        row = bisect_threshold(n, args.rounds, args.seeds)
        rows.append(row)
        print(f"n={n}: eps* = {row['eps_star']} "
              f"(bracket {row['bracket']})", flush=True)

    fit_pts = [r for r in rows if r["eps_star"] is not None
               and not r.get("censored_at_floor")]
    fit = fit_power_law(fit_pts) if len(fit_pts) >= 3 else None
    result = {
        "config": {"rounds": args.rounds, "seeds": args.seeds,
                   "backend": jax.devices()[0].platform},
        "rows": rows,
        "fit": fit,
        "elapsed_s": round(time.time() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
    with open(args.json_out, "w") as f:
        json.dump(result, f, indent=1)
    if fit:
        print(f"\nlog2 eps* = {fit['slope']} * log2 n + {fit['intercept']}"
              f"  (R^2 {fit['r2']}; sqrt-law predicts slope -0.5); "
              f"extrapolated eps* at 100k nodes: {fit['eps_star_at_100k']}")
    print(f"artifact: {args.json_out} ({result['elapsed_s']}s)")
    return result


if __name__ == "__main__":
    main()
