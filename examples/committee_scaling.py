"""Committee scaling: finality vs registry size at fixed committee k.

The stake subsystem's acceptance study (`go_avalanche_tpu/stake.py`):
Avalanche's per-query sampling is formally a stake-weighted COMMITTEE
draw ("Committee Selection is More Similar Than You Think", PAPERS.md
arXiv 1904.09839) — so the protocol-relevant scale question is **how
does finality degrade as the registry grows while the committee size k
stays fixed?**  This example sweeps the node count N under a zipf stake
distribution, runs a Monte-Carlo fleet per point
(`go_avalanche_tpu/fleet.py` — contested priors, so the network must
genuinely converge), and prints the finality-vs-N curve with Wilson
confidence intervals plus the safety P-estimates.

Each point runs TWICE: through the flat stake-CDF sampler
(`n_clusters=1`) and through the two-level HIERARCHICAL engine
(`n_clusters>1`, `ops/sampling.sample_peers_hierarchical`).  The two
are bit-parity twins of one distribution, so every fleet statistic
must come out IDENTICAL — asserted per point, which makes this example
the end-to-end machine check that the committee engine swap changes
the program, never the trajectory.

    python examples/committee_scaling.py
    python examples/committee_scaling.py --sizes 48,96,192 --fleet 64 \
        --zipf-s 1.2 --clusters 6
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # allow running from the repo root

from go_avalanche_tpu import fleet as fl
from go_avalanche_tpu.config import AvalancheConfig


def sweep_point(n_nodes: int, clusters: int, fleet: int, rounds: int,
                k: int, zipf_s: float, txs: int, seed: int) -> dict:
    """One (registry size, sampling engine) fleet: Wilson-CI finality
    and safety estimates over `fleet` contested avalanche trials."""
    cfg = AvalancheConfig(stake_mode="zipf", stake_zipf_s=zipf_s,
                          n_clusters=clusters, k=k,
                          finalization_score=16)
    res = fl.run_fleet("avalanche", cfg, fleet=fleet, n_nodes=n_nodes,
                       n_txs=txs, n_rounds=rounds, seed=seed,
                       contested=True)
    return {
        "nodes": n_nodes,
        "engine": "flat" if clusters == 1 else f"hier{clusters}",
        "p_settled": res.p_settled,
        "settled_ci": list(res.settled_ci),
        "finality_mean": res.finality_mean,
        "finality_ci": (None if res.finality_ci is None
                        else list(res.finality_ci)),
        "p_violation": res.p_violation,
        "violation_ci": list(res.violation_ci),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sizes", type=str, default="48,96,192",
                        help="comma-separated registry sizes N to sweep")
    parser.add_argument("--k", type=int, default=8,
                        help="committee size (fixed across the sweep)")
    parser.add_argument("--fleet", type=int, default=32,
                        help="Monte-Carlo trials per point")
    parser.add_argument("--rounds", type=int, default=200,
                        help="horizon per trial")
    parser.add_argument("--txs", type=int, default=8,
                        help="contested txs per trial")
    parser.add_argument("--zipf-s", type=float, default=1.0,
                        help="stake concentration exponent")
    parser.add_argument("--clusters", type=int, default=4,
                        help="cluster count of the hierarchical engine")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON line per point instead of "
                             "the table")
    args = parser.parse_args()

    sizes = [int(s) for s in args.sizes.split(",")]
    if not args.json:
        print(f"# committee scaling: k={args.k}, zipf s={args.zipf_s:g}, "
              f"{args.fleet} trials/point, horizon {args.rounds}")
        print(f"{'N':>7} {'engine':>7} {'P(settled)':>21} "
              f"{'E[finality round]':>24} {'P(violation)':>16}")
    rows = []
    for n in sizes:
        flat = sweep_point(n, 1, args.fleet, args.rounds, args.k,
                           args.zipf_s, args.txs, args.seed)
        hier = sweep_point(n, args.clusters, args.fleet, args.rounds,
                           args.k, args.zipf_s, args.txs, args.seed)
        # The engine-parity acceptance check: the hierarchical draw is
        # bit-identical to the flat CDF on the same key, so the whole
        # fleet's statistics must match exactly.
        for key in ("p_settled", "finality_mean", "p_violation"):
            assert flat[key] == hier[key], (
                f"engine divergence at N={n} {key}: flat={flat[key]} "
                f"hier={hier[key]} — the hierarchical sampler no "
                f"longer matches the flat stake CDF")
        rows.extend([flat, hier])
        for row in (flat, hier):
            if args.json:
                print(json.dumps(row))
                continue
            lo, hi = row["settled_ci"]
            fin = ("--" if row["finality_mean"] is None else
                   f"{row['finality_mean']:8.1f} "
                   f"[{row['finality_ci'][0]:.1f}, "
                   f"{row['finality_ci'][1]:.1f}]")
            vlo, vhi = row["violation_ci"]
            print(f"{row['nodes']:>7} {row['engine']:>7} "
                  f"{row['p_settled']:>7.3f} [{lo:.3f}, {hi:.3f}] "
                  f"{fin:>24} "
                  f"{row['p_violation']:>6.3f} [{vlo:.3f}, {vhi:.3f}]")
    if not args.json:
        settled = [r for r in rows if r["finality_mean"] is not None
                   and r["engine"] == "flat"]
        if len(settled) >= 2:
            lo_n, hi_n = settled[0], settled[-1]
            print(f"# finality moved {lo_n['finality_mean']:.1f} -> "
                  f"{hi_n['finality_mean']:.1f} rounds from N="
                  f"{lo_n['nodes']} to N={hi_n['nodes']} at fixed "
                  f"k={args.k} (flat == hierarchical, asserted per "
                  f"point)")


if __name__ == "__main__":
    main()
