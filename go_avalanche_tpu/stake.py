"""The stake subsystem: jit-static stake distributions + registry draws.

"Committee Selection is More Similar Than You Think" (PAPERS.md,
arXiv 1904.09839) shows Avalanche's per-query peer sampling is formally
a stake-weighted committee draw; real deployments weight nodes by stake,
not uniformly.  This module realizes `cfg.stake_mode` into a per-node
stake vector and provides the weighted-without-replacement registry
draw behind the node-axis streaming scheduler
(`models/node_stream.py`):

  * **`node_stake`** — the jit-static realization: "uniform" (equal
    stake — the weighted machinery with a flat distribution), "zipf"
    (node i holds ``1/(i+1)**s``; id 0 richest, and with
    ``byzantine_fraction > 0`` the adversary holds the TOP stake — the
    worst case), or "explicit" (the validated `cfg.stake_weights`
    vector).  The vector is FOLDED INTO the `latency_weight`
    sampling-propensity plane at init (`models/avalanche.init`), so the
    peer draw dispatch (`ops/sampling.draw_peers`) sees one composed
    propensity plane — stake x latency weights x aliveness — and the
    inverse-CDF machinery generalizes unchanged.  "off" returns None
    (statically absent: every archived hlo pin byte-identical,
    machine-checked by `benchmarks/hlo_pin.py --verify-off-path`).
  * **`draw_working_set`** — EXACT stake-proportional sampling without
    replacement via the Gumbel top-k trick (perturbed log-stake,
    ``lax.top_k``): the distribution over W-subsets is successive
    weighted draws without replacement, which is precisely how a
    bounded active-node working set should be drawn from an R-entry
    registry (DAG-Sword's resident-working-set regime, PAPERS.md
    arXiv 2311.04638).  Zero-stake (or masked) entries carry a -inf
    score and are never drawn.

Everything here is a pure function of (config, shapes[, key]) — no
state, no host round-trips — so it composes with `vmap` (the
Monte-Carlo fleet sweeps `stake_zipf_s` as a phase axis) and with the
sharded drivers (replicated stake planes draw identically everywhere).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from go_avalanche_tpu.config import AvalancheConfig


def stake_enabled(cfg: AvalancheConfig) -> bool:
    """Static: is the stake subsystem on for this config?"""
    return cfg.stake_mode != "off"


def registry_enabled(cfg: AvalancheConfig) -> bool:
    """Static: is the node-axis streaming registry on
    (`models/node_stream.py`)?"""
    return cfg.registry_nodes > 0


def node_stake(cfg: AvalancheConfig,
               n_nodes: int) -> Optional[jax.Array]:
    """float32 ``[n_nodes]`` per-node stake realized from the config;
    None (statically) when `cfg.stake_mode` is "off".

    jit-static: a pure function of (config, n_nodes), constant under
    `vmap` — every fleet trial at one config point sees the same stake
    vector (trial-to-trial variation is the PRNG's, not the
    distribution's).  An "explicit" vector whose length does not match
    `n_nodes` raises at trace time with both lengths — the registry
    case is already caught at config construction.
    """
    if cfg.stake_mode == "off":
        return None
    if cfg.stake_mode == "uniform":
        return jnp.ones((n_nodes,), jnp.float32)
    if cfg.stake_mode == "zipf":
        ranks = jnp.arange(1, n_nodes + 1, dtype=jnp.float32)
        return (1.0 / ranks ** jnp.float32(cfg.stake_zipf_s)).astype(
            jnp.float32)
    # explicit — length re-checked here because the config cannot know
    # the node count (only the registry spelling pins it up front).
    if len(cfg.stake_weights) != n_nodes:
        raise ValueError(
            f"stake_mode 'explicit' needs one stake per node: "
            f"stake_weights has {len(cfg.stake_weights)} entries for "
            f"{n_nodes} nodes")
    return jnp.asarray(cfg.stake_weights, jnp.float32)


def draw_working_set(
    key: jax.Array,
    stake: jax.Array,
    w: int,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Draw `w` DISTINCT registry ids stake-proportionally (exact
    weighted sampling without replacement, Gumbel top-k).

    Returns ``(ids [w], valid [w])`` in descending perturbed-score
    order: `valid[i]` is False where fewer than `w` drawable entries
    exist (zero stake, or excluded by `mask`) — those slots must not be
    consumed.  `mask` (bool ``[R]``, True = drawable) restricts the
    pool; the node-stream churn pass excludes resident rows with it.
    """
    stake = jnp.asarray(stake, jnp.float32)
    drawable = stake > 0.0
    if mask is not None:
        drawable = drawable & mask
    log_stake = jnp.where(drawable, jnp.log(jnp.maximum(stake, 1e-38)),
                          -jnp.inf)
    score = log_stake + jax.random.gumbel(key, stake.shape)
    top, ids = jax.lax.top_k(score, w)
    return ids.astype(jnp.int32), top > -jnp.inf
