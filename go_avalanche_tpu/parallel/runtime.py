"""Multi-host / multi-slice runtime entry points.

The reference has no distributed backend at all (SURVEY.md §5: no
NCCL/MPI/Gloo; its "network hop" is a mutex-guarded method call,
`examples/basic-preconcensus/main.go:168-193`).  This module is the
scale-out half of ours: process-group bring-up via `jax.distributed` and
mesh construction that is aware of the two interconnect tiers —

  ICI  (intra-slice, fast):   carries the "nodes" axis, the only axis with
                              per-round collectives (packed-preference
                              all-gather, telemetry psum).
  DCN  (inter-slice, slower): carries the "txs" axis, which needs no
                              per-round collectives at all (a vote for
                              target t only touches column t), so slices
                              only talk when aggregating final statistics.

On a single host this degrades gracefully to `mesh.make_mesh`, so the same
driver script runs from a laptop CPU (with
``--xla_force_host_platform_device_count``) to a multi-slice pod.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS, make_mesh

_initialized = False


def initialize_runtime(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Bring up the multi-host process group; returns this process's index.

    Single-process (all args None): no-op, returns 0.  Multi-host: calls
    `jax.distributed.initialize` exactly once (idempotent thereafter) so
    every host sees the global device set before any mesh is built.
    """
    global _initialized
    if coordinator_address is None and num_processes is None:
        return jax.process_index()
    if not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return jax.process_index()


def build_on_mesh(make_fn, mesh: Mesh, specs):
    """Construct a state pytree directly into its mesh placement.

    Multi-host-safe replacement for the `shard_state` pattern
    (`jax.device_put` onto a sharding that spans other processes is
    illegal): `make_fn` is traced once and compiled with the target
    shardings as `out_shardings`, so every process materializes exactly
    its addressable shards — no host-global array ever exists.
    `make_fn` must be deterministic (same trace on every process) and
    `specs` a matching pytree of `PartitionSpec`s.
    """
    from jax.sharding import NamedSharding

    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    return jax.jit(make_fn, out_shardings=shardings)()


def _slice_index(d: jax.Device) -> int:
    """Slice id of a device; 0 when the platform has no slice concept."""
    return getattr(d, "slice_index", 0) or 0


def group_devices_by_slice(
    devices: Optional[Sequence[jax.Device]] = None,
) -> list[list[jax.Device]]:
    """Devices grouped by slice (DCN domain), each group in stable id order."""
    if devices is None:
        devices = jax.devices()
    groups: dict[int, list[jax.Device]] = {}
    for d in sorted(devices, key=lambda d: (_slice_index(d), d.id)):
        groups.setdefault(_slice_index(d), []).append(d)
    return [groups[s] for s in sorted(groups)]


def make_runtime_mesh(
    n_tx_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Interconnect-aware ``(nodes, txs)`` mesh over all slices.

    Layout rule: the txs axis spans slices (DCN) because it never
    communicates per round; the nodes axis stays inside a slice (ICI)
    because it all-gathers every round.  With `n_tx_shards=None` the txs
    axis gets exactly one shard per slice.  On a single slice (or CPU) this
    is `make_mesh` with the same arithmetic.

    The returned mesh uses the same axis names as `mesh.make_mesh`, so
    `parallel.sharded` works unchanged on it.
    """
    groups = group_devices_by_slice(devices)
    n_slices = len(groups)
    per_slice = len(groups[0])
    if any(len(g) != per_slice for g in groups):
        raise ValueError("slices have unequal device counts: "
                         f"{[len(g) for g in groups]}")
    if n_tx_shards is None:
        n_tx_shards = n_slices
    if n_slices == 1:
        return make_mesh(n_tx_shards=n_tx_shards, devices=groups[0])

    if n_tx_shards % n_slices:
        raise ValueError(
            f"n_tx_shards={n_tx_shards} must be a multiple of the slice "
            f"count {n_slices} so the DCN boundary falls between tx shards")
    tx_per_slice = n_tx_shards // n_slices
    if per_slice % tx_per_slice:
        raise ValueError(
            f"{per_slice} devices/slice not divisible by {tx_per_slice} "
            "tx shards/slice")
    node_shards = per_slice // tx_per_slice
    # [n_slices, node_shards, tx_per_slice] -> (nodes, txs) with the txs
    # axis ordered slice-major, so crossing a tx-shard boundary crosses DCN
    # only every `tx_per_slice` shards.
    arr = np.asarray([g for g in groups]).reshape(
        n_slices, node_shards, tx_per_slice)
    dev_array = np.transpose(arr, (1, 0, 2)).reshape(node_shards, n_tx_shards)
    return Mesh(dev_array, (NODES_AXIS, TXS_AXIS))
