"""Mesh-sharded streaming conflict-DAG: the north-star workload, sharded.

`models/streaming_dag` re-expressed under `jax.shard_map` over the
``(nodes, txs)`` mesh — the composition of `parallel/sharded_dag` (the
conflicted inner round; reused verbatim as `sharded_dag._local_round`) and
`parallel/sharded_backlog` (the streaming scheduler's collectives, lifted
from tx granularity to set granularity):

  * **settle test**    — `psum` over the nodes axis of the per-set
    "some (node, member) still pollable" bit;
  * **admission rank** — exclusive prefix over tx shards (all-gather of one
    scalar per shard) so free set-slots across shards take backlog sets in
    global score order;
  * **output merge**   — retiring shards row-scatter member outcomes into
    zero-init ``[S_b, c]`` planes, merged by a `psum` over the txs axis
    (each set occupies exactly one set-slot, so rows never collide).

Sharding layout: the ``[N, W]`` window shards on both axes; ``W`` must
split into tx shards at whole-set granularity (``W / n_tx_shards``
divisible by the set capacity ``c``), which makes the static window
partition ``arange(W) // c`` locally contiguous — the same non-straddling
contract `sharded_dag.shard_dag_state` enforces for arbitrary DAGs, here
guaranteed by construction and validated at placement time.  Per-set-slot
metadata shards with the txs axis; the ``[S_b, c]`` backlog/output planes
replicate (1M txs of metadata is MBs — noise next to the window state).

Divergence from the unsharded scheduler (documented, tested): poll-order
score ranks are computed per tx shard; with ``W <= max_element_poll`` —
the recommended configuration — ranks never matter because nothing is
truncated.
"""

from __future__ import annotations

import dataclasses

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu import traffic as tf
from go_avalanche_tpu.config import (
    AvalancheConfig,
    DEFAULT_CONFIG,
    suppress_taps,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.models import dag as dag_model
from go_avalanche_tpu.models.streaming_dag import (
    NO_SET,
    SetBacklog,
    SetOutputs,
    StreamingDagState,
    StreamingDagTelemetry,
)
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.parallel import sharded, sharded_dag
from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS, shard_map
from go_avalanche_tpu.parallel.sharded_backlog import _traffic_specs


def streaming_dag_state_specs(n_sets: int,
                              set_size=None,
                              track_finality: bool = True,
                              with_inflight: bool = False,
                              with_fault_params: bool = False,
                              with_traffic: bool = False,
                              trace_spec=None,
                              ) -> StreamingDagState:
    """PartitionSpecs for every leaf of `StreamingDagState`;
    `trace_spec` mirrors the scheduler-owned trace plane (replicated —
    `obs.trace.replicated_spec`)."""
    return StreamingDagState(
        dag=sharded_dag.dag_state_specs(n_sets, set_size, track_finality,
                                        with_inflight, with_fault_params,
                                        trace_spec),
        slot_set=P(TXS_AXIS),
        slot_admit_round=P(TXS_AXIS),
        backlog=SetBacklog(score=P(), init_pref=P(), valid=P()),
        outputs=SetOutputs(settled=P(), accepted=P(), accept_votes=P(),
                           settle_round=P(), admit_round=P()),
        next_idx=P(),
        traffic=_traffic_specs(with_traffic),
    )


def shard_streaming_dag_state(state: StreamingDagState,
                              mesh) -> StreamingDagState:
    """Place a host-built streaming-DAG state onto the mesh.

    Validates whole-set tx sharding: the per-shard window width must be a
    multiple of the set capacity (then no window set straddles a shard).
    """
    n_tx_shards = mesh.shape[TXS_AXIS]
    c = state.backlog.score.shape[1]
    w = state.dag.base.records.votes.shape[1]
    if w % n_tx_shards:
        raise ValueError(f"window ({w}) must divide by tx shards "
                         f"({n_tx_shards})")
    if (w // n_tx_shards) % c:
        raise ValueError(
            f"per-shard window ({w // n_tx_shards}) must be a multiple of "
            f"the set capacity ({c}) so sets do not straddle tx shards")
    state = state._replace(dag=dataclasses.replace(
        state.dag, base=state.dag.base._replace(
            inflight=inflight.repack_polled_for_shards(
                state.dag.base.inflight, w, n_tx_shards))))
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state, streaming_dag_state_specs(
            state.dag.n_sets, state.dag.set_size,
            state.dag.base.finalized_at is not None,
            state.dag.base.inflight is not None,
            state.dag.base.fault_params is not None,
            state.traffic is not None,
            obs_trace.replicated_spec(state.dag.base.trace)))


def _merge_rows(old, row_idx, rows, s_b):
    """Replicated [S_b, c] plane update from per-shard row scatters.

    `row_idx` entries == s_b are dropped.  Rows are written by exactly one
    shard (a backlog set occupies one set-slot), so a psum of one-hot
    planes reconstructs them exactly.
    """
    dtype = old.dtype
    vdt = jnp.int32 if dtype == jnp.bool_ else dtype
    c = old.shape[1]
    written = jnp.zeros((s_b,), jnp.int32).at[row_idx].set(1, mode="drop")
    vals = (jnp.zeros((s_b, c), vdt)
            .at[row_idx].set(rows.astype(vdt), mode="drop"))
    written = lax.psum(written, TXS_AXIS)
    vals = lax.psum(vals, TXS_AXIS)
    return jnp.where((written > 0)[:, None], vals.astype(dtype), old)


def _local_settled_sets(state: StreamingDagState, cfg: AvalancheConfig,
                        c: int) -> jax.Array:
    """bool [s_w_local]: globally-settled occupied set-slots.

    The `models/streaming_dag._settled_set_slots` predicate with the
    node-axis `any` turned into one psum."""
    base = state.dag.base
    n_local, w_local = base.records.votes.shape
    s_w_local = w_local // c
    nshard = lax.axis_index(NODES_AXIS)
    alive_local = lax.dynamic_slice(base.alive, (nshard * n_local,),
                                    (n_local,))
    occupied = state.slot_set != NO_SET

    fin = vr.has_finalized(base.records.confidence, cfg)
    fin_acc = fin & vr.is_accepted(base.records.confidence)
    node_set_done = fin_acc.reshape(n_local, s_w_local, c).any(axis=2)
    rival_settled = (jnp.repeat(node_set_done, c, axis=1)
                     & jnp.logical_not(fin_acc))
    pending = (base.added & alive_local[:, None] & base.valid[None, :]
               & jnp.logical_not(fin) & jnp.logical_not(rival_settled))
    pending_local = pending.reshape(n_local, s_w_local, c).any(
        axis=(0, 2)).astype(jnp.int32)
    pending_any = lax.psum(pending_local, NODES_AXIS) > 0
    return occupied & jnp.logical_not(pending_any)


def _local_retire_and_refill(
    state: StreamingDagState,
    cfg: AvalancheConfig,
    c: int,
    refill: bool = True,
) -> Tuple[StreamingDagState, jax.Array]:
    """The set-granular scheduler pass on one shard; see
    `models/streaming_dag`.  Returns (new_state, globally-retired sets)."""
    base = state.dag.base
    n_local, w_local = base.records.votes.shape
    s_w_local = w_local // c
    s_b = state.backlog.score.shape[0]
    settled = _local_settled_sets(state, cfg, c)
    empty = state.slot_set == NO_SET
    cap = cfg.stream_retire_cap
    sparse = refill and cap is not None
    tshard = lax.axis_index(TXS_AXIS)
    if sparse:
        # Same capped/column-scatter scheduler as the unsharded model
        # (`models/streaming_dag._retire_and_refill`), with the
        # participation rank made global: shards hold contiguous slot
        # ranges, so an exclusive prefix of pool counts over the txs axis
        # reproduces the unsharded cumsum order bit-for-bit.
        k_local = min(cap, s_w_local)
        pool = settled | empty
        pcounts = lax.all_gather(pool.sum().astype(jnp.int32), TXS_AXIS)
        pprefix = jnp.where(jnp.arange(pcounts.shape[0]) < tshard,
                            pcounts, 0).sum()
        grank = pprefix + jnp.cumsum(pool.astype(jnp.int32)) - 1
        participate = pool & (grank < cap)
        settled = settled & participate
        free = participate
    else:
        free = settled | empty

    # --- live traffic: per-shard member-weighted latency deltas psum'd
    # over the txs axis (each set lives in exactly one tx shard;
    # integer adds, so the replicated histogram matches the dense one
    # bit-for-bit); admission gated on the replicated watermark.
    traffic = state.traffic
    if traffic is not None:
        rows_safe = jnp.clip(state.slot_set, 0, s_b - 1)
        lat = base.round - traffic.arrival_round[rows_safe]
        members = state.backlog.valid[rows_safe].sum(axis=1).astype(
            jnp.int32)
        delta = tf.latency_delta(cfg, lat, jnp.where(settled, members, 0))
        traffic = traffic._replace(
            lat_hist=traffic.lat_hist + lax.psum(delta, TXS_AXIS))

    # --- retire: member outcomes; node-axis sums via psum so every node
    # shard computes identical [w_local] planes.
    conf = base.records.confidence
    fin_acc = vr.has_finalized(conf, cfg) & vr.is_accepted(conf)
    accept_votes = lax.psum(
        (fin_acc & base.added).sum(axis=0).astype(jnp.int32), NODES_AXIS)
    n_live = jnp.maximum(base.alive.sum().astype(jnp.int32), 1)
    accepted = accept_votes * 2 > n_live

    row_idx = jnp.where(settled, state.slot_set, s_b)
    out = state.outputs
    out = SetOutputs(
        settled=_merge_rows(out.settled, row_idx,
                            jnp.ones((s_w_local, c), jnp.bool_), s_b),
        accepted=_merge_rows(out.accepted, row_idx,
                             accepted.reshape(s_w_local, c), s_b),
        accept_votes=_merge_rows(out.accept_votes, row_idx,
                                 accept_votes.reshape(s_w_local, c), s_b),
        settle_round=_merge_rows(
            out.settle_round, row_idx,
            jnp.broadcast_to(base.round, (s_w_local, c)).astype(jnp.int32),
            s_b),
        admit_round=_merge_rows(
            out.admit_round, row_idx,
            jnp.broadcast_to(state.slot_admit_round[:, None],
                             (s_w_local, c)), s_b),
    )

    # --- refill: global admission rank = exclusive prefix over tx shards.
    count_local = free.sum().astype(jnp.int32)
    counts = lax.all_gather(count_local, TXS_AXIS)
    prefix = jnp.where(jnp.arange(counts.shape[0]) < tshard,
                       counts, 0).sum()
    rank = prefix + jnp.cumsum(free.astype(jnp.int32)) - 1
    cand = state.next_idx + rank
    avail = s_b if traffic is None else jnp.minimum(jnp.int32(s_b),
                                                    traffic.arrived_idx)
    take = free & (cand < avail)
    if not refill:   # end-of-run harvest
        take = jnp.zeros_like(take)
    new_set = jnp.where(take, cand, jnp.where(settled, NO_SET,
                                              state.slot_set))
    n_taken = lax.psum(take.sum().astype(jnp.int32), TXS_AXIS)

    cand_safe = jnp.clip(cand, 0, s_b - 1)
    pref_rows = state.backlog.init_pref[cand_safe]        # [s_w_local, c]
    take_w = jnp.repeat(take, c)
    occupied_after_w = jnp.repeat(new_set != NO_SET, c)

    if sparse:
        # Column-scatter plane updates; see the unsharded model for the
        # invariant arguments (cleared slots keep dead records, unchanged
        # empty slots are already added=False).
        changed = settled | take
        slot_ids = jnp.nonzero(changed, size=k_local,
                               fill_value=s_w_local)[0]
        sid_safe = jnp.minimum(slot_ids, s_w_local - 1)
        cols = (slot_ids[:, None].astype(jnp.int32) * c
                + jnp.arange(c, dtype=jnp.int32)[None, :]).reshape(-1)
        cols_safe = jnp.minimum(cols, w_local - 1)
        take_cols = jnp.repeat(take[sid_safe], c)
        fresh = vr.init_state(pref_rows[sid_safe].reshape(-1)[None, :])

        def fill_cols(plane, fresh_plane):
            upd = jnp.where(take_cols[None, :], fresh_plane,
                            plane[:, cols_safe])
            return plane.at[:, cols].set(upd.astype(plane.dtype),
                                         mode="drop")

        records = vr.VoteRecordState(
            votes=fill_cols(base.records.votes, fresh.votes),
            consider=fill_cols(base.records.consider, fresh.consider),
            confidence=fill_cols(base.records.confidence,
                                 fresh.confidence),
        )
        added = base.added.at[:, cols].set(
            jnp.broadcast_to(take_cols[None, :], (n_local, k_local * c)),
            mode="drop")
        if base.finalized_at is None:
            finalized_at = None
        else:
            fa_upd = jnp.where(take_cols[None, :], jnp.int32(-1),
                               base.finalized_at[:, cols_safe])
            finalized_at = base.finalized_at.at[:, cols].set(fa_upd,
                                                             mode="drop")
    else:
        pref_w = pref_rows.reshape(w_local)
        # Row-constant fresh values at [1, W]; the fill `where` broadcasts.
        # (Cost analysis shows XLA fused the explicit [N, W] broadcast this
        # replaces, so this is clarity, not traffic — PERF_NOTES.md.)
        fresh = vr.init_state(pref_w[None, :])

        def fill(plane, fresh_plane):
            return jnp.where(take_w[None, :], fresh_plane, plane)

        records = vr.VoteRecordState(
            votes=fill(base.records.votes, fresh.votes),
            consider=fill(base.records.consider, fresh.consider),
            confidence=fill(base.records.confidence, fresh.confidence),
        )
        added = jnp.where(take_w[None, :], True,
                          base.added & occupied_after_w[None, :])
        finalized_at = av.reset_finality(base.finalized_at, take_w)

    safe_rows = jnp.clip(new_set, 0, s_b - 1)
    valid = jnp.where(take_w,
                      state.backlog.valid[cand_safe].reshape(w_local),
                      base.valid & occupied_after_w)
    score = jnp.where(occupied_after_w,
                      state.backlog.score[safe_rows].reshape(w_local),
                      jnp.int32(-2**31 + 1))

    # Per-shard ranks (module note), with the hoisted poll-order pair
    # refreshed in the same single argsort.
    score_rank, poll_order, poll_order_inv = av.score_rank_with_orders(score)
    new_base = base._replace(
        records=records,
        added=added,
        valid=valid,
        score_rank=score_rank,
        poll_order=poll_order,
        poll_order_inv=poll_order_inv,
        finalized_at=finalized_at,
        # In-flight responses for a retired set-slot must not land on its
        # NEW occupant (see models/streaming_dag); columns are shard-local.
        inflight=inflight.clear_columns(base.inflight,
                                        jnp.repeat(settled | take, c)),
    )
    retired = lax.psum(settled.sum().astype(jnp.int32), TXS_AXIS)
    return StreamingDagState(
        dag=dag_model.DagSimState(new_base, state.dag.conflict_set,
                                  state.dag.n_sets, state.dag.set_size),
        slot_set=new_set,
        slot_admit_round=jnp.where(take, base.round,
                                   state.slot_admit_round),
        backlog=state.backlog,
        outputs=out,
        next_idx=state.next_idx + n_taken,
        traffic=traffic,
    ), retired


def _local_step(
    state: StreamingDagState,
    cfg: AvalancheConfig,
    c: int,
    n_global: int,
    n_tx_shards: int,
) -> Tuple[StreamingDagState, StreamingDagTelemetry]:
    round_val = state.dag.base.round
    arrivals = jnp.int32(0)
    if state.traffic is not None:
        # Replicated draw with the GLOBAL set-slot occupancy — every
        # shard realizes the dense arrival sequence bit-for-bit.
        s_w_local = state.slot_set.shape[0]
        occ = lax.psum((state.slot_set != NO_SET).sum().astype(jnp.int32),
                       TXS_AXIS)
        new_traffic, arrivals = tf.arrive(state.traffic, cfg,
                                          state.dag.base.round, occ,
                                          s_w_local * n_tx_shards)
        state = state._replace(traffic=new_traffic)
    state, retired = _local_retire_and_refill(state, cfg, c)
    # Scheduler-owned trace plane (models/streaming_dag contract): the
    # inner conflict round runs trace-suppressed; the full scheduler
    # record is written below from psum'd (replicated) counters.
    new_dag, round_tel = sharded_dag._local_round(state.dag,
                                                  suppress_taps(cfg),
                                                  n_global, n_tx_shards)
    occupied = lax.psum((state.slot_set != NO_SET).sum().astype(jnp.int32),
                        TXS_AXIS)
    tel = StreamingDagTelemetry(
        round=round_tel,
        retired_sets=retired,
        occupied_sets=occupied,
        backlog_left=state.backlog.score.shape[0] - state.next_idx,
        traffic=(None if state.traffic is None
                 else tf.traffic_telemetry(state.traffic, arrivals)),
    )
    new_dag = dataclasses.replace(new_dag, base=new_dag.base._replace(
        trace=obs_trace.write_round(new_dag.base.trace, cfg, round_val,
                                    tel)))
    return state._replace(dag=new_dag), tel


def _shard_mapped(mesh, n_sets: int, fn, with_tel=True, set_size=None,
                  track_finality: bool = True,
                  with_inflight: bool = False,
                  with_fault_params: bool = False,
                  with_traffic: bool = False,
                  trace_spec=None):
    specs = streaming_dag_state_specs(n_sets, set_size, track_finality,
                                      with_inflight, with_fault_params,
                                      with_traffic, trace_spec)
    if with_tel:
        tel_specs = StreamingDagTelemetry(
            round=av.SimTelemetry(*([P()] * len(av.SimTelemetry._fields))),
            retired_sets=P(), occupied_sets=P(), backlog_left=P(),
            traffic=(tf.TrafficTelemetry(
                *([P()] * len(tf.TrafficTelemetry._fields)))
                if with_traffic else None))
        out_specs = (specs, tel_specs)
    else:
        out_specs = specs
    return shard_map(fn, mesh=mesh, in_specs=(specs,),
                     out_specs=out_specs, check_vma=False)


def make_sharded_streaming_dag_step(mesh,
                                    cfg: AvalancheConfig = DEFAULT_CONFIG,
                                    donate: bool = False):
    """Jitted (state) -> (state, telemetry) scheduler+conflict-round step.
    `donate=True` donates the input state per call (chain, never reuse)."""
    n_tx = mesh.shape[TXS_AXIS]
    cache = {}

    def step(state: StreamingDagState):
        c = state.backlog.score.shape[1]
        key = (state.dag.base.records.votes.shape[0], state.dag.n_sets, c,
               state.dag.set_size,
               state.dag.base.finalized_at is not None,
               state.dag.base.inflight is not None,
               state.dag.base.fault_params is not None,
               state.traffic is not None,
               state.dag.base.trace is not None)
        if key not in cache:
            n_global = key[0]
            cache[key] = jax.jit(_shard_mapped(
                mesh, state.dag.n_sets,
                lambda s: _local_step(s, cfg, c, n_global, n_tx),
                set_size=state.dag.set_size, track_finality=key[4],
                with_inflight=key[5], with_fault_params=key[6],
                with_traffic=key[7],
                trace_spec=obs_trace.replicated_spec(
                    state.dag.base.trace)),
                donate_argnums=sharded._donate(donate))
        return cache[key](state)

    return step


# Collective allowlist (analysis/hlo_audit.py): the set-streaming
# scheduler's txs-axis merges (row-block retire/refill psums, pool-count
# all-gather — a [n_tx_shards] vector, never a plane) on top of the
# inner round's node-axis surface.
DECLARED_COLLECTIVES = frozenset({
    ("all_gather", (NODES_AXIS,)),
    ("all_gather", (TXS_AXIS,)),      # per-shard admission-pool counts
    ("all_reduce", (NODES_AXIS,)),    # settle test over the nodes axis
    ("all_reduce", (TXS_AXIS,)),      # retire/refill merges, occupancy,
                                      #   traffic deltas
    ("all_reduce", (NODES_AXIS, TXS_AXIS)),
})


def settle_program(mesh, state: StreamingDagState,
                   cfg: AvalancheConfig = DEFAULT_CONFIG,
                   max_rounds: int = 100_000, donate: bool = False):
    """The jitted drain-to-settlement program `run_sharded_streaming_dag`
    executes — exposed unexecuted so `analysis/hlo_audit.py` lowers THE
    driver program (the `bench.flagship_program` seam).  Only tree
    structure and shapes are read from `state`."""
    n_global = state.dag.base.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]
    c = state.backlog.score.shape[1]

    def local_run(s):
        def undrained(st: StreamingDagState) -> jax.Array:
            s_b = st.backlog.score.shape[0]
            unsettled = ((st.slot_set != NO_SET)
                         & jnp.logical_not(_local_settled_sets(st, cfg, c)))
            any_left = lax.psum(unsettled.any().astype(jnp.int32),
                                TXS_AXIS) > 0
            return (st.next_idx < s_b) | any_left

        def cond(carry):
            st, live = carry
            return live & (st.dag.base.round < max_rounds)

        def body(carry):
            st, _ = carry
            new_st, _ = _local_step(st, cfg, c, n_global, n_tx)
            return new_st, undrained(new_st)

        final, _ = lax.while_loop(cond, body, (s, undrained(s)))
        final, _ = _local_retire_and_refill(final, cfg, c, refill=False)
        return final

    fn = _shard_mapped(mesh, state.dag.n_sets, local_run, with_tel=False,
                       set_size=state.dag.set_size,
                       track_finality=state.dag.base.finalized_at
                       is not None,
                       with_inflight=state.dag.base.inflight is not None,
                       with_fault_params=(state.dag.base.fault_params
                                          is not None),
                       with_traffic=state.traffic is not None,
                       trace_spec=obs_trace.replicated_spec(
                           state.dag.base.trace))
    return jax.jit(fn, donate_argnums=sharded._donate(donate))


def run_sharded_streaming_dag(
    mesh,
    state: StreamingDagState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 100_000,
    donate: bool = False,
) -> StreamingDagState:
    """Stream the whole conflict graph to settlement over the mesh; one jit.

    Ends with a harvest pass so the last window's outcomes are recorded.
    """
    return settle_program(mesh, state, cfg, max_rounds, donate)(state)


def scan_program(mesh, state: StreamingDagState,
                 cfg: AvalancheConfig = DEFAULT_CONFIG,
                 n_rounds: int = 100, donate: bool = False):
    """The jitted fixed-round program `run_scan_sharded_streaming_dag`
    executes — the audit seam twin of `settle_program`."""
    n_global = state.dag.base.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]
    c = state.backlog.score.shape[1]

    def local_scan(s):
        def body(carry, _):
            new_s, tel = _local_step(carry, cfg, c, n_global, n_tx)
            return new_s, tel
        return lax.scan(body, s, None, length=n_rounds)

    return jax.jit(_shard_mapped(
        mesh, state.dag.n_sets, local_scan, set_size=state.dag.set_size,
        track_finality=state.dag.base.finalized_at is not None,
        with_inflight=state.dag.base.inflight is not None,
        with_fault_params=state.dag.base.fault_params is not None,
        with_traffic=state.traffic is not None,
        trace_spec=obs_trace.replicated_spec(state.dag.base.trace)),
        donate_argnums=sharded._donate(donate))


def run_scan_sharded_streaming_dag(
    mesh,
    state: StreamingDagState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 100,
    donate: bool = False,
) -> Tuple[StreamingDagState, StreamingDagTelemetry]:
    """Fixed-round sharded stream; one jit, collectives inside the scan."""
    return scan_program(mesh, state, cfg, n_rounds, donate)(state)
