"""Mesh sharding of the simulators (ICI/DCN scale-out)."""
