"""Mesh sharding of the simulators (ICI/DCN scale-out).

Also home of the per-driver FOOTPRINT registry (`footprint_cases`):
for each of the five sharded drivers, the abstract audit-shape state,
its `PartitionSpec` tree, and the exact scan/settle program seam the
contract auditor lowers — everything the resource plane
(`obs/resources.py`, `benchmarks/mem_pin.py`) needs to compare a
driver's compiled `memory_analysis()` against the analytic per-device
footprint model, without re-deriving either per call site.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class FootprintCase:
    """One sharded driver's resource-accounting case: lower
    ``program_builder(mesh)`` over ``state_abs`` for the compiled side;
    feed ``(state_abs, specs, mesh)`` to `obs.resources.footprint` for
    the analytic per-device side."""

    driver: str
    mesh: object
    state_abs: object
    specs: object
    program_builder: object  # mesh -> jitted donated program


def _specs_for(driver: str, state):
    """The driver's `state_specs` tree for exactly this state variant —
    optional planes (finalized_at / inflight / fault_params / trace)
    mirrored from the state so both trees unflatten identically."""
    from go_avalanche_tpu.obs import trace as obs_trace

    def _sim_flags(sim):
        return (sim.finalized_at is not None, sim.inflight is not None,
                sim.fault_params is not None,
                obs_trace.replicated_spec(sim.trace))

    if driver == "avalanche":
        from go_avalanche_tpu.parallel import sharded

        return sharded.state_specs(*_sim_flags(state))
    if driver == "dag":
        from go_avalanche_tpu.parallel import sharded_dag

        track, infl, fault, trace_spec = _sim_flags(state.base)
        return sharded_dag.dag_state_specs(
            state.n_sets, state.set_size, track, infl, fault, trace_spec)
    if driver == "backlog":
        from go_avalanche_tpu.parallel import sharded_backlog

        track, infl, fault, trace_spec = _sim_flags(state.sim)
        return sharded_backlog.backlog_state_specs(
            track, infl, fault, state.traffic is not None, trace_spec)
    if driver == "streaming_dag":
        from go_avalanche_tpu.parallel import sharded_streaming_dag

        track, infl, fault, trace_spec = _sim_flags(state.dag.base)
        return sharded_streaming_dag.streaming_dag_state_specs(
            state.dag.n_sets, state.dag.set_size, track, infl, fault,
            state.traffic is not None, trace_spec)
    if driver == "node_stream":
        from go_avalanche_tpu.parallel import sharded_node_stream

        track, infl, fault, trace_spec = _sim_flags(state.sim)
        return sharded_node_stream.node_stream_state_specs(
            track, infl, fault, trace_spec)
    raise ValueError(f"unknown sharded driver {driver!r}")


def footprint_cases(drivers: Optional[Sequence[str]] = None
                    ) -> Dict[str, FootprintCase]:
    """The five sharded drivers' footprint entries on the 2x2 audit
    mesh, base variant each — states and program builders come from the
    contract auditor's case table (`analysis.hlo_audit._sharded_case`),
    so the resource plane accounts THE audited programs, never a
    reconstruction.  Raises `hlo_audit.AuditUnavailable` under 4
    devices (run under the tier-1 harness or on hardware)."""
    from go_avalanche_tpu.analysis import hlo_audit

    mesh = hlo_audit._audit_mesh()
    out: Dict[str, FootprintCase] = {}
    for driver in (drivers or hlo_audit.SHARDED_DRIVERS):
        variants, _, _ = hlo_audit._sharded_case(driver)
        _, builder, state_abs = variants[0]  # the base variant
        out[driver] = FootprintCase(
            driver=driver, mesh=mesh, state_abs=state_abs,
            specs=_specs_for(driver, state_abs),
            program_builder=builder)
    return out
