"""Mesh-sharded multi-target simulator: the distributed backend.

`models/avalanche.round_step` re-expressed under `jax.shard_map` over the
``(nodes, txs)`` mesh of `parallel/mesh.py`.  Where the reference has no
communication backend at all (SURVEY.md section 5), every cross-node
interaction here is an explicit XLA collective on the "nodes" axis:

  * **preference exchange** — each shard packs its local preference plane to
    bits (`ops/bitops.pack_bool_plane`, 8x traffic reduction) and
    `all_gather`s it, so peer gathers index a replicated packed plane;
  * **gossip admission**    — local scatter-ORs into a global-height plane,
    then `psum_scatter` back to owner shards;
  * **global statistics**   — telemetry and the settled flag are `psum`s.

The "txs" axis needs no collectives (a vote for target t touches only
column t), making it the natural cross-slice/DCN axis.

Randomness: per-round base keys are folded with the shard's "nodes" axis
index only, so all "txs" shards of the same node rows draw identical peers /
flips / drops — preserving the unsharded semantics where one response covers
all of a node's polled targets.  Runs are deterministic for a fixed key and
mesh shape (the stream differs from the unsharded model's, which folds
nothing).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu.config import (
    AdversaryStrategy,
    AvalancheConfig,
    DEFAULT_CONFIG,
    VoteMode,
)
from go_avalanche_tpu.models.avalanche import (
    AvalancheSimState,
    SimTelemetry,
    capped_poll_mask,
    popcnt_plane,
)
from go_avalanche_tpu.ops import adversary, voterecord as vr
from go_avalanche_tpu.ops.bitops import pack_bool_plane, unpack_bool_plane
from go_avalanche_tpu.ops.sampling import (
    sample_peers_uniform,
    sample_peers_weighted,
    self_sample_mask,
)
from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS


def state_specs() -> AvalancheSimState:
    """PartitionSpecs for every leaf of `AvalancheSimState`."""
    return AvalancheSimState(
        records=vr.VoteRecordState(
            votes=P(NODES_AXIS, TXS_AXIS),
            consider=P(NODES_AXIS, TXS_AXIS),
            confidence=P(NODES_AXIS, TXS_AXIS),
        ),
        added=P(NODES_AXIS, TXS_AXIS),
        valid=P(TXS_AXIS),
        score_rank=P(TXS_AXIS),
        byzantine=P(),           # replicated [N]: peer lookups need all rows
        alive=P(),
        latency_weight=P(),      # replicated [N]: global sampling CDF
        finalized_at=P(NODES_AXIS, TXS_AXIS),
        round=P(),
        key=P(),
    )


def shard_state(state: AvalancheSimState, mesh) -> AvalancheSimState:
    """Place a host-built state onto the mesh with the canonical shardings."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state, state_specs())


def _global_minority_plane(prefs_local: jax.Array,
                           n_global: int) -> jax.Array:
    """Bool ``[t_local]`` — per-target minority color over ALL node rows.

    The sharded form of `ops/adversary.minority_plane`: local column sums,
    psum'd over the nodes axis, compared against the global row count (same
    tie semantics: an even split counts "no" as the minority).
    """
    yes_counts = lax.psum(prefs_local.sum(axis=0).astype(jnp.int32),
                          NODES_AXIS)
    return yes_counts * 2 < n_global


def _local_round(
    state: AvalancheSimState,
    cfg: AvalancheConfig,
    n_global: int,
    n_tx_shards: int,
) -> Tuple[AvalancheSimState, SimTelemetry]:
    """One round on this shard's block; collectives on the nodes axis only."""
    n_local, t_local = state.records.votes.shape
    nshard = lax.axis_index(NODES_AXIS)
    offset = nshard * n_local

    # Per-round keys: base split is replicated; sampling/fault draws fold in
    # the nodes-shard index (NOT the txs index — see module docstring).
    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(state.key, 5)
    k_sample = jax.random.fold_in(k_sample, nshard)
    k_byz = jax.random.fold_in(k_byz, nshard)
    k_drop = jax.random.fold_in(k_drop, nshard)
    k_churn = jax.random.fold_in(k_churn, nshard)

    fin = vr.has_finalized(state.records.confidence, cfg)
    alive_local = lax.dynamic_slice(state.alive, (offset,), (n_local,))

    # --- GetInvsForNextPoll on the local block.  With txs sharding the poll
    # cap is applied per shard at cap/n_tx_shards (exact when T fits the cap,
    # approximate otherwise — a global cap would need a cross-shard cumsum).
    pollable = (state.added & alive_local[:, None] & state.valid[None, :]
                & jnp.logical_not(fin))
    local_cap = max(1, cfg.max_element_poll // n_tx_shards)
    polled = capped_poll_mask(pollable, state.score_rank, local_cap)

    # --- sample k global peer ids for the local rows (uniform or
    # latency-weighted; the weighted CDF is global/replicated).
    if cfg.weighted_sampling:
        w = state.latency_weight * state.alive.astype(jnp.float32)
        peers = sample_peers_weighted(k_sample, w, n_local, cfg.k)
        self_draw = self_sample_mask(peers, id_offset=offset)
    else:
        peers = sample_peers_uniform(
            k_sample, n_global, cfg.k, cfg.exclude_self,
            n_local=n_local, id_offset=offset,
            with_replacement=cfg.sample_with_replacement)
        self_draw = None

    lie = adversary.lie_mask(k_byz, peers, state.byzantine, cfg)
    responded = state.alive[peers]
    if self_draw is not None:
        responded &= jnp.logical_not(self_draw)
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    # --- gossip-on-poll across shards: scatter into a global-height plane,
    # reduce-scatter back to owners.
    added = state.added
    admissions = jnp.int32(0)
    if cfg.gossip:
        heard_global = jnp.zeros((n_global, t_local), jnp.uint8)
        polled_u8 = polled.astype(jnp.uint8)
        for j in range(cfg.k):
            heard_global = heard_global.at[peers[:, j]].max(polled_u8)
        heard = lax.psum_scatter(heard_global, NODES_AXIS,
                                 scatter_dimension=0, tiled=True)
        new_adds = ((heard > 0) & jnp.logical_not(added)
                    & alive_local[:, None] & state.valid[None, :])
        admissions = new_adds.sum().astype(jnp.int32)
        added = added | new_adds

    # --- preference exchange: pack local plane, all-gather, gather rows.
    prefs_local = vr.is_accepted(state.records.confidence)
    packed_local = pack_bool_plane(prefs_local)        # [n_local, ceil(t/8)]
    packed_global = lax.all_gather(packed_local, NODES_AXIS, axis=0,
                                   tiled=True)         # [n_global, ceil(t/8)]
    if cfg.adversary_strategy is AdversaryStrategy.OPPOSE_MAJORITY:
        # One extra [t_local] psum per round, paid only under this strategy.
        minority_t = _global_minority_plane(prefs_local, n_global)
    else:
        minority_t = jnp.zeros((t_local,), jnp.bool_)  # unused
    # The equivocation coin is per-target, so unlike every other fault draw
    # it must NOT be identical across txs shards: fold the txs-axis index in.
    k_vote = k_byz
    if cfg.adversary_strategy is AdversaryStrategy.EQUIVOCATE:
        k_vote = jax.random.fold_in(k_byz, lax.axis_index(TXS_AXIS))

    yes_pack, consider_pack = adversary.pack_adversarial_votes(
        lambda j: unpack_bool_plane(packed_global[peers[:, j]], t_local),
        responded, lie, k_vote, cfg, minority_t)

    # --- ingest.
    if cfg.vote_mode is VoteMode.SEQUENTIAL:
        records, changed = vr.register_packed_votes(
            state.records, yes_pack, consider_pack, cfg.k, cfg,
            update_mask=polled)
        votes_applied = (popcnt_plane(consider_pack) * polled).sum()
    else:
        thresh = math.ceil(cfg.alpha * cfg.k)
        yes_cnt = popcnt_plane(yes_pack & consider_pack)
        no_cnt = popcnt_plane(~yes_pack & consider_pack)
        err = jnp.where(yes_cnt >= thresh, jnp.int32(0),
                        jnp.where(no_cnt >= thresh, jnp.int32(1),
                                  jnp.int32(-1)))
        records, changed = vr.register_vote(state.records, err, cfg,
                                            update_mask=polled)
        votes_applied = ((err >= 0) & polled).sum()

    # --- lifecycle.
    fin_after = vr.has_finalized(records.confidence, cfg)
    newly_final = fin_after & jnp.logical_not(fin)
    finalized_at = jnp.where(newly_final & (state.finalized_at < 0),
                             state.round, state.finalized_at)

    alive = state.alive
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability,
                                      (n_local,))
        alive_local_new = jnp.logical_xor(alive_local, toggle)
        alive = lax.all_gather(alive_local_new, NODES_AXIS, axis=0,
                               tiled=True)

    # --- global telemetry: psum over both axes => replicated scalars.
    def _global_sum(x):
        return lax.psum(x.astype(jnp.int32), (NODES_AXIS, TXS_AXIS))

    telemetry = SimTelemetry(
        polls=_global_sum(polled.sum()),
        votes_applied=_global_sum(votes_applied),
        flips=_global_sum((changed & jnp.logical_not(newly_final)).sum()),
        finalizations=_global_sum(newly_final.sum()),
        admissions=_global_sum(admissions),
    )
    new_state = AvalancheSimState(
        records=records,
        added=added,
        valid=state.valid,
        score_rank=state.score_rank,
        byzantine=state.byzantine,
        alive=alive,
        latency_weight=state.latency_weight,
        finalized_at=finalized_at,
        round=state.round + 1,
        key=k_next,
    )
    return new_state, telemetry


def _shard_mapped(mesh, fn):
    specs = state_specs()
    tel_specs = SimTelemetry(*([P()] * len(SimTelemetry._fields)))
    return jax.shard_map(fn, mesh=mesh, in_specs=(specs,),
                         out_specs=(specs, tel_specs), check_vma=False)


def make_sharded_round_step(mesh, cfg: AvalancheConfig = DEFAULT_CONFIG):
    """Build a jitted one-round step over the mesh; call it with a (global)
    `AvalancheSimState` placed by `shard_state`."""
    n_tx = mesh.shape[TXS_AXIS]
    cache = {}

    def step(state: AvalancheSimState):
        n_global = state.records.votes.shape[0]
        if n_global not in cache:
            cache[n_global] = jax.jit(_shard_mapped(
                mesh, lambda s: _local_round(s, cfg, n_global, n_tx)))
        return cache[n_global](state)

    return step


def run_scan_sharded(
    mesh,
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 100,
) -> Tuple[AvalancheSimState, SimTelemetry]:
    """Fixed-round sharded run; one jit, collectives inside the scan."""
    n_global = state.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_scan(s):
        def body(carry, _):
            new_s, tel = _local_round(carry, cfg, n_global, n_tx)
            return new_s, tel
        return lax.scan(body, s, None, length=n_rounds)

    return jax.jit(_shard_mapped(mesh, local_scan))(state)


def run_sharded(
    mesh,
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 2000,
) -> AvalancheSimState:
    """Run until globally settled (psum'd flag) or `max_rounds`; one jit."""
    n_global = state.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_run(s):
        def unsettled(st):
            n_local = st.records.votes.shape[0]
            nshard = lax.axis_index(NODES_AXIS)
            alive_local = lax.dynamic_slice(
                st.alive, (nshard * n_local,), (n_local,))
            fin = vr.has_finalized(st.records.confidence, cfg)
            pollable = (st.added & alive_local[:, None]
                        & st.valid[None, :] & jnp.logical_not(fin))
            return lax.psum(pollable.any().astype(jnp.int32),
                            (NODES_AXIS, TXS_AXIS)) > 0

        def cond(carry):
            st, live = carry
            return live & (st.round < max_rounds)

        def body(carry):
            st, _ = carry
            new_st, _ = _local_round(st, cfg, n_global, n_tx)
            return new_st, unsettled(new_st)

        final, _ = lax.while_loop(cond, body, (s, unsettled(s)))
        return final

    specs = state_specs()
    fn = jax.shard_map(local_run, mesh=mesh, in_specs=(specs,),
                       out_specs=specs, check_vma=False)
    return jax.jit(fn)(state)
