"""Mesh-sharded multi-target simulator: the distributed backend.

`models/avalanche.round_step` re-expressed under `jax.shard_map` over the
``(nodes, txs)`` mesh of `parallel/mesh.py`.  Where the reference has no
communication backend at all (SURVEY.md section 5), every cross-node
interaction here is an explicit XLA collective on the "nodes" axis:

  * **preference exchange** — each shard packs its local preference plane to
    bits (`ops/bitops.pack_bool_plane`, 8x traffic reduction) and
    `all_gather`s it, so peer gathers index a replicated packed plane;
  * **gossip admission**    — bit-packed or-scatter into a global-height
    plane (a max-scatter per bit), then an `all_to_all` + OR back to owner
    shards (`_gossip_heard_packed`);
  * **poll cap**            — the 4096-inv cap holds globally across tx
    shards via a per-node rank-threshold binary search whose only traffic
    is one int32 per node per step (`global_capped_poll_mask`);
  * **global statistics**   — telemetry and the settled flag are `psum`s.

The "txs" axis needs no collectives (a vote for target t touches only
column t), making it the natural cross-slice/DCN axis.

Randomness: per-round base keys are folded with the shard's "nodes" axis
index only, so all "txs" shards of the same node rows draw identical peers /
flips / drops — preserving the unsharded semantics where one response covers
all of a node's polled targets.  Runs are deterministic for a fixed key and
mesh shape (the stream differs from the unsharded model's, which folds
nothing).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu.config import (
    AdversaryStrategy,
    AvalancheConfig,
    DEFAULT_CONFIG,
    VoteMode,
)
from go_avalanche_tpu.models.avalanche import (
    AvalancheSimState,
    SimTelemetry,
    capped_poll_mask,
    popcnt_plane,
    stamp_finality,
)
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import adversary, exchange, inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.ops.bitops import pack_bool_plane, unpack_bool_plane
from go_avalanche_tpu.ops.sampling import draw_peers
from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS, shard_map


def state_specs(track_finality: bool = True,
                with_inflight: bool = False,
                with_fault_params: bool = False,
                trace_spec=None) -> AvalancheSimState:
    """PartitionSpecs for every leaf of `AvalancheSimState`.

    `track_finality=False` mirrors a state whose `finalized_at` leaf is
    None (see `models/avalanche.init`): the spec tree must carry None in
    the same slot or tree-structure checks fail.  `with_inflight=True`
    adds specs for the async-query ring buffer (`ops/inflight.py`): the
    per-draw planes shard with the node rows (leading ring-depth axis
    replicated), the poll-mask plane with both axes.
    `with_fault_params=True` mirrors a state carrying realized
    stochastic fault parameters (`inflight.FaultParams`) — tiny
    per-event scalars, replicated everywhere so every shard sees the
    SAME realized schedule the dense init drew.  `trace_spec` mirrors a
    state carrying the on-device trace plane (obs/trace.py): pass
    `obs.trace.replicated_spec(state.trace)` — the counters are psum'd
    before the write, so the plane replicates (same static column/
    stride aux as the value tree, or unflattening fails loudly).
    """
    inflight_specs = None
    if with_inflight:
        inflight_specs = inflight.InflightState(
            peers=P(None, NODES_AXIS, None),
            lat=P(None, NODES_AXIS, None),
            responded=P(None, NODES_AXIS, None),
            lie=P(None, NODES_AXIS, None),
            polled=P(None, NODES_AXIS, TXS_AXIS),
        )
    fault_specs = None
    if with_fault_params:
        fault_specs = inflight.FaultParams(
            *([P()] * len(inflight.FaultParams._fields)))
    return AvalancheSimState(
        records=vr.VoteRecordState(
            votes=P(NODES_AXIS, TXS_AXIS),
            consider=P(NODES_AXIS, TXS_AXIS),
            confidence=P(NODES_AXIS, TXS_AXIS),
        ),
        added=P(NODES_AXIS, TXS_AXIS),
        valid=P(TXS_AXIS),
        score_rank=P(TXS_AXIS),
        poll_order=P(TXS_AXIS),      # consulted only when n_tx_shards == 1
        poll_order_inv=P(TXS_AXIS),  # (the >1 path binary-searches ranks)
        byzantine=P(),           # replicated [N]: peer lookups need all rows
        alive=P(),
        latency_weight=P(),      # replicated [N]: global sampling CDF
        finalized_at=(P(NODES_AXIS, TXS_AXIS) if track_finality else None),
        round=P(),
        key=P(),
        inflight=inflight_specs,
        fault_params=fault_specs,
        trace=trace_spec,
    )


def shard_state(state: AvalancheSimState, mesh) -> AvalancheSimState:
    """Place a host-built state onto the mesh with the canonical shardings.

    `device_put` may ALIAS leaves whose placement already matches (single
    host, replicated spec) rather than copy — so when the result feeds a
    `donate=True` driver, treat the ORIGINAL `state` as consumed too.

    A coalesced-engine in-flight ring re-packs its poll-mask plane to the
    mesh's per-shard-padded byte layout first
    (`inflight.repack_polled_for_shards` — a no-op for walk rings and
    byte-aligned shard widths).
    """
    state = state._replace(inflight=inflight.repack_polled_for_shards(
        state.inflight, state.added.shape[1], mesh.shape[TXS_AXIS]))
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state, state_specs(state.finalized_at is not None,
                           state.inflight is not None,
                           state.fault_params is not None,
                           obs_trace.replicated_spec(state.trace)))


def _global_minority_plane(prefs_local: jax.Array,
                           n_global: int) -> jax.Array:
    """Bool ``[t_local]`` — per-target minority color over ALL node rows.

    The sharded form of `ops/adversary.minority_plane`: local column sums,
    psum'd over the nodes axis, compared against the global row count (same
    tie semantics: an even split counts "no" as the minority).
    """
    yes_counts = lax.psum(prefs_local.sum(axis=0).astype(jnp.int32),
                          NODES_AXIS)
    return yes_counts * 2 < n_global


def _policy_ctx_sharded(
    cfg: AvalancheConfig,
    records,
    prefs_local: jax.Array,
    byzantine: jax.Array,
    latency_weight: jax.Array,
    offset,
    n_local: int,
):
    """The sharded twin of `ops/adversary.policy_ctx` — bit-exact
    context planes from psum'd tallies; None (statically) with the
    policy off.

      split_vote — the honest yes tally is a local column sum over this
        shard's rows psum'd over the nodes axis (the
        `_global_minority_plane` recipe, honest rows only); the honest
        COUNT comes from the replicated byzantine plane directly.
      withhold_near_quorum — the per-querier near-quorum gate reduces
        this shard's LOCAL record columns, then ORs across tx shards
        (one [n_local] int32 psum — the querier's row spans them).
      stake_eclipse — the eclipse set derives from the replicated
        [N_global] weight/byzantine planes (identical on every shard);
        only this shard's row slice is kept.
    """
    if cfg.adversary_policy == "off":
        return None
    if cfg.adversary_policy == "split_vote":
        honest = jnp.logical_not(byzantine)            # replicated [N]
        honest_local = lax.dynamic_slice(honest, (offset,), (n_local,))
        yes = lax.psum(
            (prefs_local & honest_local[:, None]).sum(axis=0)
            .astype(jnp.int32), NODES_AXIS)
        n_honest = honest.sum().astype(jnp.int32)
        return adversary.PolicyCtx(split_t=yes * 2 < n_honest,
                                   split_even=yes * 2 == n_honest)
    if cfg.adversary_policy == "withhold_near_quorum":
        near_local = adversary.near_quorum_rows(records, cfg)
        near = lax.psum(near_local.astype(jnp.int32), TXS_AXIS) > 0
        return adversary.PolicyCtx(withhold_q=near)
    if cfg.adversary_policy == "stake_eclipse":
        eclipse = adversary.eclipse_rows(latency_weight, byzantine, cfg)
        return adversary.PolicyCtx(eclipse_q=lax.dynamic_slice(
            eclipse, (offset,), (n_local,)))
    return adversary.PolicyCtx()   # timing: latency-plane only


def global_capped_poll_mask(
    pollable: jax.Array,
    score_rank: jax.Array,
    cap: int,
    n_tx_shards: int,
    poll_order: jax.Array | None = None,
    poll_order_inv: jax.Array | None = None,
) -> jax.Array:
    """`capped_poll_mask` with the cap honored GLOBALLY across tx shards.

    Exactly `AvalancheMaxElementPoll` semantics (`avalanche.go:17`,
    truncation at `processor.go:165-167`, intended score order): per node,
    keep the `cap` best-globally-ranked pollable targets.  Local inputs are
    this shard's ``[n_local, t_local]`` block and its slice of the global
    rank permutation.

    Method: per-node binary search for the largest rank threshold R with
    ``|{t : pollable[i,t] and rank[t] < R}| <= cap``.  Global ranks are a
    permutation, so counts step by 1 and the threshold reproduces the flat
    top-cap exactly.  Each of the ~log2(T) steps exchanges one int32 per
    node row (a psum over the txs axis) — the whole search moves
    ``bits * n_local * 4`` bytes, noise next to one preference all-gather.
    (With per-shard rank vectors — `parallel/sharded_backlog`'s documented
    divergence — ranks repeat across shards and the count can step by up to
    n_tx_shards at one threshold; the search then yields <= cap, a safe
    under-fill, never an overshoot.)
    """
    t_local = pollable.shape[1]
    total_t = t_local * n_tx_shards
    if total_t <= cap:
        return pollable                     # statically un-truncated
    if n_tx_shards == 1:
        return capped_poll_mask(pollable, score_rank, cap,
                                poll_order, poll_order_inv)

    n_local = pollable.shape[0]
    rank_row = score_rank[None, :]

    def count(r):
        keep = pollable & (rank_row < r[:, None])
        return lax.psum(keep.sum(axis=1).astype(jnp.int32), TXS_AXIS)

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi + 1) // 2
        ok = count(mid) <= cap
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - 1)

    lo = jnp.zeros((n_local,), jnp.int32)
    hi = jnp.full((n_local,), total_t, jnp.int32)
    lo, hi = lax.fori_loop(0, total_t.bit_length() + 1, body, (lo, hi))
    return pollable & (rank_row < lo[:, None])


def _gossip_heard_packed(
    peers: jax.Array,
    polled: jax.Array,
    n_global: int,
    fused: bool = False,
) -> jax.Array:
    """uint8 ``[n_local, ceil(t_local/8)]`` — this shard's rows' heard bits.

    The gossip-on-poll exchange (`main.go:177`) with the scratch plane
    bit-packed along txs: 8x less resident HBM and 8x less ICI traffic than
    the uint8 0/1 plane it replaces (at 100k nodes x 4096 window txs the
    unpacked scratch alone was ~410 MB per device per round).

    Two tricks stand in for the or-scatter/or-reduce XLA doesn't offer:

      * **or-scatter**: a max-scatter of single-bit bytes IS an or-scatter
        — one `.at[rows].max` per bit position, each writing values in
        {0, 1<<b} (max of which == bitwise or);
      * **cross-shard or-reduce**: `psum_scatter` would carry across packed
        bits, so exchange shard contributions with `all_to_all` (same ICI
        volume as reduce-scatter) and OR the n_node_shards blocks locally.

    `fused` (cfg.fused_sharded_gossip) folds the 8 serial per-bit
    scatter-maxes into ONE batched scatter over an ``[8, N*k, t8]``
    per-bit update stack: the bit planes carry disjoint bits, so the OR
    over the bit axis is an exact byte sum.  The ICI leg is unchanged —
    the fold happens before the `all_to_all`, which still moves the
    packed ``[n_global, t8]`` plane — but the scatter scratch grows 8x
    (== one UNPACKED plane), which is why the per-bit loop stays the
    default until a hardware A/B prices dispatch count against scratch
    (ROADMAP).  Bit-exact either way
    (tests/test_sharding.py::test_sharded_gossip_scatter_engines_parity).
    """
    n_local, t_local = polled.shape
    k = peers.shape[1]
    n_shards = n_global // n_local
    polled_packed = pack_bool_plane(polled)             # [n_local, t8]
    t8 = polled_packed.shape[1]
    idx = peers.reshape(-1)                             # [n_local*k]
    if fused:
        upd = jnp.repeat(polled_packed, k, axis=0)      # rows match idx order
        bit = jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8)
        upd8 = upd[None, :, :] & bit[:, None, None]     # [8, N*k, t8]
        planes = jnp.zeros((8, n_global, t8), jnp.uint8).at[:, idx].max(upd8)
        heard = planes.sum(axis=0, dtype=jnp.uint8)     # disjoint bits: +==|
    else:
        heard = jnp.zeros((n_global, t8), jnp.uint8)
        for b in range(8):
            src = polled_packed & jnp.uint8(1 << b)
            upd = jnp.repeat(src, k, axis=0)            # rows match idx order
            heard |= jnp.zeros((n_global, t8), jnp.uint8).at[idx].max(upd)
    if n_shards == 1:
        return heard
    parts = lax.all_to_all(heard, NODES_AXIS, split_axis=0, concat_axis=0,
                           tiled=True).reshape(n_shards, n_local, t8)
    out = parts[0]
    for s in range(1, n_shards):
        out |= parts[s]
    return out


def _local_round(
    state: AvalancheSimState,
    cfg: AvalancheConfig,
    n_global: int,
    n_tx_shards: int,
) -> Tuple[AvalancheSimState, SimTelemetry]:
    """One round on this shard's block; collectives on the nodes axis only."""
    n_local, t_local = state.records.votes.shape
    nshard = lax.axis_index(NODES_AXIS)
    offset = nshard * n_local

    # Per-round keys: base split is replicated; sampling/fault draws fold in
    # the nodes-shard index (NOT the txs index — see module docstring).
    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(state.key, 5)
    k_sample = jax.random.fold_in(k_sample, nshard)
    k_byz = jax.random.fold_in(k_byz, nshard)
    k_drop = jax.random.fold_in(k_drop, nshard)
    k_churn = jax.random.fold_in(k_churn, nshard)

    fin = vr.has_finalized(state.records.confidence, cfg)
    alive_local = lax.dynamic_slice(state.alive, (offset,), (n_local,))

    # --- GetInvsForNextPoll on the local block, with the 4096-inv cap
    # honored GLOBALLY across tx shards (exact `AvalancheMaxElementPoll`
    # semantics via a per-node rank-threshold search; see
    # `global_capped_poll_mask`).
    pollable = (state.added & alive_local[:, None] & state.valid[None, :]
                & jnp.logical_not(fin))
    polled = global_capped_poll_mask(pollable, state.score_rank,
                                     cfg.max_element_poll, n_tx_shards,
                                     state.poll_order, state.poll_order_inv)

    # --- sample k global peer ids for the local rows: the shared draw
    # dispatch (weighted CDFs / cluster rows are global + replicated).
    peers, self_draw = draw_peers(k_sample, cfg, state.latency_weight,
                                  state.alive, n_global,
                                  n_local=n_local, id_offset=offset)

    lie = adversary.lie_mask(k_byz, peers, state.byzantine, cfg)
    responded = state.alive[peers]
    if self_draw is not None:
        responded &= jnp.logical_not(self_draw)
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    # --- gossip-on-poll across shards: bit-packed or-scatter into a
    # global-height plane, all_to_all + OR back to owner shards.
    added = state.added
    admissions = jnp.int32(0)
    gossip_writes = jnp.int32(0)
    if cfg.gossip:
        heard_packed = _gossip_heard_packed(peers, polled, n_global,
                                            fused=cfg.fused_sharded_gossip)
        heard = unpack_bool_plane(heard_packed, t_local)
        new_adds = (heard & jnp.logical_not(added)
                    & alive_local[:, None] & state.valid[None, :])
        admissions = new_adds.sum().astype(jnp.int32)
        gossip_writes = heard.sum().astype(jnp.int32)
        added = added | new_adds

    # --- preference exchange: pack local plane, all-gather, gather rows.
    prefs_local = vr.is_accepted(state.records.confidence)
    packed_local = pack_bool_plane(prefs_local)        # [n_local, ceil(t/8)]
    packed_global = lax.all_gather(packed_local, NODES_AXIS, axis=0,
                                   tiled=True)         # [n_global, ceil(t/8)]
    if cfg.adversary_strategy is AdversaryStrategy.OPPOSE_MAJORITY:
        # One extra [t_local] psum per round, paid only under this strategy.
        minority_t = _global_minority_plane(prefs_local, n_global)
    else:
        minority_t = jnp.zeros((t_local,), jnp.bool_)  # unused
    # The equivocation coin is per-target, so unlike every other fault draw
    # it must NOT be identical across txs shards: fold the txs-axis index
    # in.  The split_vote tie coin is per-target too (same argument).
    k_vote = k_byz
    if (cfg.adversary_strategy is AdversaryStrategy.EQUIVOCATE
            or cfg.adversary_policy == "split_vote"):
        k_vote = jax.random.fold_in(k_byz, lax.axis_index(TXS_AXIS))

    # --- adaptive adversary (cfg.adversary_policy): psum'd twin of the
    # dense round's per-round context; statically absent when off.
    pol = _policy_ctx_sharded(cfg, state.records, prefs_local,
                              state.byzantine, state.latency_weight,
                              offset, n_local)
    lie, responded, withheld = adversary.apply_policy_issue(cfg, pol, lie,
                                                            responded)

    # --- ingest.
    ring = state.inflight
    if inflight.enabled(cfg):
        # Async query lifecycle (ops/inflight.py): delivery gathers index
        # the round's replicated packed plane exactly like the
        # synchronous gather; the ring's per-draw planes are node-row
        # sharded, so the whole pass stays collective-free.
        lat = inflight.draw_latency(k_sample, cfg, peers,
                                    state.latency_weight, n_global,
                                    row_offset=offset)
        lat = adversary.apply_policy_latency(cfg, lat, lie, withheld)
        lat = inflight.apply_faults(lat, cfg, state.round, offset,
                                    peers, n_global, state.fault_params)
        ring = inflight.enqueue(state.inflight, state.round, peers, lat,
                                responded, lie, polled)
        records, changed, votes_applied = inflight.deliver_multi_engine(
            ring, state.records, cfg, packed_global, minority_t, k_vote,
            state.round, t_local, live_rows=alive_local, ctx=pol)
    elif cfg.vote_mode is VoteMode.SEQUENTIAL:
        # Engine dispatch (`ops/exchange.gather_vote_packs`): global peer
        # ids index the replicated packed plane — one flattened gather
        # (fused, default) or k row-gathers (legacy).
        yes_pack, consider_pack = exchange.gather_vote_packs(
            packed_global, peers, responded, lie, k_vote, cfg, minority_t,
            t_local, pol)
        records, changed = vr.register_packed_votes_engine(
            state.records, yes_pack, consider_pack, cfg.k, cfg,
            update_mask=polled)
        votes_applied = (popcnt_plane(consider_pack) * polled).sum()
    else:
        yes_pack, consider_pack = exchange.gather_vote_packs(
            packed_global, peers, responded, lie, k_vote, cfg, minority_t,
            t_local, pol)
        thresh = math.ceil(cfg.alpha * cfg.k)
        yes_cnt = popcnt_plane(yes_pack & consider_pack)
        no_cnt = popcnt_plane(~yes_pack & consider_pack)
        err = jnp.where(yes_cnt >= thresh, jnp.int32(0),
                        jnp.where(no_cnt >= thresh, jnp.int32(1),
                                  jnp.int32(-1)))
        records, changed = vr.register_vote(state.records, err, cfg,
                                            update_mask=polled)
        votes_applied = ((err >= 0) & polled).sum()

    # --- lifecycle.
    fin_after = vr.has_finalized(records.confidence, cfg)
    newly_final = fin_after & jnp.logical_not(fin)
    finalized_at = stamp_finality(state.finalized_at, newly_final,
                                  state.round)

    alive = state.alive
    alive_local_new = alive_local
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability,
                                      (n_local,))
        alive_local_new = jnp.logical_xor(alive_local, toggle)
    # Scheduled churn bursts toggle this shard's own rows (k_churn is
    # already shard-folded), then the replicated [N] plane is rebuilt —
    # statically absent with no burst events.
    alive_local_new = inflight.apply_churn_bursts(alive_local_new, cfg,
                                                  state.round, k_churn)
    if cfg.churn_probability > 0.0 or cfg.churn_burst_events():
        alive = lax.all_gather(alive_local_new, NODES_AXIS, axis=0,
                               tiled=True)

    # --- global telemetry: psum over both axes => replicated scalars.
    # The ring counters come from planes sharded over NODE rows but
    # REPLICATED across tx shards (`inflight.ring_telemetry` reads the
    # no-T latency planes; the partition cut reads peers) — psum over
    # the nodes axis ONLY, or every tx shard would be double-counted.
    # Either way the result is replicated on both axes, and equals the
    # dense round's counter bit-for-bit for the same trajectory.
    def _global_sum(x):
        return lax.psum(x.astype(jnp.int32), (NODES_AXIS, TXS_AXIS))

    def _nodes_sum(x):
        return lax.psum(x.astype(jnp.int32), NODES_AXIS)

    zero = jnp.int32(0)
    ring_tel = (zero, zero, zero)
    if inflight.enabled(cfg):
        rt = inflight.ring_telemetry(ring, cfg, state.round)
        ring_tel = (_nodes_sum(rt.deliveries), _nodes_sum(rt.expiries),
                    _nodes_sum(rt.occupancy))
    cut = (inflight.partition_cut(cfg, state.round, offset, peers,
                                  n_global, state.fault_params)
           if inflight.enabled(cfg) else None)
    telemetry = SimTelemetry(
        polls=_global_sum(polled.sum()),
        votes_applied=_global_sum(votes_applied),
        flips=_global_sum((changed & jnp.logical_not(newly_final)).sum()),
        finalizations=_global_sum(newly_final.sum()),
        admissions=_global_sum(admissions),
        deliveries=ring_tel[0],
        expiries=ring_tel[1],
        ring_occupancy=ring_tel[2],
        partition_blocked=(zero if cut is None else _nodes_sum(cut.sum())),
        gossip_writes=(_global_sum(gossip_writes) if cfg.gossip else zero),
    )
    new_state = AvalancheSimState(
        records=records,
        added=added,
        valid=state.valid,
        score_rank=state.score_rank,
        poll_order=state.poll_order,
        poll_order_inv=state.poll_order_inv,
        byzantine=state.byzantine,
        alive=alive,
        latency_weight=state.latency_weight,
        finalized_at=finalized_at,
        round=state.round + 1,
        key=k_next,
        inflight=ring,
        fault_params=state.fault_params,
        # Trace plane (obs/trace.py): the row is assembled from the
        # psum'd counters above — identical on every shard, so the
        # replicated [S, M] buffer stays replicated and decodes to the
        # same rows the dense formula would produce for this trajectory.
        trace=obs_trace.write_round(state.trace, cfg, state.round,
                                    telemetry),
    )
    return new_state, telemetry


def _donate(donate: bool) -> tuple:
    """`donate_argnums` for the state argument — the shared knob every
    sharded driver threads through its jit so the ``[N, T]`` planes update
    in place instead of double-buffering in HBM."""
    return (0,) if donate else ()


# The collective allowlist (go_avalanche_tpu/analysis/hlo_audit.py): every
# (collective kind, mesh axes) pair this driver's lowered program may
# contain — psum on DECLARED axes only, nothing else.  The audit lowers
# the scan program on a small mesh and asserts set equality, so both an
# undeclared collective (an accidental all-gather of an [N, T] plane)
# and a stale manifest entry fail tier-1.
DECLARED_COLLECTIVES = frozenset({
    ("all_gather", (NODES_AXIS,)),    # packed preference plane [N, T/8]
                                      #   + the alive vector [N]
    ("all_to_all", (NODES_AXIS,)),    # gossip heard-plane owner exchange
    ("all_reduce", (NODES_AXIS,)),    # minority plane, ring counters
    ("all_reduce", (NODES_AXIS, TXS_AXIS)),  # telemetry + settled flag
})


def _shard_mapped(mesh, fn, track_finality: bool = True,
                  with_inflight: bool = False,
                  with_fault_params: bool = False,
                  trace_spec=None):
    specs = state_specs(track_finality, with_inflight, with_fault_params,
                        trace_spec)
    tel_specs = SimTelemetry(*([P()] * len(SimTelemetry._fields)))
    return shard_map(fn, mesh=mesh, in_specs=(specs,),
                     out_specs=(specs, tel_specs), check_vma=False)


def _reject_round_engine(cfg: AvalancheConfig) -> None:
    """The sharded drivers run the phased per-phase round: the
    megakernel's in-kernel gather needs the WHOLE node axis resident,
    which is exactly the axis these drivers shard away.  Reject rather
    than silently fall back (the PR-13 inert-knob rule)."""
    if cfg.round_engine != "phased":
        raise ValueError(
            "round_engine 'megakernel' is wired for the single-device "
            "dense avalanche round only; the sharded drivers keep the "
            "phased path (the fused gather needs the full node axis "
            "resident per device) — the knob would be inert here")


def make_sharded_round_step(mesh, cfg: AvalancheConfig = DEFAULT_CONFIG,
                            donate: bool = False):
    """Build a jitted one-round step over the mesh; call it with a (global)
    `AvalancheSimState` placed by `shard_state`.

    `donate=True` donates the input state to each call (in-place plane
    updates) — callers must chain ``state = step(state)[0]`` and never
    reuse a consumed state."""
    _reject_round_engine(cfg)
    n_tx = mesh.shape[TXS_AXIS]
    cache = {}

    def step(state: AvalancheSimState):
        n_global = state.records.votes.shape[0]
        track = state.finalized_at is not None
        asyncq = state.inflight is not None
        fparams = state.fault_params is not None
        traced = state.trace is not None
        key = (n_global, track, asyncq, fparams, traced)
        if key not in cache:
            cache[key] = jax.jit(
                _shard_mapped(
                    mesh, lambda s: _local_round(s, cfg, n_global, n_tx),
                    track_finality=track, with_inflight=asyncq,
                    with_fault_params=fparams,
                    trace_spec=obs_trace.replicated_spec(state.trace)),
                donate_argnums=_donate(donate))
        return cache[key](state)

    return step


def scan_program(mesh, state: AvalancheSimState,
                 cfg: AvalancheConfig = DEFAULT_CONFIG,
                 n_rounds: int = 100, donate: bool = False):
    """The jitted fixed-round sharded program `run_scan_sharded`
    executes — exposed unexecuted so `analysis/hlo_audit.py` lowers THE
    driver program, not a reconstruction of it (the
    `bench.flagship_program` seam, applied to the mesh drivers).  Only
    tree structure and shapes are read from `state`, so abstract
    (`jax.eval_shape`) states lower on any host."""
    _reject_round_engine(cfg)
    n_global = state.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_scan(s):
        def body(carry, _):
            new_s, tel = _local_round(carry, cfg, n_global, n_tx)
            return new_s, tel
        return lax.scan(body, s, None, length=n_rounds)

    return jax.jit(_shard_mapped(
        mesh, local_scan,
        track_finality=state.finalized_at is not None,
        with_inflight=state.inflight is not None,
        with_fault_params=state.fault_params is not None,
        trace_spec=obs_trace.replicated_spec(state.trace)),
        donate_argnums=_donate(donate))


def run_scan_sharded(
    mesh,
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 100,
    donate: bool = False,
) -> Tuple[AvalancheSimState, SimTelemetry]:
    """Fixed-round sharded run; one jit, collectives inside the scan."""
    return scan_program(mesh, state, cfg, n_rounds, donate)(state)


def settle_program(mesh, state: AvalancheSimState,
                   cfg: AvalancheConfig = DEFAULT_CONFIG,
                   max_rounds: int = 2000, donate: bool = False):
    """The jitted run-until-settled program `run_sharded` executes
    (while_loop + psum'd settled flag) — the audit seam twin of
    `scan_program`."""
    _reject_round_engine(cfg)
    n_global = state.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_run(s):
        def unsettled(st):
            n_local = st.records.votes.shape[0]
            nshard = lax.axis_index(NODES_AXIS)
            alive_local = lax.dynamic_slice(
                st.alive, (nshard * n_local,), (n_local,))
            fin = vr.has_finalized(st.records.confidence, cfg)
            pollable = (st.added & alive_local[:, None]
                        & st.valid[None, :] & jnp.logical_not(fin))
            return lax.psum(pollable.any().astype(jnp.int32),
                            (NODES_AXIS, TXS_AXIS)) > 0

        def cond(carry):
            st, live = carry
            return live & (st.round < max_rounds)

        def body(carry):
            st, _ = carry
            new_st, _ = _local_round(st, cfg, n_global, n_tx)
            return new_st, unsettled(new_st)

        final, _ = lax.while_loop(cond, body, (s, unsettled(s)))
        return final

    specs = state_specs(state.finalized_at is not None,
                        state.inflight is not None,
                        state.fault_params is not None,
                        obs_trace.replicated_spec(state.trace))
    fn = shard_map(local_run, mesh=mesh, in_specs=(specs,),
                   out_specs=specs, check_vma=False)
    return jax.jit(fn, donate_argnums=_donate(donate))


def run_sharded(
    mesh,
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 2000,
    donate: bool = False,
) -> AvalancheSimState:
    """Run until globally settled (psum'd flag) or `max_rounds`; one jit."""
    return settle_program(mesh, state, cfg, max_rounds, donate)(state)
