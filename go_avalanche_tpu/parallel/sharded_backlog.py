"""Mesh-sharded streaming backlog: north-star scale in bounded HBM.

`models/backlog` re-expressed under `jax.shard_map`: the dense ``[N, W]``
window shards exactly like the plain simulator (`parallel/sharded.py`), the
per-slot metadata shards with the txs axis, and the ``[B]`` backlog /
output planes stay replicated (1M txs of metadata is ~MBs — noise next to
the window state). The scheduler's collectives per step:

  * **settle test**     — `psum` over the nodes axis of the per-slot
    "some node still pending" bit (the reference's all-nodes-finalized
    condition, `examples/basic-preconcensus/main.go:159-161`).
  * **admission rank**  — an exclusive prefix over tx shards (all-gather of
    k scalars) so free slots across shards take backlog entries in the
    intended global score order without a cross-shard sort.
  * **output merge**    — retiring shards scatter their txs' outcomes into
    zero-initialized [B] planes; a `psum` over the txs axis merges them
    (each tx occupies exactly one slot, so writes never collide). On a
    nodes-only mesh this psum is a no-op.

The inner consensus round is `parallel/sharded._local_round`, unchanged.

Divergence from the unsharded scheduler (documented, tested): poll-order
score ranks are computed per tx shard (global rank needs a cross-shard
sort); with W <= max_element_poll — the recommended configuration — ranks
never matter because nothing is truncated.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu import traffic as tf
from go_avalanche_tpu.config import (
    AvalancheConfig,
    DEFAULT_CONFIG,
    suppress_taps,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.models.backlog import (
    NO_TX,
    Backlog,
    BacklogOutputs,
    BacklogSimState,
    BacklogTelemetry,
)
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.parallel import sharded
from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS, shard_map


def _traffic_specs(with_traffic: bool):
    """Replicated (`P()`) specs for the live-traffic plane — the draw is
    identical on every shard, like the backlog metadata it gates."""
    if not with_traffic:
        return None
    return tf.TrafficState(key=P(), arrived_idx=P(), arrival_round=P(),
                           lat_hist=P())


def backlog_state_specs(track_finality: bool = True,
                        with_inflight: bool = False,
                        with_fault_params: bool = False,
                        with_traffic: bool = False,
                        trace_spec=None) -> BacklogSimState:
    """PartitionSpecs for every leaf of `BacklogSimState`;
    `trace_spec` mirrors the scheduler-owned trace plane (replicated —
    `obs.trace.replicated_spec`)."""
    return BacklogSimState(
        sim=sharded.state_specs(track_finality, with_inflight,
                                with_fault_params, trace_spec),
        slot_tx=P(TXS_AXIS),
        slot_admit_round=P(TXS_AXIS),
        backlog=Backlog(score=P(), init_pref=P(), valid=P()),
        outputs=BacklogOutputs(settled=P(), accepted=P(), accept_votes=P(),
                               settle_round=P(), admit_round=P()),
        next_idx=P(),
        traffic=_traffic_specs(with_traffic),
    )


def shard_backlog_state(state: BacklogSimState, mesh) -> BacklogSimState:
    """Place a host-built backlog state onto the mesh."""
    state = state._replace(sim=state.sim._replace(
        inflight=inflight.repack_polled_for_shards(
            state.sim.inflight, state.sim.records.votes.shape[1],
            mesh.shape[TXS_AXIS])))
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state, backlog_state_specs(state.sim.finalized_at is not None,
                                   state.sim.inflight is not None,
                                   state.sim.fault_params is not None,
                                   state.traffic is not None,
                                   obs_trace.replicated_spec(
                                       state.sim.trace)))


def _merge_write(old, idx, value, b):
    """Replicated [B] plane update from per-shard scatters.

    `idx` entries == b are dropped. Writes are unique per tx across shards,
    so a psum of one-hot planes reconstructs them exactly.
    """
    dtype = old.dtype
    # psum promotes bools; carry bool planes through int32 and cast back so
    # scan carries keep their types.
    vdt = jnp.int32 if dtype == jnp.bool_ else dtype
    written = (jnp.zeros((b,), jnp.int32).at[idx].set(1, mode="drop"))
    vals = (jnp.zeros((b,), vdt).at[idx].set(value.astype(vdt), mode="drop"))
    written = lax.psum(written, TXS_AXIS)
    vals = lax.psum(vals, TXS_AXIS)
    return jnp.where(written > 0, vals.astype(dtype), old)


def _local_settled(state: BacklogSimState, cfg: AvalancheConfig) -> jax.Array:
    """bool [w_local]: globally-settled occupied slots (psum over nodes)."""
    sim = state.sim
    n_local = sim.records.votes.shape[0]
    nshard = lax.axis_index(NODES_AXIS)
    alive_local = lax.dynamic_slice(sim.alive, (nshard * n_local,),
                                    (n_local,))
    occupied = state.slot_tx != NO_TX
    fin = vr.has_finalized(sim.records.confidence, cfg)
    pending = sim.added & alive_local[:, None] & jnp.logical_not(fin)
    pending_any = lax.psum(pending.any(axis=0).astype(jnp.int32),
                           NODES_AXIS) > 0
    return occupied & (jnp.logical_not(pending_any)
                       | jnp.logical_not(sim.valid))


def _local_retire_and_refill(
    state: BacklogSimState,
    cfg: AvalancheConfig,
    refill: bool = True,
) -> Tuple[BacklogSimState, jax.Array]:
    """The scheduler pass on one shard; see `models/backlog`. Returns
    (new_state, globally-retired count)."""
    sim = state.sim
    w_local = sim.records.votes.shape[1]
    b = state.backlog.score.shape[0]
    settled = _local_settled(state, cfg)

    # --- retire: per-slot outcomes; node-axis sums via psum so every node
    # shard computes identical [w_local] planes.
    conf = sim.records.confidence
    fin = vr.has_finalized(conf, cfg)
    acc = vr.is_accepted(conf)
    accept_votes = lax.psum(
        (fin & acc & sim.added).sum(axis=0).astype(jnp.int32), NODES_AXIS)
    n_live = jnp.maximum(sim.alive.sum().astype(jnp.int32), 1)
    accepted = accept_votes * 2 > n_live

    idx = jnp.where(settled, state.slot_tx, b)
    out = state.outputs
    out = BacklogOutputs(
        settled=_merge_write(out.settled, idx,
                             jnp.ones((w_local,), jnp.bool_), b),
        accepted=_merge_write(out.accepted, idx, accepted, b),
        accept_votes=_merge_write(out.accept_votes, idx, accept_votes, b),
        settle_round=_merge_write(
            out.settle_round, idx,
            jnp.broadcast_to(sim.round, (w_local,)).astype(jnp.int32), b),
        admit_round=_merge_write(out.admit_round, idx,
                                 state.slot_admit_round, b),
    )

    # --- live traffic: per-shard latency deltas psum'd over the txs
    # axis (each slot lives in exactly one tx shard; integer adds, so
    # the replicated histogram matches the dense one bit-for-bit), and
    # admission gated on the replicated arrived watermark.
    traffic = state.traffic
    if traffic is not None:
        arr = traffic.arrival_round[jnp.clip(state.slot_tx, 0, b - 1)]
        delta = tf.latency_delta(cfg, sim.round - arr,
                                 settled.astype(jnp.int32))
        traffic = traffic._replace(
            lat_hist=traffic.lat_hist + lax.psum(delta, TXS_AXIS))

    # --- refill: global admission rank = exclusive prefix over tx shards.
    free = settled | (state.slot_tx == NO_TX)
    count_local = free.sum().astype(jnp.int32)
    counts = lax.all_gather(count_local, TXS_AXIS)        # [n_tx_shards]
    tshard = lax.axis_index(TXS_AXIS)
    prefix = jnp.where(jnp.arange(counts.shape[0]) < tshard,
                       counts, 0).sum()
    rank = prefix + jnp.cumsum(free.astype(jnp.int32)) - 1
    cand = state.next_idx + rank
    avail = b if traffic is None else jnp.minimum(jnp.int32(b),
                                                  traffic.arrived_idx)
    take = free & (cand < avail)
    if not refill:   # end-of-run harvest: record outcomes, admit nothing
        take = jnp.zeros_like(take)
    new_tx = jnp.where(take, cand, jnp.where(settled, NO_TX, state.slot_tx))
    n_taken = lax.psum(take.sum().astype(jnp.int32), TXS_AXIS)

    cand_safe = jnp.clip(cand, 0, b - 1)
    pref = state.backlog.init_pref[cand_safe]
    # Row-constant fresh values at [1, W]; the fill `where` broadcasts.
    # (Cost analysis shows XLA fused the explicit [N, W] broadcast this
    # replaces, so this is clarity, not traffic — PERF_NOTES.md.)
    fresh = vr.init_state(pref[None, :])

    def fill(plane, fresh_plane):
        return jnp.where(take[None, :], fresh_plane, plane)

    records = vr.VoteRecordState(
        votes=fill(sim.records.votes, fresh.votes),
        consider=fill(sim.records.consider, fresh.consider),
        confidence=fill(sim.records.confidence, fresh.confidence),
    )
    occupied_after = new_tx != NO_TX
    added = jnp.where(take[None, :], True,
                      sim.added & occupied_after[None, :])
    valid = jnp.where(take, state.backlog.valid[cand_safe],
                      sim.valid & occupied_after)
    score = jnp.where(occupied_after,
                      state.backlog.score[jnp.clip(new_tx, 0, b - 1)],
                      jnp.int32(-2**31 + 1))
    finalized_at = av.reset_finality(sim.finalized_at, take)

    # Per-shard ranks (module note), with the hoisted poll-order pair
    # refreshed in the same single argsort.
    score_rank, poll_order, poll_order_inv = av.score_rank_with_orders(score)
    new_sim = sim._replace(
        records=records,
        added=added,
        valid=valid,
        score_rank=score_rank,
        poll_order=poll_order,
        poll_order_inv=poll_order_inv,
        finalized_at=finalized_at,
        # In-flight responses for a retired slot must not land on its
        # NEW occupant (see models/backlog); columns are shard-local.
        inflight=inflight.clear_columns(sim.inflight, settled | take),
    )
    retired = lax.psum(settled.sum().astype(jnp.int32), TXS_AXIS)
    return BacklogSimState(
        sim=new_sim,
        slot_tx=new_tx,
        slot_admit_round=jnp.where(take, sim.round, state.slot_admit_round),
        backlog=state.backlog,
        outputs=out,
        next_idx=state.next_idx + n_taken,
        traffic=traffic,
    ), retired


def _local_step(
    state: BacklogSimState,
    cfg: AvalancheConfig,
    n_global: int,
    n_tx_shards: int,
) -> Tuple[BacklogSimState, BacklogTelemetry]:
    round_val = state.sim.round
    arrivals = jnp.int32(0)
    if state.traffic is not None:
        # The draw is on replicated state with the GLOBAL occupancy
        # (psum over tx shards), so every shard realizes the dense
        # arrival sequence bit-for-bit (tests/test_traffic.py).
        w_local = state.slot_tx.shape[0]
        occ = lax.psum((state.slot_tx != NO_TX).sum().astype(jnp.int32),
                       TXS_AXIS)
        new_traffic, arrivals = tf.arrive(state.traffic, cfg,
                                          state.sim.round, occ,
                                          w_local * n_tx_shards)
        state = state._replace(traffic=new_traffic)
    state, retired = _local_retire_and_refill(state, cfg)
    # The scheduler owns the trace plane (models/backlog contract): the
    # inner round runs trace-suppressed, the full scheduler record is
    # written below from psum'd (replicated) counters.
    new_sim, round_tel = sharded._local_round(state.sim, suppress_taps(cfg),
                                              n_global, n_tx_shards)
    occupied = lax.psum((state.slot_tx != NO_TX).sum().astype(jnp.int32),
                        TXS_AXIS)
    tel = BacklogTelemetry(
        round=round_tel,
        retired=retired,
        occupied=occupied,
        backlog_left=state.backlog.score.shape[0] - state.next_idx,
        traffic=(None if state.traffic is None
                 else tf.traffic_telemetry(state.traffic, arrivals)),
    )
    new_sim = new_sim._replace(
        trace=obs_trace.write_round(new_sim.trace, cfg, round_val, tel))
    return state._replace(sim=new_sim), tel


def _shard_mapped(mesh, fn, with_tel=True, track_finality: bool = True,
                  with_inflight: bool = False,
                  with_fault_params: bool = False,
                  with_traffic: bool = False,
                  trace_spec=None):
    specs = backlog_state_specs(track_finality, with_inflight,
                                with_fault_params, with_traffic,
                                trace_spec)
    if with_tel:
        tel_specs = BacklogTelemetry(
            round=av.SimTelemetry(
                *([P()] * len(av.SimTelemetry._fields))),
            retired=P(), occupied=P(), backlog_left=P(),
            traffic=(tf.TrafficTelemetry(
                *([P()] * len(tf.TrafficTelemetry._fields)))
                if with_traffic else None))
        out_specs = (specs, tel_specs)
    else:
        out_specs = specs
    return shard_map(fn, mesh=mesh, in_specs=(specs,),
                     out_specs=out_specs, check_vma=False)


def make_sharded_backlog_step(mesh, cfg: AvalancheConfig = DEFAULT_CONFIG,
                              donate: bool = False):
    """Jitted (state) -> (state, telemetry) scheduler+round step.
    `donate=True` donates the input state per call (chain, never reuse)."""
    n_tx = mesh.shape[TXS_AXIS]
    cache = {}

    def step(state: BacklogSimState):
        n_global = state.sim.records.votes.shape[0]
        track = state.sim.finalized_at is not None
        asyncq = state.sim.inflight is not None
        fparams = state.sim.fault_params is not None
        arriv = state.traffic is not None
        traced = state.sim.trace is not None
        key = (n_global, track, asyncq, fparams, arriv, traced)
        if key not in cache:
            cache[key] = jax.jit(
                _shard_mapped(
                    mesh, lambda s: _local_step(s, cfg, n_global, n_tx),
                    track_finality=track, with_inflight=asyncq,
                    with_fault_params=fparams, with_traffic=arriv,
                    trace_spec=obs_trace.replicated_spec(
                        state.sim.trace)),
                donate_argnums=sharded._donate(donate))
        return cache[key](state)

    return step


# Collective allowlist (analysis/hlo_audit.py): the streaming scheduler
# adds txs-axis merges (one-hot retire/refill psums, admission-count
# all-gather — a [n_tx_shards] vector, never a plane) on top of the
# inner round's node-axis surface.
DECLARED_COLLECTIVES = frozenset({
    ("all_gather", (NODES_AXIS,)),
    ("all_gather", (TXS_AXIS,)),      # per-shard admission counts
    ("all_to_all", (NODES_AXIS,)),
    ("all_reduce", (NODES_AXIS,)),
    ("all_reduce", (TXS_AXIS,)),      # retire/refill one-hot merges,
                                      #   occupancy, traffic deltas
    ("all_reduce", (NODES_AXIS, TXS_AXIS)),
})


def scan_program(mesh, state: BacklogSimState,
                 cfg: AvalancheConfig = DEFAULT_CONFIG,
                 n_rounds: int = 100, donate: bool = False):
    """The jitted fixed-round program `run_scan_sharded_backlog`
    executes — exposed unexecuted so `analysis/hlo_audit.py` lowers THE
    driver program (the `bench.flagship_program` seam).  Only tree
    structure and shapes are read from `state`."""
    n_global = state.sim.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_scan(s):
        def body(carry, _):
            new_s, tel = _local_step(carry, cfg, n_global, n_tx)
            return new_s, tel
        return lax.scan(body, s, None, length=n_rounds)

    return jax.jit(_shard_mapped(
        mesh, local_scan,
        track_finality=state.sim.finalized_at is not None,
        with_inflight=state.sim.inflight is not None,
        with_fault_params=state.sim.fault_params is not None,
        with_traffic=state.traffic is not None,
        trace_spec=obs_trace.replicated_spec(state.sim.trace)),
        donate_argnums=sharded._donate(donate))


def run_scan_sharded_backlog(
    mesh,
    state: BacklogSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 100,
    donate: bool = False,
) -> Tuple[BacklogSimState, BacklogTelemetry]:
    """Fixed-round sharded stream; one jit, collectives inside the scan."""
    return scan_program(mesh, state, cfg, n_rounds, donate)(state)


def settle_program(mesh, state: BacklogSimState,
                   cfg: AvalancheConfig = DEFAULT_CONFIG,
                   max_rounds: int = 100_000, donate: bool = False):
    """The jitted drain-to-settlement program `run_sharded_backlog`
    executes (while_loop + harvest pass) — the audit seam twin of
    `scan_program`."""
    n_global = state.sim.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_run(s):
        def undrained(st: BacklogSimState) -> jax.Array:
            b = st.backlog.score.shape[0]
            unsettled = ((st.slot_tx != NO_TX)
                         & jnp.logical_not(_local_settled(st, cfg)))
            any_left = lax.psum(unsettled.any().astype(jnp.int32),
                                TXS_AXIS) > 0
            return (st.next_idx < b) | any_left

        def cond(carry):
            st, live = carry
            return live & (st.sim.round < max_rounds)

        def body(carry):
            st, _ = carry
            new_st, _ = _local_step(st, cfg, n_global, n_tx)
            return new_st, undrained(new_st)

        final, _ = lax.while_loop(cond, body, (s, undrained(s)))
        final, _ = _local_retire_and_refill(final, cfg, refill=False)
        return final

    return jax.jit(_shard_mapped(
        mesh, local_run, with_tel=False,
        track_finality=state.sim.finalized_at is not None,
        with_inflight=state.sim.inflight is not None,
        with_fault_params=state.sim.fault_params is not None,
        with_traffic=state.traffic is not None,
        trace_spec=obs_trace.replicated_spec(state.sim.trace)),
        donate_argnums=sharded._donate(donate))


def run_sharded_backlog(
    mesh,
    state: BacklogSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 100_000,
    donate: bool = False,
) -> BacklogSimState:
    """Stream the whole backlog to settlement over the mesh; one jit.

    Ends with a harvest pass so the last window's outcomes are recorded.
    """
    return settle_program(mesh, state, cfg, max_rounds, donate)(state)
