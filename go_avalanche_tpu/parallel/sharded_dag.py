"""Mesh-sharded conflict-set DAG: BASELINE config "byzantine mix, sharded DAG".

`models/dag.round_step` re-expressed under `jax.shard_map` over the
``(nodes, txs)`` mesh.  The DAG adds two things to the plain sharded round
(`parallel/sharded.py`) and both stay collective-free on the txs axis:

  * the **response plane** is preferred-in-set rather than is-accepted —
    computed per shard with local segment ops (legal because conflict sets
    must not straddle tx shards; validated at `shard_dag_state` time), then
    bit-packed and all-gathered over the nodes axis exactly like the plain
    preference plane;
  * the **rival-settled freeze** (a set settles for a node once any member
    finalizes accepted, `models/dag.py`) is likewise a per-shard segment
    pass over local columns.

Randomness follows `parallel/sharded.py`: fault draws fold in only the
nodes-shard index so one peer response covers all of a node's polled
targets; the equivocation coin additionally folds the txs-shard index
(it is per-target by definition).
"""

from __future__ import annotations

import dataclasses

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu.config import (
    AdversaryStrategy,
    AvalancheConfig,
    DEFAULT_CONFIG,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.models import dag as dag_model
from go_avalanche_tpu.models.dag import DagSimState
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import adversary, exchange, inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.ops.bitops import pack_bool_plane
from go_avalanche_tpu.ops.sampling import draw_peers
from go_avalanche_tpu.parallel import sharded
from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS, shard_map


def dag_state_specs(n_sets: int,
                    set_size: Optional[int] = None,
                    track_finality: bool = True,
                    with_inflight: bool = False,
                    with_fault_params: bool = False,
                    trace_spec=None) -> DagSimState:
    """PartitionSpecs for every leaf of `DagSimState`.

    `n_sets` and `set_size` ride along as the pytree's static aux data so
    the spec tree and the value tree unflatten identically;
    `track_finality=False` mirrors a base state whose `finalized_at` leaf
    is None (`models/avalanche.init`); `with_inflight=True` adds the
    async-query ring specs (`sharded.state_specs`);
    `with_fault_params=True` mirrors realized stochastic fault
    parameters (replicated scalars); `trace_spec` mirrors the on-device
    trace plane (replicated — `obs.trace.replicated_spec`).
    """
    return DagSimState(base=sharded.state_specs(track_finality,
                                                with_inflight,
                                                with_fault_params,
                                                trace_spec),
                       conflict_set=P(TXS_AXIS), n_sets=n_sets,
                       set_size=set_size)


def shard_dag_state(state: DagSimState, mesh) -> DagSimState:
    """Place a host-built DAG state onto the mesh.

    Validates the sharding-compatibility contract from the model docstring
    (`models/dag.py`): no conflict set may straddle a txs-shard boundary,
    and set ids must be sorted so each shard's ids form one contiguous
    range (the standard ``idx // set_size`` partition satisfies both).
    """
    n_tx_shards = mesh.shape[TXS_AXIS]
    cs = np.asarray(jax.device_get(state.conflict_set))
    t = cs.shape[0]
    if t % n_tx_shards:
        raise ValueError(f"txs ({t}) must divide by tx shards "
                         f"({n_tx_shards})")
    if (np.diff(cs) < 0).any():
        raise ValueError("conflict_set ids must be sorted non-decreasing "
                         "for tx sharding")
    blocks = cs.reshape(n_tx_shards, t // n_tx_shards)
    for i in range(n_tx_shards - 1):
        if blocks[i, -1] == blocks[i + 1, 0]:
            raise ValueError(
                f"conflict set {int(blocks[i, -1])} straddles the boundary "
                f"between tx shards {i} and {i + 1}")
    state = dataclasses.replace(state, base=state.base._replace(
        inflight=inflight.repack_polled_for_shards(
            state.base.inflight, t, n_tx_shards)))
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state, dag_state_specs(state.n_sets, state.set_size,
                               state.base.finalized_at is not None,
                               state.base.inflight is not None,
                               state.base.fault_params is not None,
                               obs_trace.replicated_spec(
                                   state.base.trace)))


def _local_sets(conflict_set_local: jax.Array) -> jax.Array:
    """Re-base this shard's set ids to 0..(local sets - 1).

    With sorted, non-straddling sets the local ids are one contiguous
    range; subtracting the first id localizes them.  Callers use the
    global `n_sets` as a safe static bound for the local segment count.
    """
    return conflict_set_local - conflict_set_local[0]


def _local_round(
    state: DagSimState,
    cfg: AvalancheConfig,
    n_global: int,
    n_tx_shards: int,
) -> Tuple[DagSimState, av.SimTelemetry]:
    """One DAG round on this shard's block; collectives on nodes axis only."""
    base = state.base
    n_local, t_local = base.records.votes.shape
    nshard = lax.axis_index(NODES_AXIS)
    offset = nshard * n_local
    cs_local = _local_sets(state.conflict_set)

    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(base.key, 5)
    k_sample = jax.random.fold_in(k_sample, nshard)
    k_byz = jax.random.fold_in(k_byz, nshard)
    k_drop = jax.random.fold_in(k_drop, nshard)
    k_churn = jax.random.fold_in(k_churn, nshard)

    fin = vr.has_finalized(base.records.confidence, cfg)
    fin_acc = fin & vr.is_accepted(base.records.confidence)
    alive_local = lax.dynamic_slice(base.alive, (offset,), (n_local,))

    # --- rival-settled freeze: local set pass over local columns (the
    # non-straddling contract makes the fixed partition locally contiguous,
    # so the reshape fast path applies per shard too).
    if state.set_size is not None:
        if t_local % state.set_size:
            # `shard_dag_state` placement guarantees this; re-validate for
            # states placed by other means so the failure names the
            # contract instead of surfacing as a reshape-size trace error.
            raise ValueError(
                f"set_size={state.set_size} must divide the per-shard tx "
                f"width ({t_local}) for the fixed-partition fast path")
        rival_settled = (dag_model.set_any_fixed(fin_acc, state.set_size)
                         & jnp.logical_not(fin_acc))
    else:
        set_done = jax.ops.segment_max(fin_acc.astype(jnp.uint8).T, cs_local,
                                       num_segments=state.n_sets)
        rival_settled = (set_done.T[:, cs_local] > 0) \
            & jnp.logical_not(fin_acc)

    pollable = (base.added & alive_local[:, None] & base.valid[None, :]
                & jnp.logical_not(fin) & jnp.logical_not(rival_settled))
    # Global 4096-inv cap across tx shards, as in `parallel/sharded.py`.
    polled = sharded.global_capped_poll_mask(pollable, base.score_rank,
                                             cfg.max_element_poll,
                                             n_tx_shards,
                                             base.poll_order,
                                             base.poll_order_inv)

    # The shared draw dispatch, exactly as in `parallel/sharded`.
    peers, self_draw = draw_peers(k_sample, cfg, base.latency_weight,
                                  base.alive, n_global,
                                  n_local=n_local, id_offset=offset)
    lie = adversary.lie_mask(k_byz, peers, base.byzantine, cfg)
    responded = base.alive[peers]
    if self_draw is not None:
        responded &= jnp.logical_not(self_draw)
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    # --- response plane: preferred-in-set, packed + all-gathered.
    if state.set_size is not None:
        prefs_local = dag_model.preferred_in_set_fixed(
            base.records.confidence, state.set_size)
    else:
        prefs_local = dag_model.preferred_in_set(base.records.confidence,
                                                 cs_local, state.n_sets)
    packed_global = lax.all_gather(pack_bool_plane(prefs_local), NODES_AXIS,
                                   axis=0, tiled=True)
    if cfg.adversary_strategy is AdversaryStrategy.OPPOSE_MAJORITY:
        minority_t = sharded._global_minority_plane(prefs_local, n_global)
    else:
        minority_t = jnp.zeros((t_local,), jnp.bool_)  # unused
    k_vote = k_byz
    if (cfg.adversary_strategy is AdversaryStrategy.EQUIVOCATE
            or cfg.adversary_policy == "split_vote"):
        # Per-target coins must differ across tx shards (the
        # `parallel/sharded.py` equivocation rule).
        k_vote = jax.random.fold_in(k_byz, lax.axis_index(TXS_AXIS))

    # --- adaptive adversary: the psum'd context twin, on the
    # preferred-in-set response plane (`parallel/sharded.py` recipe).
    pol = sharded._policy_ctx_sharded(cfg, base.records, prefs_local,
                                      base.byzantine, base.latency_weight,
                                      offset, n_local)
    lie, responded, withheld = adversary.apply_policy_issue(cfg, pol, lie,
                                                            responded)

    ring = base.inflight
    if inflight.enabled(cfg):
        # Async query lifecycle (ops/inflight.py): delivery gathers index
        # the all-gathered preferred-in-set plane — same observation
        # convention as the synchronous round.
        lat = inflight.draw_latency(k_sample, cfg, peers,
                                    base.latency_weight, n_global,
                                    row_offset=offset)
        lat = adversary.apply_policy_latency(cfg, lat, lie, withheld)
        lat = inflight.apply_faults(lat, cfg, base.round, offset,
                                    peers, n_global, base.fault_params)
        ring = inflight.enqueue(base.inflight, base.round, peers, lat,
                                responded, lie, polled)
        records, changed, votes_applied = inflight.deliver_multi_engine(
            ring, base.records, cfg, packed_global, minority_t, k_vote,
            base.round, t_local, live_rows=alive_local, ctx=pol)
    else:
        yes_pack, consider_pack = exchange.gather_vote_packs(
            packed_global, peers, responded, lie, k_vote, cfg, minority_t,
            t_local, pol)

        records, changed = vr.register_packed_votes_engine(
            base.records, yes_pack, consider_pack, cfg.k, cfg,
            update_mask=polled)
        votes_applied = (av.popcnt_plane(consider_pack) * polled).sum()

    fin_after = vr.has_finalized(records.confidence, cfg)
    newly_final = fin_after & jnp.logical_not(fin)
    finalized_at = av.stamp_finality(base.finalized_at, newly_final,
                                     base.round)

    # Dynamic membership: each node-shard toggles its own rows, then the
    # replicated [N] plane is rebuilt with one all-gather (the
    # `parallel/sharded.py` recipe).
    alive = base.alive
    alive_local_new = alive_local
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability,
                                      (n_local,))
        alive_local_new = jnp.logical_xor(alive_local, toggle)
    alive_local_new = inflight.apply_churn_bursts(alive_local_new, cfg,
                                                  base.round, k_churn)
    if cfg.churn_probability > 0.0 or cfg.churn_burst_events():
        alive = lax.all_gather(alive_local_new, NODES_AXIS, axis=0,
                               tiled=True)

    def _global_sum(x):
        return lax.psum(x.astype(jnp.int32), (NODES_AXIS, TXS_AXIS))

    # Ring counters: node-row-sharded, TX-REPLICATED planes — psum over
    # the nodes axis only (see parallel/sharded.py); no gossip in the
    # DAG round, so those counters stay statically zero.
    def _nodes_sum(x):
        return lax.psum(x.astype(jnp.int32), NODES_AXIS)

    zero = jnp.int32(0)
    ring_tel = (zero, zero, zero)
    if inflight.enabled(cfg):
        rt = inflight.ring_telemetry(ring, cfg, base.round)
        ring_tel = (_nodes_sum(rt.deliveries), _nodes_sum(rt.expiries),
                    _nodes_sum(rt.occupancy))
    cut = (inflight.partition_cut(cfg, base.round, offset, peers,
                                  n_global, base.fault_params)
           if inflight.enabled(cfg) else None)
    telemetry = av.SimTelemetry(
        polls=_global_sum(polled.sum()),
        votes_applied=_global_sum(votes_applied),
        flips=_global_sum((changed & jnp.logical_not(newly_final)).sum()),
        finalizations=_global_sum(newly_final.sum()),
        admissions=jnp.int32(0),
        deliveries=ring_tel[0],
        expiries=ring_tel[1],
        ring_occupancy=ring_tel[2],
        partition_blocked=(zero if cut is None else _nodes_sum(cut.sum())),
        gossip_writes=jnp.int32(0),
    )
    new_base = av.AvalancheSimState(
        records=records, added=base.added, valid=base.valid,
        score_rank=base.score_rank, poll_order=base.poll_order,
        poll_order_inv=base.poll_order_inv, byzantine=base.byzantine,
        alive=alive, latency_weight=base.latency_weight,
        finalized_at=finalized_at, round=base.round + 1, key=k_next,
        inflight=ring, fault_params=base.fault_params,
        # Replicated trace plane: the row comes from the psum'd
        # counters above, identical on every shard (obs/trace.py).
        trace=obs_trace.write_round(base.trace, cfg, base.round,
                                    telemetry))
    return DagSimState(new_base, state.conflict_set, state.n_sets,
                       state.set_size), telemetry


def _shard_mapped(mesh, n_sets: int, fn, tel: bool = True,
                  set_size: Optional[int] = None,
                  track_finality: bool = True,
                  with_inflight: bool = False,
                  with_fault_params: bool = False,
                  trace_spec=None):
    specs = dag_state_specs(n_sets, set_size, track_finality,
                            with_inflight, with_fault_params, trace_spec)
    if tel:
        tel_specs = av.SimTelemetry(*([P()] * len(av.SimTelemetry._fields)))
        out_specs = (specs, tel_specs)
    else:
        out_specs = specs
    return shard_map(fn, mesh=mesh, in_specs=(specs,),
                     out_specs=out_specs, check_vma=False)


def make_sharded_dag_round_step(mesh, cfg: AvalancheConfig = DEFAULT_CONFIG,
                                donate: bool = False):
    """Build a jitted one-round DAG step over the mesh; call it with a
    (global) `DagSimState` placed by `shard_dag_state`.  `donate=True`
    donates the input state per call (chain, never reuse)."""
    sharded._reject_round_engine(cfg)
    cache = {}

    n_tx = mesh.shape[TXS_AXIS]

    def step(state: DagSimState):
        key = (state.base.records.votes.shape[0], state.n_sets,
               state.set_size, state.base.finalized_at is not None,
               state.base.inflight is not None,
               state.base.fault_params is not None,
               state.base.trace is not None)
        if key not in cache:
            n_global = key[0]
            cache[key] = jax.jit(_shard_mapped(
                mesh, state.n_sets,
                lambda s: _local_round(s, cfg, n_global, n_tx),
                set_size=state.set_size, track_finality=key[3],
                with_inflight=key[4], with_fault_params=key[5],
                trace_spec=obs_trace.replicated_spec(state.base.trace)),
                donate_argnums=sharded._donate(donate))
        return cache[key](state)

    return step


# Collective allowlist (analysis/hlo_audit.py): the conflict-DAG round
# gathers the packed preference plane over nodes and psums telemetry /
# the settled flag over both axes; async configs add the node-axis ring
# psums.  Segment reductions stay shard-local (sets never straddle tx
# shards — shard_dag_state validates) and the DAG gossip path never
# lowers an all_to_all here.
DECLARED_COLLECTIVES = frozenset({
    ("all_gather", (NODES_AXIS,)),
    ("all_reduce", (NODES_AXIS,)),      # ring counters (async configs)
    ("all_reduce", (NODES_AXIS, TXS_AXIS)),
})


def settle_program(mesh, state: DagSimState,
                   cfg: AvalancheConfig = DEFAULT_CONFIG,
                   max_rounds: int = 2000, donate: bool = False):
    """The jitted run-until-resolved program `run_sharded_dag` executes
    — exposed unexecuted so `analysis/hlo_audit.py` lowers THE driver
    program (the `bench.flagship_program` seam).  Only tree structure
    and shapes are read from `state`; abstract states lower fine."""
    sharded._reject_round_engine(cfg)
    n_global = state.base.records.votes.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_run(s):
        def unresolved(st):
            base = st.base
            n_local = base.records.votes.shape[0]
            nshard = lax.axis_index(NODES_AXIS)
            alive_local = lax.dynamic_slice(
                base.alive, (nshard * n_local,), (n_local,))
            cs_local = _local_sets(st.conflict_set)
            fin_acc = (vr.has_finalized(base.records.confidence, cfg)
                       & vr.is_accepted(base.records.confidence))
            if st.set_size is not None:
                set_done_t = dag_model.set_any_fixed(fin_acc, st.set_size)
            else:
                set_done = jax.ops.segment_max(
                    fin_acc.astype(jnp.uint8).T, cs_local,
                    num_segments=st.n_sets)
                set_done_t = set_done.T[:, cs_local] > 0
            open_sets = (jnp.logical_not(set_done_t)
                         & alive_local[:, None] & base.valid[None, :])
            return lax.psum(open_sets.any().astype(jnp.int32),
                            (NODES_AXIS, TXS_AXIS)) > 0

        def cond(carry):
            st, live = carry
            return live & (st.base.round < max_rounds)

        def body(carry):
            st, _ = carry
            new_st, _ = _local_round(st, cfg, n_global, n_tx)
            return new_st, unresolved(new_st)

        final, _ = lax.while_loop(cond, body, (s, unresolved(s)))
        return final

    fn = _shard_mapped(mesh, state.n_sets, local_run, tel=False,
                       set_size=state.set_size,
                       track_finality=state.base.finalized_at is not None,
                       with_inflight=state.base.inflight is not None,
                       with_fault_params=(state.base.fault_params
                                          is not None),
                       trace_spec=obs_trace.replicated_spec(
                           state.base.trace))
    return jax.jit(fn, donate_argnums=sharded._donate(donate))


def run_sharded_dag(
    mesh,
    state: DagSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 2000,
    donate: bool = False,
) -> DagSimState:
    """Run until every (live node, set) resolved globally, or `max_rounds`;
    one jit, early exit via a psum'd settled flag."""
    return settle_program(mesh, state, cfg, max_rounds, donate)(state)
