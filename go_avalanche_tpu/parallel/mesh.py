"""Device mesh construction for the simulators.

The scale-out design (SURVEY.md sections 2.4 item 3, 5 "distributed
communication backend"): the ``[nodes, txs]`` state shards over a 2D mesh —

  axis "nodes":  data-parallel rows; the ONLY axis that communicates
                 (packed-preference all-gather, gossip reduce-scatter,
                 telemetry psum), riding ICI within a slice.
  axis "txs":    embarrassingly parallel columns (a vote for target t only
                 touches column t), so txs-sharding needs no collectives at
                 all — the natural DCN / multi-slice axis.

This replaces the reference's absence of any distributed backend (its
"network" is a map of ids, `net.go:11-31`, and a mutex-guarded method call,
`examples/basic-preconcensus/main.go:168-193`).
"""

from __future__ import annotations

import inspect
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

NODES_AXIS = "nodes"
TXS_AXIS = "txs"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` across jax versions — the one spot that knows the API.

    Newer jax exposes top-level `jax.shard_map`; older releases (this
    container ships 0.4.37) only have
    `jax.experimental.shard_map.shard_map`.  The replication-check kwarg
    was renamed `check_rep` -> `check_vma` SEPARATELY from the top-level
    promotion, so the dispatch probes the actual signature rather than
    treating one change as a proxy for the other.  Every sharded driver
    routes through this wrapper so both probes live in exactly one place.
    """
    if hasattr(jax, "shard_map"):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn
    kwarg = ("check_vma" if "check_vma" in inspect.signature(fn).parameters
             else "check_rep")
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{kwarg: check_vma})


def make_mesh(
    n_node_shards: Optional[int] = None,
    n_tx_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(nodes, txs)`` mesh over the given (default: all) devices.

    With defaults, all devices go to the nodes axis.  `n_node_shards *
    n_tx_shards` must equal the device count.
    """
    if devices is None:
        devices = jax.devices()
    n_dev = len(devices)
    if n_node_shards is None:
        if n_dev % n_tx_shards:
            raise ValueError(f"{n_dev} devices not divisible by "
                             f"n_tx_shards={n_tx_shards}")
        n_node_shards = n_dev // n_tx_shards
    if n_node_shards * n_tx_shards != n_dev:
        raise ValueError(
            f"mesh {n_node_shards}x{n_tx_shards} != {n_dev} devices")
    dev_array = np.asarray(devices).reshape(n_node_shards, n_tx_shards)
    return Mesh(dev_array, (NODES_AXIS, TXS_AXIS))
