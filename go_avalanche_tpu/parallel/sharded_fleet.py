"""Fleet-of-sharded-sims: the Monte-Carlo TRIAL axis laid across the mesh.

The fleet (`go_avalanche_tpu/fleet.py`) vmaps WHOLE sims over a batched
seed axis — one compiled program per config point, but one device.  The
sharded drivers (`parallel/sharded*.py`) shard ONE sim's node/tx planes
— many devices, one trajectory.  This module composes them along the
axis the statistics actually need: the trial axis ``F`` is laid out
over the mesh (``P(('trials', 'nodes'))`` — the 2-D spelling; a 1-D
``P('trials')`` mesh is the ``n_node_shards=1`` special case), so ``D``
devices each run ``F/D`` whole DENSE sims — init-from-key, the full
`round_step` scan, and the in-graph outcome reduction — inside ONE
compiled program per config point.

Because each trial's computation is the dense per-trial program
unchanged (the vmap merely partitions the batch), the sharded fleet is
BIT-IDENTICAL to the dense fleet on the same seeds — the established
dense-vs-sharded acceptance pattern, pinned by
tests/test_sharded_fleet.py (outcome vectors, realizations and trace
planes leaf-exact; summary rows identical).  Wilson CIs stay host-side
and unchanged.

Two program families share the mesh:

  * `fleet_driver_program` — the `fleet.run_fleet(mesh=...)` seam:
    ``keys [F] -> (TrialOutcome [F], FleetCounts, telemetry [F, R],
    trace [F, S, M] | None)``.  Per-trial vectors are **all-gathered**
    over the trial axes (every device — and the host — reads the same
    ``[F]`` vectors the dense fleet produces) and the summary counts
    are **psum'd** in-graph (`FleetCounts`), cross-checked against the
    gathered vectors by `run_fleet` (the PR-8 sharded self-consistency
    pattern).
  * `fleet_scan_program` — the `bench.py --fleet F --mesh A,B` timed
    program (pinned as `fleet_sharded`): a fleet-stacked flagship
    state, DONATED, each device scanning its ``F/D`` trials in place.
    Trials never communicate, so the program carries ZERO collectives —
    the embarrassing parallelism is the whole perf story (the VMEM-knee
    table, `benchmarks/vmem_knee.py`, prices exactly this layout).  On
    a 1-device mesh it collapses to `bench.fleet_program` — byte-
    identical to the archived `fleet_small` pin
    (`hlo_pin.py --verify-off-path`).

Randomness: nothing here folds a shard index — each trial consumes its
own per-trial key exactly as the dense fleet splits them, which is what
makes the bit-parity hold (contrast `parallel/sharded.py`, where the
per-shard PRNG streams differ from dense by design).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu.parallel.mesh import NODES_AXIS, shard_map

TRIALS_AXIS = "trials"

# The trial axis is laid over BOTH mesh axes (row-major: trials-major,
# nodes-minor — the same order `jax.random.split` lays the keys out),
# so a (A, B) mesh shards F trials over A*B devices.  One spelling,
# shared by every in/out spec in this module and the footprint model
# (`benchmarks/mem_pin.py` accounts the per-device shard shapes with
# exactly this spec).
FLEET_SPEC = P((TRIALS_AXIS, NODES_AXIS))

# The collective allowlist (go_avalanche_tpu/analysis/hlo_audit.py —
# the manifest convention every sharded driver declares): the driver
# program gathers the per-trial outcome/telemetry/trace vectors and
# psums the summary counts over the trial axes; NOTHING else may
# communicate (a collective touching an [N, T] plane would mean a trial
# leaked into another trial's stream).  The bench scan program
# (`fleet_scan_program`) lowers ZERO collectives — the audit asserts
# that too (tests/test_sharded_fleet.py, analysis/hlo_audit.py).
DECLARED_COLLECTIVES = frozenset({
    ("all_gather", (TRIALS_AXIS, NODES_AXIS)),  # per-trial vectors [F, ...]
    ("all_reduce", (TRIALS_AXIS, NODES_AXIS)),  # FleetCounts psums
})


class FleetCounts(NamedTuple):
    """The in-graph summary reduction, psum'd over the trial axes and
    replicated on every device — the counts `FleetResult.summary` rows
    are built from, cross-checked by `fleet.run_fleet` against the
    gathered per-trial vectors (a mismatch means the gather reordered
    or dropped a trial — fail loudly, never mislabel a phase row)."""

    trials: jax.Array      # int32 — global trial count (Σ local F/D)
    violations: jax.Array  # int32 — Σ TrialOutcome.violation
    settled: jax.Array     # int32 — Σ TrialOutcome.settled
    stalled: jax.Array     # int32 — Σ TrialOutcome.stalled


def make_fleet_mesh(n_trial_shards: int, n_node_shards: int = 1,
                    devices: Optional[Sequence[jax.Device]] = None
                    ) -> Mesh:
    """A ``(trials, nodes)`` mesh over the FIRST ``A * B`` devices.

    Unlike `parallel.mesh.make_mesh` (which claims every device), the
    fleet mesh takes a prefix — the audit/CI harness exposes 8 virtual
    devices and the 2x2 parity mesh must build under it, exactly like
    `analysis/hlo_audit._audit_mesh`.  The trial axis spans BOTH axes
    (`FLEET_SPEC`), so today the 2-D spelling is a device-count
    factorization; the `nodes` axis keeps the canonical name so the
    full trials-x-node-plane composition (ROADMAP follow-up) can claim
    it without re-speccing the trial layout.
    """
    if n_trial_shards < 1 or n_node_shards < 1:
        raise ValueError(f"fleet mesh axes must be >= 1, got "
                         f"{n_trial_shards}x{n_node_shards}")
    need = n_trial_shards * n_node_shards
    if devices is None:
        devices = jax.devices()
    if len(devices) < need:
        raise ValueError(
            f"fleet mesh {n_trial_shards}x{n_node_shards} needs {need} "
            f"devices, found {len(devices)} — run under the tier-1 "
            f"harness (8 virtual CPU devices) or on hardware")
    dev_array = np.asarray(devices[:need]).reshape(n_trial_shards,
                                                   n_node_shards)
    return Mesh(dev_array, (TRIALS_AXIS, NODES_AXIS))


def mesh_devices(mesh: Optional[Mesh]) -> int:
    """Device count of a fleet mesh (0 for None) — the one spelling of
    'does this mesh actually shard' shared by the dispatch sites."""
    return 0 if mesh is None else int(mesh.devices.size)


def check_fleet_divisible(fleet: int, mesh: Mesh) -> None:
    """`shard_map` splits the trial axis evenly: F must divide by the
    mesh's device count (each device runs exactly F/D whole sims).
    THE one wording — the run_sim/bench parsers mirror it."""
    d = mesh_devices(mesh)
    if fleet % d:
        raise ValueError(
            f"fleet ({fleet}) must divide by the fleet mesh's device "
            f"count ({d} = {'x'.join(str(s) for s in mesh.devices.shape)}"
            f" devices): the trial axis shards evenly — each device "
            f"runs F/D whole sims")


def fleet_state_specs(state):
    """`FLEET_SPEC` mirrored over every leaf of a fleet-stacked state
    (every leaf carries the leading ``[F]`` trial axis — the fleet vmap
    stacks them all), None slots preserved — the spec tree
    `benchmarks/mem_pin.py` feeds the per-device footprint model."""
    return jax.tree.map(lambda _: FLEET_SPEC, state)


def shard_fleet_state(state, mesh: Mesh):
    """Place a fleet-stacked state (`workload.fleet_flagship_state`)
    onto the fleet mesh, every leaf sharded on its trial axis.  Like
    `sharded.shard_state`, `device_put` may alias already-placed
    leaves — treat the original as consumed when the result feeds the
    donated scan program."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, FLEET_SPEC)),
        state)


def fleet_driver_program(mesh: Mesh, trial):
    """The jitted sharded-fleet driver `fleet.run_fleet(mesh=...)`
    executes — exposed unexecuted so `analysis/hlo_audit.py` lowers THE
    program (the `scan_program` seam convention, applied to the fleet).

    ``trial`` is the per-key whole-sim function (`fleet._trial_fn` —
    the SAME closure the dense fleet vmaps, which is what makes the
    bit-parity a property instead of a test-only coincidence).  Inside
    `shard_map` each device vmaps its local ``F/D`` key slice, then:

      * per-trial vectors (TrialOutcome / telemetry / trace) are
        all-gathered over ``(trials, nodes)`` — tiled concat in
        row-major device order, which is exactly the order
        `FLEET_SPEC` laid the keys out, so the reassembled ``[F]``
        vectors match the dense fleet's element-for-element;
      * `FleetCounts` is psum'd — the in-graph summary reduction.

    Outputs are replicated (``out_specs=P()``), so the host-side
    Wilson-CI path in `run_fleet` is the dense one, unchanged.  The key
    plane is tiny and the outputs share no buffer with it, so the
    driver is UNDONATED like the dense `_compiled_fleet` (the donated
    program of this module is `fleet_scan_program`).
    """
    axes = (TRIALS_AXIS, NODES_AXIS)

    def local(keys):
        outcome, tel, trace = jax.vmap(trial)(keys)
        counts = FleetCounts(
            trials=lax.psum(jnp.int32(keys.shape[0]), axes),
            violations=lax.psum(
                outcome.violation.sum().astype(jnp.int32), axes),
            settled=lax.psum(
                outcome.settled.sum().astype(jnp.int32), axes),
            stalled=lax.psum(
                outcome.stalled.sum().astype(jnp.int32), axes),
        )
        gathered = jax.tree.map(
            lambda x: lax.all_gather(x, axes, axis=0, tiled=True),
            (outcome, tel, trace))
        return gathered[0], counts, gathered[1], gathered[2]

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(FLEET_SPEC,),
                             out_specs=P()))


def fleet_scan_program(mesh: Mesh, cfg, n_rounds: int):
    """The jitted DONATED `bench.py --fleet F --mesh A,B` program
    (pinned as `fleet_sharded`): each device scans its ``F/D`` flagship
    trials in place — `bench.fleet_program`'s vmapped scan partitioned
    over the fleet mesh, zero collectives (trials never communicate).

    Built here (not inline in bench.py) so `benchmarks/hlo_pin.py`,
    `benchmarks/mem_pin.py` and the contract auditor all lower THE
    timed program through one seam; `bench.fleet_program(mesh=...)`
    dispatches to it and collapses to the dense spelling on a 1-device
    mesh (`hlo_pin --verify-off-path` proves the collapse is
    byte-identical to the archived `fleet_small` chain).
    """
    from go_avalanche_tpu.models import avalanche as av
    from go_avalanche_tpu.parallel.sharded import _reject_round_engine
    _reject_round_engine(cfg)

    def run_one(s):
        def body(st, _):
            new_s, _ = av.round_step(st, cfg)
            return new_s, None
        out, _ = lax.scan(body, s, None, length=n_rounds)
        return out

    return jax.jit(
        shard_map(lambda s: jax.vmap(run_one)(s), mesh=mesh,
                  in_specs=(FLEET_SPEC,), out_specs=FLEET_SPEC),
        donate_argnums=0)
