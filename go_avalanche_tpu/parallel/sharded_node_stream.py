"""Mesh-sharded node-axis streaming scheduler: the registry at scale.

`models/node_stream` re-expressed under `jax.shard_map`: the dense
``[W, T]`` active window shards exactly like the plain simulator
(`parallel/sharded.py` — the inner round IS `sharded._local_round`),
while the registry planes (``[R]`` stake / residency, the ``[W]``
slot-node map) stay REPLICATED — 1M nodes of registry metadata is ~MBs,
noise next to the window state.

The churn pass runs on those replicated planes from the replicated
churn key with NO shard folds, so every shard computes the identical
swap sequence (the same trick the live-traffic arrival draw uses,
`parallel/sharded_backlog.py`); only the record-plane rotation is
row-local (each node shard fills its own block's rows).  That is what
makes the dense and sharded schedulers agree LEAF-EXACT on the
working-set window — `slot_node`, `resident`, the stake plane, and the
churn counters — for the same key (tests/test_node_stream.py), while
the inner consensus round keeps the sharded models' own per-shard PRNG
streams.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from go_avalanche_tpu.config import (
    AvalancheConfig,
    DEFAULT_CONFIG,
    suppress_taps,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.models import node_stream as ns_model
from go_avalanche_tpu.models.node_stream import (
    NodeStreamState,
    NodeStreamTelemetry,
    _registry_byzantine,
)
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.parallel import sharded
from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS, shard_map


def node_stream_state_specs(track_finality: bool = True,
                            with_inflight: bool = False,
                            with_fault_params: bool = False,
                            trace_spec=None,
                            ) -> NodeStreamState:
    """PartitionSpecs for every leaf of `NodeStreamState`;
    `trace_spec` mirrors the scheduler-owned trace plane (replicated —
    `obs.trace.replicated_spec`)."""
    return NodeStreamState(
        sim=sharded.state_specs(track_finality, with_inflight,
                                with_fault_params, trace_spec),
        slot_node=P(),      # replicated [W]: every shard needs the full
        resident=P(),       #   hosting map / residency for the churn
        stake=P(),          #   draw (registry metadata, ~MBs at 1M)
        init_pref=P(TXS_AXIS),
        churn_key=P(),
        churned_in=P(),
        churned_out=P(),
    )


def shard_node_stream_state(state: NodeStreamState,
                            mesh) -> NodeStreamState:
    """Place a host-built node-stream state onto the mesh."""
    state = state._replace(sim=state.sim._replace(
        inflight=inflight.repack_polled_for_shards(
            state.sim.inflight, state.sim.records.votes.shape[1],
            mesh.shape[TXS_AXIS])))
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        node_stream_state_specs(state.sim.finalized_at is not None,
                                state.sim.inflight is not None,
                                state.sim.fault_params is not None,
                                obs_trace.replicated_spec(
                                    state.sim.trace)))


def _local_churn(state: NodeStreamState,
                 cfg: AvalancheConfig) -> Tuple[NodeStreamState,
                                                jax.Array]:
    """The churn pass on one shard: replicated draws, row-local record
    rotation; see `models/node_stream.churn`."""
    if cfg.node_churn_rate <= 0.0:
        return state, jnp.int32(0)
    sim = state.sim
    r = state.resident.shape[0]
    w_local = sim.records.votes.shape[0]
    nshard = lax.axis_index(NODES_AXIS)
    offset = nshard * w_local

    # --- replicated planes: THE shared draw (models/node_stream.
    # draw_churn_swaps), identical on every shard — no axis folds, so
    # the dense and sharded schedulers realize one swap sequence (the
    # leaf-exact window-parity contract rests on this being the same
    # function, not a copy).
    swap, new_slot, resident, n_swapped, k_next = ns_model.draw_churn_swaps(
        state, cfg)
    byz_r = _registry_byzantine(cfg, r)

    # --- row-local rotation: this shard's block of the swap mask.
    swap_local = lax.dynamic_slice(swap, (offset,), (w_local,))
    fresh = vr.init_state(jnp.broadcast_to(state.init_pref[None, :],
                                           sim.records.votes.shape))

    def fill(plane, fresh_plane):
        return jnp.where(swap_local[:, None], fresh_plane, plane)

    records = vr.VoteRecordState(
        votes=fill(sim.records.votes, fresh.votes),
        consider=fill(sim.records.consider, fresh.consider),
        confidence=fill(sim.records.confidence, fresh.confidence),
    )
    added = jnp.where(swap_local[:, None], True, sim.added)
    finalized_at = (None if sim.finalized_at is None
                    else jnp.where(swap_local[:, None], -1,
                                   sim.finalized_at))
    new_sim = sim._replace(
        records=records,
        added=added,
        finalized_at=finalized_at,
        latency_weight=state.stake[new_slot],     # replicated [W]
        byzantine=byz_r[new_slot],                # replicated [W]
        alive=jnp.where(swap, True, sim.alive),   # replicated [W]
        # Querier side masks this shard's local block; the polled-peer
        # side needs the FULL swap mask (ring.peers holds global ids).
        inflight=inflight.clear_rows(sim.inflight, swap_local,
                                     peer_rows=swap),
    )
    return state._replace(
        sim=new_sim,
        slot_node=new_slot,
        resident=resident,
        churn_key=k_next,
        churned_in=state.churned_in + n_swapped,
        churned_out=state.churned_out + n_swapped,
    ), n_swapped


def _local_step(
    state: NodeStreamState,
    cfg: AvalancheConfig,
    n_global: int,
    n_tx_shards: int,
) -> Tuple[NodeStreamState, NodeStreamTelemetry]:
    round_val = state.sim.round
    state, swapped = _local_churn(state, cfg)
    # Scheduler-owned trace plane (models/node_stream contract): the
    # inner round runs trace-suppressed; the scheduler record (psum'd
    # counters + replicated registry stats) is written below.
    new_sim, round_tel = sharded._local_round(state.sim,
                                              suppress_taps(cfg),
                                              n_global, n_tx_shards)
    total = state.stake.sum()
    tel = NodeStreamTelemetry(
        round=round_tel,
        departed=swapped,
        resident_stake=(jnp.where(state.resident, state.stake, 0.0).sum()
                        / jnp.maximum(total, jnp.float32(1e-38))),
    )
    new_sim = new_sim._replace(
        trace=obs_trace.write_round(new_sim.trace, cfg, round_val, tel))
    return state._replace(sim=new_sim), tel


def _shard_mapped(mesh, fn, with_tel=True, track_finality: bool = True,
                  with_inflight: bool = False,
                  with_fault_params: bool = False,
                  trace_spec=None):
    specs = node_stream_state_specs(track_finality, with_inflight,
                                    with_fault_params, trace_spec)
    if with_tel:
        tel_specs = NodeStreamTelemetry(
            round=av.SimTelemetry(
                *([P()] * len(av.SimTelemetry._fields))),
            departed=P(), resident_stake=P())
        out_specs = (specs, tel_specs)
    else:
        out_specs = specs
    return shard_map(fn, mesh=mesh, in_specs=(specs,),
                     out_specs=out_specs, check_vma=False)


# Collective allowlist (analysis/hlo_audit.py): churn/rotation is
# replicated work (identical registry draws on every shard — no axis
# folds, see `_local_churn`), so the collective surface is exactly the
# inner avalanche round's.
DECLARED_COLLECTIVES = frozenset({
    ("all_gather", (NODES_AXIS,)),
    ("all_to_all", (NODES_AXIS,)),
    ("all_reduce", (NODES_AXIS,)),
    ("all_reduce", (NODES_AXIS, TXS_AXIS)),
})


def scan_program(mesh, state: NodeStreamState,
                 cfg: AvalancheConfig = DEFAULT_CONFIG,
                 n_rounds: int = 100, donate: bool = False):
    """The jitted fixed-round program `run_scan_sharded_node_stream`
    executes — exposed unexecuted so `analysis/hlo_audit.py` lowers THE
    driver program (the `bench.flagship_program` seam).  Only tree
    structure and shapes are read from `state`."""
    n_global = state.slot_node.shape[0]
    n_tx = mesh.shape[TXS_AXIS]

    def local_scan(s):
        def body(carry, _):
            new_s, tel = _local_step(carry, cfg, n_global, n_tx)
            return new_s, tel
        return lax.scan(body, s, None, length=n_rounds)

    return jax.jit(_shard_mapped(
        mesh, local_scan,
        track_finality=state.sim.finalized_at is not None,
        with_inflight=state.sim.inflight is not None,
        with_fault_params=state.sim.fault_params is not None,
        trace_spec=obs_trace.replicated_spec(state.sim.trace)),
        donate_argnums=sharded._donate(donate))


def run_scan_sharded_node_stream(
    mesh,
    state: NodeStreamState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 100,
    donate: bool = False,
) -> Tuple[NodeStreamState, NodeStreamTelemetry]:
    """Fixed-round sharded node stream; one jit, collectives inside the
    scan."""
    return scan_program(mesh, state, cfg, n_rounds, donate)(state)
