"""Real multi-process `jax.distributed` smoke for `parallel/runtime.py`.

Every mesh test in the suite runs single-process over virtual devices;
this worker is the one place the ACTUAL multi-host branch of
`initialize_runtime` (`jax.distributed.initialize` + cross-process
coordination) executes: N processes, each with its own CPU devices, form
one global device set, build the runtime mesh, run one sharded avalanche
round, and cross-check the psum'd telemetry.  The reference has no
distributed backend at all (SURVEY.md §5) — this is the scale-out path's
minimal execution proof, runnable anywhere:

    # terminal 1                       # terminal 2
    python -m go_avalanche_tpu.parallel.distributed_smoke \
        --coordinator 127.0.0.1:9911 --num-processes 2 --process-id 0
    ...same with --process-id 1

`tests/test_runtime.py::test_two_process_distributed_smoke` spawns both.

Prints ONE JSON line per process; assertions raise (nonzero exit) on any
cross-process disagreement.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--coordinator", required=True,
                        help="host:port of process 0's coordination service")
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--local-devices", type=int, default=4)
    args = parser.parse_args(argv)

    # Per-process virtual CPU devices must be configured before the
    # backend initializes (same mechanism as tests/conftest.py).
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{args.local_devices}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")  # the accelerator
    # sitecustomize overrides the env var; pin via config after import.

    from go_avalanche_tpu.parallel.runtime import (
        build_on_mesh,
        initialize_runtime,
        make_runtime_mesh,
    )

    pid = initialize_runtime(args.coordinator, args.num_processes,
                             args.process_id)
    assert pid == args.process_id, (pid, args.process_id)
    assert jax.process_count() == args.num_processes
    n_dev = jax.device_count()
    assert n_dev == args.num_processes * args.local_devices, n_dev
    assert len(jax.local_devices()) == args.local_devices

    from go_avalanche_tpu.config import AvalancheConfig
    from go_avalanche_tpu.models import avalanche as av
    from go_avalanche_tpu.parallel import sharded

    mesh = make_runtime_mesh(n_tx_shards=2)
    cfg = AvalancheConfig()
    # Deterministic construction traced identically on every process and
    # compiled INTO the global sharding (device_put onto non-addressable
    # shardings is illegal multi-host; see runtime.build_on_mesh).
    state = build_on_mesh(
        lambda: av.init(jax.random.key(0), 16, 8, cfg), mesh,
        sharded.state_specs(track_finality=True))
    step = sharded.make_sharded_round_step(mesh, cfg)
    state, tel = step(state)
    state, tel = step(state)

    # Telemetry scalars are psum-replicated across the whole mesh: every
    # process must read the same values, or the collective layout is
    # broken.
    digest = {
        "process": pid,
        "processes": jax.process_count(),
        "devices": n_dev,
        "round": int(jax.device_get(state.round)),
        "polls": int(jax.device_get(tel.polls)),
        "votes_applied": int(jax.device_get(tel.votes_applied)),
    }
    assert digest["round"] == 2, digest
    assert digest["polls"] > 0, digest
    print(json.dumps(digest), flush=True)


if __name__ == "__main__":
    sys.exit(main())
