"""Streaming JSONL metrics sink + the zero-dispatch in-graph tap.

Two feeding modes, one file format (docs/observability.md):

  * **host-side** — `MetricsSink.write_stacked(telemetry)` streams the
    stacked telemetry a `run_scan` returns: ONE `jax.device_get` for the
    whole pytree, then one JSON line per (strided) round.  Works for
    every model and every sharded driver (sharded telemetry is already
    psum-replicated scalars).
  * **in-graph** — `emit_round(cfg, round_, telemetry)` is called by the
    dense `round_step`s.  With `cfg.metrics_every == 0` (default) it
    returns before touching the trace: the compiled program is
    byte-identical to the pre-obs one (hlo_pin).  With a stride set, the
    round's telemetry scalars leave the device through ONE unordered
    `jax.experimental.io_callback` under a round-mod `lax.cond` — no
    extra dispatches and no host sync in the fused scan/while loop,
    which is what lets a compiled-loop run be observed without
    perturbing it (the "flight recorder").  Unordered means lines can
    land out of round order under an async dispatch stream; every record
    carries its `round`, so consumers sort (or `jq -s 'sort_by(.round)'`).

The callback writes to the innermost ACTIVE sink (`metrics_sink`
context manager) at call time — the traced program never captures a
file path, so one compiled executable serves any sink (and the
`flagship_metrics` hlo pin stays path-independent).  With no active
sink the record is dropped.
"""

from __future__ import annotations

import contextlib
import json
import threading
from pathlib import Path
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import io_callback

_ACTIVE: list["MetricsSink"] = []   # stack; innermost (last) receives


def _flatten_telemetry(tel, out: dict) -> dict:
    """Flatten (possibly nested) telemetry NamedTuples into one flat
    dict by leaf field name — `BacklogTelemetry.round` (a SimTelemetry)
    contributes its own field names, not a 'round' key.  None fields
    (statically absent planes, e.g. `BacklogTelemetry.traffic` with
    arrivals off) are skipped, so the JSONL schema only ever carries
    fields the run computed."""
    for name in tel._fields:
        v = getattr(tel, name)
        if v is None:
            continue
        if hasattr(v, "_fields"):
            _flatten_telemetry(v, out)
        else:
            out[name] = v
    return out


class MetricsSink:
    """Append-only JSONL writer; one JSON object per line.

    `tag` (see `obs.tags.tag_from_config`) is stamped into every record
    when non-empty, so merged traces from different engine configs stay
    separable.  Thread-safe: the in-graph tap's callback may fire from a
    runtime thread.

    Opening TRUNCATES: one file is one run's trace.  A retried worker
    (bench.py's CPU fallback) or a re-run of the same command starts the
    trace over instead of silently interleaving two runs' records with
    duplicate round numbers under one last-wins manifest.
    """

    def __init__(self, path, tag: str = ""):
        self.path = Path(path)
        self.tag = tag
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "w", encoding="utf-8")
        self._lock = threading.Lock()
        self.records_written = 0

    def write(self, record: dict) -> None:
        if self.tag:
            record = {**record, "tag": self.tag}
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            self._fh.write(line + "\n")
            self.records_written += 1

    def write_stacked(self, telemetry, every: int = 1,
                      start_round: int = 0, round_stride: int = 1) -> int:
        """Stream a `run_scan`'s stacked telemetry pytree: one transfer
        (`jax.device_get` on the whole tree — see
        `utils.metrics.telemetry_summary`), then one line per `every`-th
        round.  Returns the number of records written.

        `round_stride` maps entry index -> round number (``round =
        start_round + index * round_stride``): 1 (default) for per-round
        stacks, the trace stride for a decoded trace-plane buffer whose
        entries are already strided samples (`obs.trace.write_trace`).
        """
        if every < 1:
            raise ValueError("every must be >= 1")
        if round_stride < 1:
            raise ValueError("round_stride must be >= 1")
        host = jax.device_get(telemetry)
        flat = _flatten_telemetry(host, {})
        n = int(next(iter(flat.values())).shape[0])
        wrote = 0
        for r in range(0, n, every):
            self.write({"round": start_round + r * round_stride,
                        **{k: _scalar(np.asarray(v[r])) for k, v in
                           flat.items()}})
            wrote += 1
        return wrote

    def flush(self) -> None:
        with self._lock:
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()


@contextlib.contextmanager
def metrics_sink(path, tag: str = "") -> Iterator[MetricsSink]:
    """Open a sink and make it the ACTIVE receiver of the in-graph tap
    for the duration of the block."""
    sink = MetricsSink(path, tag=tag)
    _ACTIVE.append(sink)
    try:
        yield sink
    finally:
        # Unordered callbacks can trail the jit call that issued them;
        # drain them before detaching the sink or trailing records from
        # the run's last rounds would be dropped.
        try:
            jax.effects_barrier()
        except Exception:  # noqa: BLE001 — barrier is best-effort
            pass
        _ACTIVE.remove(sink)
        sink.close()


def active_sink() -> Optional[MetricsSink]:
    return _ACTIVE[-1] if _ACTIVE else None


def _scalar(a):
    """JSON-ready python scalar: floats stay floats (the PR 10
    `resident_stake` fraction — an int() cast silently truncated it to
    0), every integer/bool counter stays int."""
    return float(a) if np.issubdtype(a.dtype, np.floating) else int(a)


def _host_write(payload: dict) -> None:
    """io_callback target: route one record to the active sink (drop
    when none — the compiled program outlives any one sink)."""
    if not _ACTIVE:
        return
    _ACTIVE[-1].write({k: _scalar(np.asarray(v))
                       for k, v in payload.items()})


def emit_round(cfg, round_, telemetry) -> None:
    """The in-graph telemetry tap (call from a round_step, AFTER the
    round's telemetry is assembled).

    `cfg.metrics_every == 0`: returns before any tracing — statically
    absent, the caller's program is untouched.  Otherwise inserts one
    unordered `io_callback` behind a ``round % metrics_every == 0``
    `lax.cond`; scan/while/jit-compatible (ordered callbacks are not
    legal inside `lax.cond`, hence unordered + the `round` field for
    re-ordering).  Never emits from inside `shard_map` — the sharded
    drivers stream host-side instead (`MetricsSink.write_stacked`).
    """
    if getattr(cfg, "metrics_every", 0) <= 0:
        return
    payload = _flatten_telemetry(telemetry, {"round": round_})

    def _emit(x):
        io_callback(_host_write, None, payload, ordered=False)
        return x

    lax.cond(jnp.mod(round_, cfg.metrics_every) == 0,
             _emit, lambda x: x, jnp.int32(0))
