"""On-device trace plane: per-round telemetry as a memory write, not a
host round-trip.

The flight recorder's in-graph tap (`obs/sink.emit_round`, PR 5)
streams telemetry through an `io_callback` — measured at ~15-25% CPU
hot-loop cost, forbidden under `shard_map`, and forced off under the
Monte-Carlo fleet vmap because a callback has no per-trial identity
there.  This module is the tap whose cost is ONE `dynamic_update_slice`
into a donated on-device buffer:

  * `TraceBuffer` — a ``[S, M]`` int32 plane carried IN the sim state
    (S = ceil(rounds / stride) slots, M = the flattened telemetry
    column count) plus a write cursor.  The COLUMN MANIFEST (ordered
    ``(name, kind)`` pairs, kind ``"i"``/``"f"``) and the stride ride
    as static pytree aux data, so decode is schema-pinned: a write
    whose telemetry does not match the manifest fails at trace time,
    and the decoder can never mislabel a column.
  * `write_round` — called by every dense round/scheduler step AFTER
    its telemetry is assembled.  `cfg.trace_every == 0` (default) or a
    ``None`` buffer returns before any tracing: the compiled program is
    byte-identical to the pre-trace one (`hlo_pin --verify-off-path`).
    Otherwise one `lax.cond`-gated `dynamic_update_slice` lands the
    round's row at slot ``round // stride`` — no callback, no host
    sync, legal under `shard_map` (the counters are psum-replicated,
    so the plane stays replicated) and under `vmap` (the fleet lifts
    it to ``[F, S, M]`` per-trial traces).
  * decode — `trace_records` / `fleet_trace_records` rebuild the
    existing JSONL record schema on the host (rows ORDERED by
    construction — no unordered-io_callback re-sort), and
    `write_trace` streams a buffer through the one JSONL writer
    (`MetricsSink.write_stacked`), so trace-plane files and
    callback-tap files are bit-identical on the same run
    (tests/test_trace.py).

Float columns (e.g. the node-stream `resident_stake` fraction) are
stored BITCAST to int32 (`lax.bitcast_convert_type`) and bitcast back
at decode — bit-exact round-trip, one buffer dtype.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig

Columns = Tuple[Tuple[str, str], ...]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class TraceBuffer:
    """The on-device trace plane; carried as a sim-state leaf.

    `columns` / `stride` are STATIC pytree aux data (like
    `DagSimState.n_sets`): two buffers with different manifests are
    different pytree structures, so a decode can never read slot bytes
    under the wrong schema.
    """

    data: jax.Array    # int32 [S, M] (fleet-vmapped: [F, S, M]);
                       #   untouched slots stay zero (watchdog-checked)
    cursor: jax.Array  # int32 — slots written so far; the next write
                       #   lands at slot round // stride == cursor
    columns: Columns   # static ordered (name, kind) manifest;
                       #   kind "i" = int32, "f" = float32 (bitcast)
    stride: int        # static = cfg.trace_every

    def tree_flatten(self):
        return (self.data, self.cursor), (self.columns, self.stride)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


def enabled(cfg: AvalancheConfig) -> bool:
    """True when the trace plane is configured on."""
    return getattr(cfg, "trace_every", 0) > 0


def slots_for(n_rounds: int, stride: int) -> int:
    """ceil(n_rounds / stride): rounds ``r`` in ``[0, n_rounds)`` with
    ``r % stride == 0`` — exactly the slots a full run writes."""
    return -(-int(n_rounds) // int(stride))


def columns_from_fields(*field_groups: Sequence[str],
                        floats: frozenset = frozenset()) -> Columns:
    """Build a column manifest from ordered field-name groups (the
    telemetry NamedTuples' `_fields`, concatenated in the same order
    `sink._flatten_telemetry` flattens them).  Names in `floats` get
    kind ``"f"`` (bitcast storage); everything else is an int32
    counter."""
    cols = []
    for fields in field_groups:
        for name in fields:
            cols.append((name, "f" if name in floats else "i"))
    return tuple(cols)


def alloc(cfg: AvalancheConfig, n_rounds: int,
          columns: Columns) -> Optional[TraceBuffer]:
    """A fresh zeroed buffer for a `n_rounds`-horizon run; ``None``
    (statically absent — every archived hlo pin byte-identical) when
    `cfg.trace_every == 0`.

    Rejects the inert ``rounds < stride`` combo (mirrored at the
    `run_sim` parser): such a run would only ever sample round 0 while
    its tag claims a strided trace.
    """
    if not enabled(cfg):
        return None
    stride = cfg.trace_every
    if n_rounds < stride:
        raise ValueError(
            f"trace_every={stride} exceeds the run horizon "
            f"({n_rounds} rounds): only round 0 would ever be sampled "
            f"— lower the stride or lengthen the run")
    s = slots_for(n_rounds, stride)
    return TraceBuffer(
        data=jnp.zeros((s, len(columns)), jnp.int32),
        cursor=jnp.int32(0),
        columns=tuple(columns),
        stride=int(stride),
    )


def _flat_items(telemetry) -> List[Tuple[str, jax.Array]]:
    """Ordered (leaf name, value) pairs — the one flattening shared
    with the JSONL sink (`sink._flatten_telemetry`), so the trace
    plane's column order IS the JSONL schema's field order."""
    from go_avalanche_tpu.obs.sink import _flatten_telemetry

    return list(_flatten_telemetry(telemetry, {}).items())


def write_round(buf: Optional[TraceBuffer], cfg: AvalancheConfig,
                round_, telemetry) -> Optional[TraceBuffer]:
    """The in-graph trace tap (call from a round/scheduler step, AFTER
    the round's telemetry is assembled).

    Statically absent — returns before any tracing — when the buffer is
    ``None`` or `cfg.trace_every == 0` (a scheduler suppresses its
    inner round's write by passing a trace-zeroed inner cfg, exactly
    like the metrics tap).  Otherwise encodes the flattened telemetry
    row (floats bitcast to int32) and lands it at slot
    ``round // stride`` under a round-mod `lax.cond`.  The column
    manifest is CHECKED here: telemetry whose flattened fields drift
    from the buffer's manifest fails at trace time, not at decode.
    """
    if buf is None or not enabled(cfg):
        return buf
    items = _flat_items(telemetry)
    names = tuple(name for name, _ in items)
    if names != tuple(name for name, _ in buf.columns):
        raise ValueError(
            f"trace column manifest mismatch: buffer carries "
            f"{[n for n, _ in buf.columns]}, telemetry flattens to "
            f"{list(names)} — allocate the buffer from the same "
            f"telemetry schema the step emits")
    vals = []
    for (name, kind), (_, v) in zip(buf.columns, items):
        v = jnp.asarray(v)
        if kind == "f":
            vals.append(lax.bitcast_convert_type(v.astype(jnp.float32),
                                                 jnp.int32))
        else:
            if jnp.issubdtype(v.dtype, jnp.floating):
                raise ValueError(
                    f"trace column {name!r} is declared an int32 "
                    f"counter but the telemetry leaf is "
                    f"{v.dtype}-valued — declare it in the manifest's "
                    f"float set or the decode would misread its bits")
            vals.append(v.astype(jnp.int32))
    row = jnp.stack(vals)                                   # [M]
    stride = buf.stride
    round_ = jnp.asarray(round_, jnp.int32)
    slot = round_ // stride

    def _write(b: TraceBuffer) -> TraceBuffer:
        data = lax.dynamic_update_slice(b.data, row[None, :],
                                        (slot, jnp.int32(0)))
        return TraceBuffer(data, b.cursor + 1, b.columns, b.stride)

    if stride == 1:
        # Statically every round: no branch to trace (the round-mod
        # predicate would be constant-true, but only the Python level
        # knows that).
        return _write(buf)
    return lax.cond(jnp.mod(round_, stride) == 0, _write,
                    lambda b: b, buf)


def replicated_spec(buf: Optional[TraceBuffer]):
    """The sharded drivers' PartitionSpec mirror of a buffer: the
    counters are psum-replicated before the write, so the whole plane
    replicates (`P()`) across every mesh axis — matching aux so the
    spec tree and the value tree unflatten identically."""
    if buf is None:
        return None
    from jax.sharding import PartitionSpec as P

    return TraceBuffer(data=P(), cursor=P(), columns=buf.columns,
                       stride=buf.stride)


# ------------------------------------------------------------- decode


def _decode_columns(data: np.ndarray, columns: Columns) -> Dict:
    """int32 slot rows -> {name: numpy column} with float columns
    bitcast back to float32 (exact round-trip)."""
    out = {}
    for j, (name, kind) in enumerate(columns):
        col = np.ascontiguousarray(data[..., j])
        out[name] = col.view(np.float32) if kind == "f" else col
    return out


def _host(buf: TraceBuffer) -> TraceBuffer:
    data, cursor = jax.device_get((buf.data, buf.cursor))
    return TraceBuffer(np.asarray(data), np.asarray(cursor),
                       buf.columns, buf.stride)


def stacked_telemetry(buf: TraceBuffer):
    """Decode a single-sim buffer to a flat telemetry-shaped namedtuple
    of host arrays (one entry per WRITTEN slot, in slot order) — the
    pytree `MetricsSink.write_stacked` streams."""
    host = _host(buf)
    if host.data.ndim != 2:
        raise ValueError(
            f"stacked_telemetry decodes a single sim's [S, M] buffer; "
            f"got a {host.data.shape} plane (fleet traces decode via "
            f"fleet_trace_records)")
    n = int(host.cursor)
    cols = _decode_columns(host.data[:n], host.columns)
    tel_cls = collections.namedtuple("TraceTelemetry",
                                     [n_ for n_, _ in host.columns])
    return tel_cls(**cols)


def write_trace(sink, buf: TraceBuffer) -> int:
    """Stream a decoded buffer to a `MetricsSink` through the one JSONL
    writer (`write_stacked`): one line per written slot, stamped with
    its true round (``slot * stride``).  Returns lines written."""
    return sink.write_stacked(stacked_telemetry(buf),
                              round_stride=buf.stride)


def trace_records(buf: TraceBuffer) -> List[Dict]:
    """A single-sim buffer as flight-recorder records (the JSONL dict
    schema, ordered by round BY CONSTRUCTION) — directly consumable by
    `obs.recovery.check_recovery`."""
    host = _host(buf)
    if host.data.ndim != 2:
        raise ValueError(
            f"trace_records decodes a single sim's [S, M] buffer; got "
            f"a {host.data.shape} plane (fleet traces decode via "
            f"fleet_trace_records)")
    n = int(host.cursor)
    cols = _decode_columns(host.data[:n], host.columns)
    return [{"round": s * host.stride,
             **{name: _py(col[s]) for name, col in cols.items()}}
            for s in range(n)]


def fleet_trace_records(buf: TraceBuffer) -> List[Dict]:
    """A fleet-vmapped ``[F, S, M]`` buffer as FLEET-STACKED records:
    one dict per round whose values are per-trial LISTS — the format
    `obs.recovery.check_recovery` dispatches on (per-trial verdict
    vectors) and the fleet `--metrics` JSONL spelling
    (docs/observability.md)."""
    host = _host(buf)
    if host.data.ndim != 3:
        raise ValueError(
            f"fleet_trace_records decodes an [F, S, M] fleet buffer; "
            f"got a {host.data.shape} plane (single-sim traces decode "
            f"via trace_records)")
    cursors = set(int(c) for c in np.asarray(host.cursor).reshape(-1))
    if len(cursors) != 1:
        raise ValueError(
            f"fleet trials wrote different slot counts {sorted(cursors)} "
            f"— one fleet runs one horizon, so a divergent cursor means "
            f"a corrupted trace")
    n = cursors.pop()
    cols = _decode_columns(host.data[:, :n, :], host.columns)
    return [{"round": s * host.stride,
             **{name: [_py(col[f, s]) for f in range(col.shape[0])]
                for name, col in cols.items()}}
            for s in range(n)]


def _py(v):
    """JSON-ready python scalar (the sink's `_scalar` convention)."""
    v = np.asarray(v)
    return float(v) if np.issubdtype(v.dtype, np.floating) else int(v)
