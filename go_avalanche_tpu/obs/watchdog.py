"""Invariant watchdog: turn silent state corruption into loud failures.

The PR 4 review caught a SILENT corruption class: the layout-aliased
poll-mask repack (`inflight.repack_polled_for_shards`) produced
equal-width but differently-laid-out packed planes, and nothing
downstream could tell — votes just landed on the wrong columns.  This
module is the opt-in debug mode (`run_sim --check-invariants`) that
asserts, on the HOST between steps, the structural invariants every
engine maintains by construction:

  * confidence counter ``(conf >> 1) <= 0x7FFF`` (the saturation cap —
    the counter lives in 15 bits) AND ``<= cfg.finalization_score
    + cfg.k - 1`` (a record freezes once a round ENDS with it
    finalized — poll masks and every delivery's `update_mask` exclude
    finalized records — but the k sequential votes of the ingest call
    it crosses in keep landing, so the crossing call can overshoot the
    score by at most k - 1);
  * window planes carry no bits above ``cfg.window`` (the packed uint8
    windows are masked on every shift when window < 8);
  * every in-flight ring latency sits in ``[0, timeout_rounds()]`` and
    the ring's depth is ``timeout_rounds() + 1`` (ages < depth);
  * a bit-packed ring poll-mask plane has ZERO padding bits in every
    per-shard byte block (the exact aliased-repack corruption);
  * the finalized count never DECREASES across steps (finalized records
    freeze; streaming schedulers legitimately reset refilled columns —
    construct `Watchdog(monotonic=False)` there);
  * EVENT ACCOUNTING (PR 6): no ring entry can deliver across an
    active cut — every (querier, peer) draw severed by a fault-script
    cut event (partition / regional_outage) active at its ISSUE round
    must carry the never-delivers timeout sentinel
    (`check_ring_cut`, a host-numpy re-derivation of
    `ops/inflight.partition_cut` from the ring's own peer plane; slot
    ``r % depth`` dates each entry, so the check needs the state's
    round counter).

Host-side by design: a `jax.device_get` per check keeps the checks out
of the compiled program entirely (the traced step is byte-identical
with the watchdog on or off), and a violation raises
`InvariantViolation` with the offending indices — not a device-side
trap.  Debug-mode cost: one transfer + numpy reductions per step.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.ops import voterecord as vr


class InvariantViolation(AssertionError):
    """A structural invariant of the sim state failed."""


def _offenders(mask: np.ndarray, limit: int = 5) -> str:
    idx = np.argwhere(mask)
    shown = ", ".join(str(tuple(int(x) for x in i)) for i in idx[:limit])
    more = "" if idx.shape[0] <= limit else f" (+{idx.shape[0] - limit} more)"
    return f"{idx.shape[0]} offender(s) at {shown}{more}"


def check_records(records, cfg: AvalancheConfig) -> int:
    """Assert the vote-record invariants; returns the finalized count
    (fuel for the monotonicity check).  `records` is any
    `VoteRecordState` (``[N]`` or ``[N, T]``)."""
    votes, consider, confidence = (
        np.asarray(x) for x in jax.device_get(
            (records.votes, records.consider, records.confidence)))
    counter = confidence >> 1
    bad = counter > 0x7FFF
    if bad.any():
        raise InvariantViolation(
            f"confidence counter exceeds the 15-bit saturation cap "
            f"0x7FFF: {_offenders(bad)}")
    # A record freezes once a round ends with it finalized (poll masks
    # and per-delivery update_masks exclude finalized records), but the
    # ingest call it CROSSES in applies its remaining sequential votes
    # under a mask computed at call start — overshoot caps at k - 1.
    cap = min(0x7FFF, cfg.finalization_score + cfg.k - 1)
    bad = counter > cap
    if bad.any():
        raise InvariantViolation(
            f"confidence counter exceeds finalization_score + k - 1 = "
            f"{cap} (a record finalized at a round boundary must "
            f"freeze): {_offenders(bad)}")
    if cfg.window < 8:
        window_mask = np.uint8((1 << cfg.window) - 1)
        for name, plane in (("votes", votes), ("consider", consider)):
            bad = (plane & ~window_mask) != 0
            if bad.any():
                raise InvariantViolation(
                    f"{name} window plane carries bits above "
                    f"window={cfg.window}: {_offenders(bad)}")
    fin = np.asarray(jax.device_get(
        vr.has_finalized(records.confidence, cfg)))
    return int(fin.sum())


def check_ring(ring, cfg: AvalancheConfig, t: Optional[int] = None,
               tx_shards: int = 1) -> None:
    """Assert the in-flight ring invariants (None ring passes).

    `t` (the multi-target tx width) enables the packed-plane padding
    check for a coalesced ring; `tx_shards` selects which per-shard
    byte layout the plane must carry (`inflight.packed_polled_width`)."""
    if ring is None:
        return
    timeout = cfg.timeout_rounds()
    depth = int(ring.peers.shape[0])
    if depth != timeout + 1:
        raise InvariantViolation(
            f"ring depth {depth} != timeout_rounds() + 1 = {timeout + 1}: "
            f"entry ages can escape the ring")
    lat = np.asarray(jax.device_get(ring.lat))
    bad = (lat < 0) | (lat > timeout)
    if bad.any():
        raise InvariantViolation(
            f"ring latency outside [0, timeout={timeout}]: "
            f"{_offenders(bad)}")
    polled = np.asarray(jax.device_get(ring.polled))
    if polled.dtype == np.uint8 and t is not None:
        t_local = t // tx_shards
        pad_bits = -t_local % 8
        if pad_bits:
            blocks = polled.reshape(*polled.shape[:-1], tx_shards, -1)
            # Bits t_local .. of each shard block's last byte are pad.
            pad_mask = np.uint8(((1 << pad_bits) - 1) << (t_local % 8))
            bad = (blocks[..., -1] & pad_mask) != 0
            if bad.any():
                raise InvariantViolation(
                    f"bit-packed ring poll mask has NON-ZERO padding "
                    f"bits (layout-aliased repack? see "
                    f"inflight.repack_polled_for_shards): "
                    f"{_offenders(bad)}")


def check_ring_cut(ring, cfg: AvalancheConfig, round_: int,
                   n_global: int, row_offset: int = 0) -> None:
    """Event accounting: no delivery can be pending across an active cut.

    Re-derives, in host numpy, which of the ring's stored (querier,
    peer) draws were severed by a cut event (partition /
    regional_outage) active at their ISSUE round — slot ``r % depth``
    holds round r's queries, so `round_` (the state's NEXT-round
    counter) dates every slot — and asserts each severed entry carries
    the never-delivers timeout sentinel, exactly what
    `ops/inflight.apply_faults` stamped at issue.  A severed entry
    with a deliverable latency is a query that would cross the cut —
    the fault model's cardinal sin.  Pre-fault / init slots pass
    vacuously (the init ring is all-sentinel).  None ring or empty cut
    schedule: no-op.
    """
    if ring is None:
        return
    events = cfg.cut_events()
    if not events:
        return
    from go_avalanche_tpu.ops import inflight

    timeout = cfg.timeout_rounds()
    depth = int(ring.peers.shape[0])
    peers, lat = (np.asarray(x) for x in
                  jax.device_get((ring.peers, ring.lat)))
    rows = peers.shape[1]
    qids = np.arange(rows, dtype=np.int64) + row_offset
    for slot in range(depth):
        if round_ <= slot:            # slot never written yet
            continue
        issue = round_ - 1 - ((round_ - 1 - slot) % depth)
        severed = np.zeros(peers[slot].shape, np.bool_)
        for kind, start, end, param in events:
            if not (start <= issue < end):
                continue
            if kind == "partition":
                split = inflight._partition_split(cfg, n_global, param)
                qside = qids < split
                pside = peers[slot] < split
            else:                      # regional_outage
                qside = (qids * cfg.n_clusters // n_global) == param
                pside = (peers[slot].astype(np.int64)
                         * cfg.n_clusters // n_global) == param
            severed |= qside[:, None] != pside
        bad = severed & (lat[slot] != timeout)
        if bad.any():
            raise InvariantViolation(
                f"ring slot {slot} (issued round {issue}) holds "
                f"deliverable entries across an active cut — severed "
                f"draws must carry the timeout sentinel {timeout}: "
                f"{_offenders(bad)}")


def check_trace(trace, cfg: AvalancheConfig, round_: int) -> None:
    """Trace-plane consistency (obs/trace.py; None buffer passes):

      * the write cursor equals the number of emitted slots after
        ``round_`` completed rounds — ``ceil(round_ / stride)``, i.e.
        slot index == round // stride for every write (a drifted
        cursor means a slot was skipped or double-written);
      * every slot at or beyond the cursor is still ZERO (untouched
        slots must stay zero, or the decode would report rounds that
        never ran).
    """
    if trace is None:
        return
    stride = trace.stride
    cursor = int(jax.device_get(trace.cursor))
    expected = -(-int(round_) // stride)       # ceil(round / stride)
    if cursor != expected:
        raise InvariantViolation(
            f"trace cursor {cursor} != ceil(round / stride) = "
            f"ceil({round_} / {stride}) = {expected}: the trace plane "
            f"skipped or double-wrote a slot")
    data = np.asarray(jax.device_get(trace.data))
    if cursor < data.shape[0]:
        bad = (data[cursor:] != 0).any(axis=-1)
        if bad.any():
            raise InvariantViolation(
                f"trace slots beyond the cursor ({cursor}) are "
                f"non-zero — untouched slots must stay zero: "
                f"{_offenders(bad)}")


def _resolve(state):
    """(records, ring, t, round, trace) from any model's state pytree."""
    if hasattr(state, "dag"):                  # StreamingDagState
        state = state.dag
    if hasattr(state, "sim"):                  # BacklogSimState
        state = state.sim
    if hasattr(state, "base"):                 # DagSimState
        state = state.base
    records = state.records
    t = records.votes.shape[1] if records.votes.ndim == 2 else None
    return (records, getattr(state, "inflight", None), t,
            getattr(state, "round", None),
            getattr(state, "trace", None))


class Watchdog:
    """Stateful checker: call `check(state)` after every step.

    Tracks the finalized count across calls for the monotonicity
    invariant; `monotonic=False` for the streaming schedulers, whose
    column refills legitimately reset finality.  `tx_shards` forwards
    to the packed-plane padding check for mesh-placed states.
    """

    def __init__(self, cfg: AvalancheConfig, monotonic: bool = True,
                 tx_shards: int = 1):
        self.cfg = cfg
        self.monotonic = monotonic
        self.tx_shards = tx_shards
        self.checks = 0
        self._prev_finalized: Optional[int] = None

    def check(self, state) -> int:
        """Run every invariant against `state`; returns the finalized
        count.  Raises `InvariantViolation` on the first failure."""
        records, ring, t, round_, trace = _resolve(state)
        finalized = check_records(records, self.cfg)
        check_ring(ring, self.cfg, t=t, tx_shards=self.tx_shards)
        if round_ is not None:
            check_ring_cut(ring, self.cfg, int(jax.device_get(round_)),
                           n_global=int(records.votes.shape[0]))
            check_trace(trace, self.cfg, int(jax.device_get(round_)))
        if (self.monotonic and self._prev_finalized is not None
                and finalized < self._prev_finalized):
            raise InvariantViolation(
                f"finalized count decreased: {self._prev_finalized} -> "
                f"{finalized} (finalized records must freeze)")
        self._prev_finalized = finalized
        self.checks += 1
        return finalized
