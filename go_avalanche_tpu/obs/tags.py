"""`tag_from_config` — the single spelling of the metric engine tag.

`bench.py` grew its tag by ad-hoc concatenation per A/B axis
(--exchange / --ingest / --latency* / --inflight-engine); roofline rows
and the metrics sink need the same label or their artifacts stop being
joinable against bench lines.  The tag is part of the round-over-round
delta contract (`bench._attach_prev_delta` compares same-metric rounds
only), so its format is PINNED by `tests/test_obs.py` — change it only
with the test, knowing every archived `BENCH_r*.json` chain breaks at
the rename.

Format: empty for the all-default config; otherwise a concatenation of
``", <axis-tag>"`` fragments, one per NON-default engine axis, in this
fixed order:

    ", legacy-exchange"        cfg.fused_exchange False
    ", {engine}-ingest"        cfg.ingest_engine != "u8"
    ", megakernel"             cfg.round_engine != "phased" (the
                               whole-round fused Pallas program,
                               ops/megakernel.py — an entirely
                               different timed program from the
                               phased chain)
    ", latency{N}"             async on with a latency distribution
    ", {mode}-latency"         cfg.latency_mode not fixed
    ", timeout{T}"             timeout differs from the bench-derived
                               default (`default_timeout_rounds`:
                               2 * latency + 2 rounds).  ONE deliberate
                               divergence from bench's historic
                               concatenation: bench tagged whenever
                               --timeout-rounds was passed EXPLICITLY,
                               even at the default value; a config
                               cannot carry explicitness, so an
                               explicit-at-default timeout is now
                               untagged (no archived chain used one)
    ", {engine}-inflight"      cfg.inflight_engine != "walk"
    ", partition"              cfg.partition_spec scheduled
    ", {policy}-adversary"     cfg.adversary_policy != "off" (the
                               adaptive-adversary context plane and
                               policy transforms change the timed
                               program; the static strategy knobs
                               stay untagged — they predate the tag
                               and alter only draw values)
    ", {mode}-stake[S]"        cfg.stake_mode != "off" (stake-weighted
                               committee draws change the timed
                               program; S = stake_zipf_s, %g-formatted,
                               zipf mode only)
    ", hier{C}"                stake on with n_clusters > 1 (the
                               two-level hierarchical sampling engine;
                               C = n_clusters)
    ", registry{R}/{W}"        cfg.registry_nodes > 0 (the node-axis
                               streaming scheduler's R-entry registry
                               over a W-row window)
    ", {mode}-arrival{R}"      cfg.arrivals_enabled() (the live-traffic
                               plane changes the timed program; R =
                               arrival_rate, %g-formatted)
    ", backpressure"           cfg.arrival_backpressure set (closed-loop
                               admission throttles the offered rate)
    ", arrival-skew"           cfg.arrival_cluster_weights set (hot-
                               region per-cluster rate multipliers)
    ", metrics{N}"             cfg.metrics_every > 0 (the in-graph tap
                               changes the timed program)
    ", trace{N}"               cfg.trace_every > 0 (the on-device
                               trace plane changes the timed program —
                               one dynamic_update_slice per emitted
                               round; obs/trace.py)
"""

from __future__ import annotations

from go_avalanche_tpu.config import AvalancheConfig

# The canonical phase-span names (`utils/tracing.annotate` REJECTS any
# other spelling).  One registry so every per-phase surface joins on
# the same keys: the eager wall timers (`bench.py --profile`,
# `tracing.collect_phase_times`), the device-time xplane harvest
# (`tracing.device_phase_times` — HLO `op_name` metadata carries these
# as named-scope path segments), and the profiler timeline itself.
# The strings are FROZEN: they are embedded in archived profile
# artifacts and in the HLO metadata of every pinned program — renaming
# one silently orphans both (and moving a pin's hash is the loud
# version of the same mistake).
PHASE_SPANS = (
    "poll_mask",          # capped per-(node, tx) pollable mask
    "sample_peers",       # committee peer draw (uniform/stake/hier)
    "gossip_admission",   # gossip scatter-max admission (gossip on)
    "gather_prefs",       # peer-preference gathers (exchange engines)
    "ingest_votes",       # RegisterVotes window ingest (u8/swar32)
    "fused_round",        # whole-round megakernel (gather+ingest+conf)
)


def default_timeout_rounds(latency_rounds: int) -> int:
    """The bench lane's derived timeout default: 2 * latency + 2 rounds
    (room for a full round trip plus jitter before a draw is reaped).
    THE single spelling — `benchmarks/workload.flagship_config` derives
    its `request_timeout_s` from this, and `tag_from_config` suppresses
    the ", timeoutN" fragment exactly when a config matches it; a
    drifted copy would silently relabel configs and break the archived
    same-metric delta chains."""
    return 2 * latency_rounds + 2


def tag_from_config(cfg: AvalancheConfig) -> str:
    """Metric tag fragment for this config's non-default engine axes.

    Matches what `bench.py` historically concatenated from its flags
    (sole divergence: the explicit-at-default timeout case — see the
    module docstring), so existing same-metric delta chains keep
    resolving; leading ", " so it appends directly inside a metric
    string's parenthetical.
    """
    tag = "" if cfg.fused_exchange else ", legacy-exchange"
    if cfg.ingest_engine != "u8":
        tag += f", {cfg.ingest_engine}-ingest"
    if cfg.round_engine != "phased":
        tag += ", megakernel"
    if cfg.async_queries():
        if cfg.latency_mode != "none":
            tag += f", latency{cfg.latency_rounds}"
            if cfg.latency_mode != "fixed":
                tag += f", {cfg.latency_mode}-latency"
            if cfg.timeout_rounds() != default_timeout_rounds(
                    cfg.latency_rounds):
                tag += f", timeout{cfg.timeout_rounds()}"
        if cfg.inflight_engine != "walk":
            tag += f", {cfg.inflight_engine}-inflight"
        if cfg.partition_spec is not None:
            tag += ", partition"
    if cfg.adversary_policy != "off":
        tag += f", {cfg.adversary_policy}-adversary"
    if cfg.stake_mode != "off":
        tag += f", {cfg.stake_mode}-stake"
        if cfg.stake_mode == "zipf":
            tag += f"{cfg.stake_zipf_s:g}"
        if cfg.n_clusters > 1:
            tag += f", hier{cfg.n_clusters}"
    if cfg.registry_nodes > 0:
        tag += f", registry{cfg.registry_nodes}/{cfg.active_nodes}"
    if cfg.arrivals_enabled():
        tag += f", {cfg.arrival_mode}-arrival{cfg.arrival_rate:g}"
        if cfg.arrival_backpressure is not None:
            tag += ", backpressure"
        if cfg.arrival_cluster_weights is not None:
            tag += ", arrival-skew"
    if cfg.metrics_every > 0:
        tag += f", metrics{cfg.metrics_every}"
    if cfg.trace_every > 0:
        tag += f", trace{cfg.trace_every}"
    return tag
