"""Recovery-curve checker: machine-verify "does the network recover,
and how fast" from a flight-recorder trace.

The fault-script engine (`cfg.fault_script`, `ops/inflight.py`) turned
`examples/partition_outage.py` into a scenario library
(now `examples/fault_scenarios.py`); this module
turns its strip charts into tier-1-testable PROPERTIES.  Given the
config that ran (the script is static — the schedule is known) and the
per-round JSONL trace the flight recorder emitted (`--metrics`, or a
`MetricsSink.write_stacked` of a `run_scan`'s telemetry), it verifies
the three invariants every healing network must satisfy:

  1. **Cut accounting** — every fault-blocked draw is reaped exactly
     once, `timeout_rounds()` later: per round,
     ``expiries[r] == partition_blocked[r - timeout]``.  Nothing
     vanishes silently, nothing is reaped twice.  The equality is
     STRICT when cuts are the only expiry source (bounded latency
     modes whose worst case — base max + active spike extra — stays
     below the timeout); stochastic tails (geometric) and
     over-the-timeout spikes add expiries of their own, so those
     configs get the one-sided ``>=`` check.
  2. **Occupancy recovery** — the ring's fill returns to its pre-fault
     baseline within ``timeout_rounds() + slack`` rounds of each heal:
     blocked entries swell the ring for exactly one timeout after the
     cut ends, then drain.  A ring that stays swollen is a leak; one
     that never swelled means the cut never fired.
  3. **Finality monotonicity** — the finalized count never decreases
     across fault events (per-round `finalizations` >= 0 everywhere;
     finalized records freeze — the watchdog's end-of-round invariant,
     asserted here on the trace itself).

Traces must be stride-1 (`metrics_every=1` / unstrided write_stacked)
and are re-sorted by `round` (the in-graph tap's unordered io_callback
may land lines out of order).

    from go_avalanche_tpu.obs import recovery
    report = recovery.check_recovery(cfg, "trace.jsonl")   # raises
    report = recovery.verify_recovery(cfg, records)        # inspects

See docs/observability.md (fault scripts & recovery curves) for the
event schema and `examples/fault_scenarios.py` for worked scenarios
that emit a trace and a recovery verdict in one run.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Union

from go_avalanche_tpu.config import AvalancheConfig


class RecoveryViolation(AssertionError):
    """A recovery invariant of the fault script failed on the trace."""


def jnp_ndim(x) -> int:
    """ndim of a device or host array without importing jax eagerly at
    module load (recovery is importable in stripped environments)."""
    return len(getattr(x, "shape", ()))


@dataclasses.dataclass
class RecoveryReport:
    """Outcome of `verify_recovery`: the machine-checked verdict plus
    the recovery curve's summary numbers (per merged cut window)."""

    ok: bool
    violations: List[str]
    # One dict per MERGED cut window (overlapping cut events — e.g. a
    # cascading two-region outage — verify as one composite outage):
    #   start, heal, baseline_occupancy, recovery_round (first round
    #   >= heal with occupancy back at baseline; None if never),
    #   recovery_rounds (recovery_round - heal), blocked (draws severed
    #   during the window).
    windows: List[Dict]
    totals: Dict

    def __bool__(self) -> bool:  # `assert report` reads naturally
        return self.ok


def load_trace(path: Union[str, Path]) -> List[Dict]:
    """Read a flight-recorder JSONL trace, sorted by `round`.

    Accepts both emission modes (docs/observability.md): the in-graph
    tap's unordered lines and `write_stacked`'s pre-sorted ones.
    """
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return sorted(records, key=lambda r: r["round"])


def merged_cut_windows(cfg: AvalancheConfig) -> List[tuple]:
    """The script's STATIC cut events collapsed into disjoint
    ``[start, heal)`` outage intervals (see `_merge_windows`).
    Stochastic cuts have no static window — callers verifying a
    stochastic script pass the trial's REALIZED windows explicitly
    (`verify_recovery(..., windows=...)`, from
    `fleet.FleetResult.cut_windows`)."""
    return _merge_windows((e[1], e[2]) for e in cfg.cut_events())


def _max_scheduled_latency(cfg: AvalancheConfig) -> Optional[int]:
    """Worst-case deliverable latency any draw can be stamped with
    (base mode max + the tallest active spike — a stochastic spike
    counts its range's HI, the worst realization), or None when the
    mode is unbounded (geometric)."""
    if cfg.latency_mode in ("none",):
        base = 0
    elif cfg.latency_mode in ("fixed", "weighted"):
        base = cfg.latency_rounds
    elif cfg.latency_mode == "rtt":
        base = max(entry for row in cfg.rtt_matrix for entry in row)
    else:  # geometric: unbounded tail expires on its own
        return None
    spike = max((e[3] for e in cfg.spike_events()), default=0)
    spike = max(spike, max((e[3][1] for e in cfg.stochastic_spike_events()),
                           default=0))
    return base + spike


def _merge_windows(spans) -> List[tuple]:
    """Collapse [start, heal) spans into disjoint intervals —
    overlapping or back-to-back outages recover as one composite
    window (occupancy cannot return to baseline between cuts that
    share rounds)."""
    merged: List[tuple] = []
    for start, end in sorted((int(s), int(e)) for s, e in spans):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def _series(records: Sequence[Dict], field: str) -> List[int]:
    try:
        return [int(r[field]) for r in records]
    except KeyError:
        raise ValueError(
            f"trace records lack the {field!r} counter — recovery "
            f"checking needs the async-era ring telemetry "
            f"(deliveries/expiries/ring_occupancy/partition_blocked; "
            f"every model's round carries it since PR 5)")


def verify_recovery(
    cfg: AvalancheConfig,
    records: Sequence[Dict],
    occupancy_slack: int = 2,
    windows: Optional[Sequence] = None,
) -> RecoveryReport:
    """Verify the recovery invariants of `cfg`'s fault script against a
    stride-1 per-round trace; returns a `RecoveryReport` (violations
    collected, not raised — `check_recovery` is the raising wrapper).

    `occupancy_slack` widens the occupancy-recovery bound past the
    structural ``timeout_rounds()`` tail (default 2 rounds: scheduling
    jitter from entries issued in the heal round itself).

    `windows` supplies the REALIZED ``[start, heal)`` spans of the
    script's stochastic cuts — REQUIRED when the script schedules any
    (their windows are per-trial; the fleet driver returns them as
    `FleetResult.cut_windows`).  They are MERGED with the script's
    static cut windows, not a replacement: a mixed static+stochastic
    script still checks occupancy recovery after every static heal.
    """
    violations: List[str] = []
    if windows is None:
        if cfg.stochastic_cut_events():
            raise ValueError(
                "this script schedules stochastic_partition events, "
                "whose windows are realized per trial — pass the "
                "trial's realized windows explicitly "
                "(verify_recovery(..., windows=...); the fleet driver "
                "returns them as FleetResult.cut_windows)")
        cut_windows = merged_cut_windows(cfg)
    else:
        cut_windows = _merge_windows(
            [(int(s), int(e)) for s, e in windows]
            + [(e[1], e[2]) for e in cfg.cut_events()])
    records = sorted(records, key=lambda r: r["round"])
    rounds = [int(r["round"]) for r in records]
    n_rounds = len(records)
    if rounds != list(range(n_rounds)):
        raise ValueError(
            f"recovery checking needs a stride-1 trace covering rounds "
            f"0..R-1 (metrics_every=1); got rounds "
            f"{rounds[:3]}..{rounds[-3:] if n_rounds >= 3 else rounds}")
    expiries = _series(records, "expiries")
    occupancy = _series(records, "ring_occupancy")
    blocked = _series(records, "partition_blocked")
    finalizations = _series(records, "finalizations")
    timeout = cfg.timeout_rounds()

    # --- 1. cut accounting: blocked draws expire exactly one timeout
    # later; strict equality when cuts are the only expiry source.
    max_lat = _max_scheduled_latency(cfg)
    strict = max_lat is not None and max_lat < timeout
    for r in range(n_rounds):
        expected = blocked[r - timeout] if r >= timeout else 0
        if strict and expiries[r] != expected:
            violations.append(
                f"cut accounting: round {r} reaped {expiries[r]} "
                f"expiries but round {r - timeout} blocked {expected} "
                f"draws (blocked queries must expire exactly "
                f"timeout_rounds={timeout} later, and nothing else "
                f"expires under this config)")
        elif not strict and expiries[r] < expected:
            violations.append(
                f"cut accounting: round {r} reaped only {expiries[r]} "
                f"expiries for {expected} draws blocked at round "
                f"{r - timeout} — blocked queries vanished unreaped")

    # --- 2. occupancy returns to the pre-fault baseline after each heal.
    windows = []
    for start, heal in cut_windows:
        if 1 <= start <= n_rounds:
            baseline = occupancy[start - 1]
        else:
            # A cut live from round 0 has no pre-fault round to anchor
            # on — anchor on the trace's final occupancy, the post-heal
            # steady state the drain must reach (never 0: any nonzero
            # latency keeps ~N*k queries permanently in flight).
            baseline = occupancy[-1] if n_rounds else 0
        bound = heal + timeout + occupancy_slack
        recovery_round = next(
            (r for r in range(min(heal, n_rounds), n_rounds)
             if occupancy[r] <= baseline), None)
        window_blocked = sum(blocked[start:heal])
        windows.append(dict(start=start, heal=heal,
                            baseline_occupancy=baseline,
                            recovery_round=recovery_round,
                            recovery_rounds=(None if recovery_round is None
                                             else recovery_round - heal),
                            blocked=window_blocked))
        if heal >= n_rounds:
            violations.append(
                f"occupancy recovery: the trace ({n_rounds} rounds) ends "
                f"before the cut window [{start}, {heal}) heals — run "
                f"past the heal to verify recovery")
        elif recovery_round is None or recovery_round > bound:
            at = (f"round {recovery_round}" if recovery_round is not None
                  else "never")
            violations.append(
                f"occupancy recovery: after the heal at round {heal}, "
                f"ring occupancy first returned to its pre-fault "
                f"baseline ({baseline}) {at}, past the bound "
                f"heal + timeout + slack = {bound} — blocked entries "
                f"must drain within one timeout of the heal")

    # --- 3. finality monotonicity across events.
    for r, f in enumerate(finalizations):
        if f < 0:
            violations.append(
                f"finality monotonicity: round {r} reports "
                f"{f} finalizations — the finalized count decreased "
                f"(finalized records must freeze across fault events)")

    totals = dict(rounds=n_rounds,
                  blocked_total=sum(blocked),
                  expiries_total=sum(expiries),
                  deliveries_total=sum(_series(records, "deliveries")),
                  finalizations_total=sum(finalizations),
                  peak_occupancy=max(occupancy, default=0),
                  strict_cut_accounting=strict)
    return RecoveryReport(ok=not violations, violations=violations,
                          windows=windows, totals=totals)


def is_fleet_trace(records: Sequence[Dict]) -> bool:
    """True when the trace is FLEET-STACKED: counter fields carry
    per-trial LISTS (a leading trial axis) instead of scalars — the
    format `fleet.fleet_trace_records` emits and a fleet `--metrics`
    run writes (docs/observability.md)."""
    for r in records:
        for field, v in r.items():
            if field != "round" and isinstance(v, (list, tuple)):
                return True
        return False
    return False


def _trial_records(records: Sequence[Dict], trial: int) -> List[Dict]:
    """Slice one trial's scalar record stream out of a fleet-stacked
    trace (non-list fields — `round`, `tag` — pass through)."""
    return [{k: (v[trial] if isinstance(v, (list, tuple)) else v)
             for k, v in r.items()} for r in records]


def verify_recovery_fleet(
    cfg: AvalancheConfig,
    records: Sequence[Dict],
    occupancy_slack: int = 2,
    windows: Optional[Sequence] = None,
) -> List[RecoveryReport]:
    """Per-trial recovery verdicts for a FLEET-STACKED trace: one
    `RecoveryReport` per trial, in trial order — the verdict VECTOR a
    Monte-Carlo sweep reduces to P(recovery) with a Wilson CI
    (`fleet.wilson_interval`).

    `windows`, when given, is PER-TRIAL: ``windows[i]`` holds trial i's
    realized ``[start, heal)`` spans (`fleet.FleetResult.cut_windows`
    is exactly this shape) — required for stochastic scripts, whose
    realized schedules differ per trial.  Mixed-width records (a trial
    axis that changes length mid-trace) raise `ValueError`.
    """
    records = sorted(records, key=lambda r: r["round"])
    widths = {len(v) for r in records for v in r.values()
              if isinstance(v, (list, tuple))}
    if len(widths) != 1:
        raise ValueError(
            f"a fleet-stacked trace carries ONE trial-axis width on "
            f"every counter field; got widths {sorted(widths)}")
    fleet = widths.pop()
    if windows is not None and len(windows) != fleet:
        raise ValueError(
            f"per-trial windows ({len(windows)}) must match the "
            f"trace's trial axis ({fleet})")
    return [verify_recovery(cfg, _trial_records(records, i),
                            occupancy_slack=occupancy_slack,
                            windows=None if windows is None
                            else windows[i])
            for i in range(fleet)]


def check_recovery(
    cfg: AvalancheConfig,
    trace: Union[str, Path, Sequence[Dict]],
    occupancy_slack: int = 2,
    windows: Optional[Sequence] = None,
) -> Union[RecoveryReport, List[RecoveryReport]]:
    """`verify_recovery` that LOADS a JSONL path (or takes records) and
    RAISES `RecoveryViolation` listing every failed invariant; returns
    the passing report otherwise.

    A FLEET-STACKED trace (per-trial list values — `is_fleet_trace`)
    returns the per-trial verdict VECTOR (`verify_recovery_fleet`)
    WITHOUT raising: a Monte-Carlo sweep's product is the fraction of
    trials that recovered, not a first-shape-mismatch exception —
    callers reduce ``[r.ok for r in reports]`` to P(recovery) ± CI.
    `windows` follows the selected mode's contract (scalar spans, or
    per-trial spans for a fleet trace).
    """
    if isinstance(trace, (str, Path)):
        trace = load_trace(trace)
    elif hasattr(trace, "columns") and hasattr(trace, "stride"):
        # An on-device TraceBuffer (obs/trace.py): decode directly —
        # rows are ordered by construction (slot index == round //
        # stride), so no unordered-io_callback re-sort is needed; a
        # fleet-vmapped [F, S, M] buffer decodes to the fleet-stacked
        # record format and takes the per-trial verdict path below.
        from go_avalanche_tpu.obs import trace as trace_mod

        trace = (trace_mod.fleet_trace_records(trace)
                 if jnp_ndim(trace.data) == 3
                 else trace_mod.trace_records(trace))
    if is_fleet_trace(trace):
        return verify_recovery_fleet(cfg, trace,
                                     occupancy_slack=occupancy_slack,
                                     windows=windows)
    report = verify_recovery(cfg, trace, occupancy_slack=occupancy_slack,
                             windows=windows)
    if not report.ok:
        raise RecoveryViolation(
            "recovery invariants violated:\n  "
            + "\n  ".join(report.violations))
    return report
