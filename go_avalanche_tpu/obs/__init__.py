"""On-device flight recorder: streaming metrics, manifests, watchdog.

The observability layer (docs/observability.md):

  * `sink`     — JSONL metrics sink: host-side streaming of stacked
                 telemetry, plus the zero-dispatch in-graph tap
                 (`emit_round`) the dense rounds call under
                 `cfg.metrics_every`;
  * `manifest` — run-manifest writer (config, jax/device topology,
                 hlo-pin hashes, git sha) emitted next to every metrics
                 file by `bench.py` and `run_sim.py`;
  * `trace`    — the on-device trace plane (PR 11): a `TraceBuffer`
                 ``[S, M]`` pytree carried in the sim state and written
                 in-graph via one `dynamic_update_slice` per emitted
                 round — the zero-callback tap that works under
                 `shard_map` and under the fleet vmap (per-trial
                 ``[F, S, M]`` traces), decoded to the same JSONL
                 schema;
  * `tags`     — `tag_from_config`: the one metric-tag spelling shared
                 by bench, roofline and the sink;
  * `watchdog` — opt-in invariant checks (`run_sim --check-invariants`)
                 that turn silent state corruption into loud failures;
  * `recovery` — recovery-curve checker (PR 6): machine-verifies a
                 fault script's cut accounting, occupancy recovery and
                 finality monotonicity from a flight-recorder trace.
"""

from go_avalanche_tpu.obs.manifest import (  # noqa: F401
    manifest_dict,
    manifest_path_for,
    write_manifest,
)
from go_avalanche_tpu.obs.sink import (  # noqa: F401
    MetricsSink,
    emit_round,
    metrics_sink,
)
from go_avalanche_tpu.obs.recovery import (  # noqa: F401
    RecoveryReport,
    RecoveryViolation,
    check_recovery,
    verify_recovery,
    verify_recovery_fleet,
)
from go_avalanche_tpu.obs.tags import tag_from_config  # noqa: F401
from go_avalanche_tpu.obs.trace import (  # noqa: F401
    TraceBuffer,
    fleet_trace_records,
    trace_records,
    write_trace,
)
from go_avalanche_tpu.obs.watchdog import (  # noqa: F401
    InvariantViolation,
    Watchdog,
    check_records,
    check_ring,
    check_ring_cut,
    check_trace,
)
