"""Resource-observability plane: analytic footprints vs compiled memory.

The flight recorder (sink/trace/manifest) sees every EVENT; this module
is the first plane that sees RESOURCES.  Three pieces:

  * `footprint` — the ANALYTIC per-plane footprint model: byte counts
    for every leaf of a sim-state pytree, derived purely from config
    shapes (`jax.eval_shape` — nothing allocates, full bench shape
    costs milliseconds on any host).  With a `PartitionSpec` tree and a
    mesh it accounts PER-DEVICE bytes (sharded planes divide by their
    mesh axes, replicated planes count whole) — the same arithmetic
    the XLA allocator does for a `shard_map` program.
  * `memory_record` — the COMPILED side: `compiled.memory_analysis()`
    (argument / output / temp / generated-code / aliased bytes) plus
    the donation-adjusted live peak.
  * `check_memory` — the assertion joining the two: the compiled
    argument bytes must equal the analytic state bytes, and for a
    donated program the aliased bytes must COVER the state.  A failure
    means an unaccounted buffer clone — an undonated copy, a plane
    XLA silently double-buffers, a leaked intermediate — exactly the
    class the PR-4 fori-loop work chased by hand through HLO dumps.

`benchmarks/mem_pin.py` archives `memory_record` for every pinned
program + the five sharded drivers (`benchmarks/mem_pin.json`);
`benchmarks/vmem_knee.py` sweeps `footprint` over the `[F, N, T]` cube;
`run_sim --report-memory` prints both for the exact program a flag
selection runs.  The plane only READS programs — no archived HLO pin
moves.
"""

from __future__ import annotations

from typing import Dict, List, Optional

LIVE_PEAK_DOC = ("argument + output - aliased + temp bytes: what the "
                 "allocator must hold at the program's high-water mark "
                 "once donation collapses each aliased output into its "
                 "argument buffer")


def plane_bytes(state_abs, specs=None, mesh=None) -> Dict[str, int]:
    """Per-plane byte counts for a (possibly abstract) state pytree.

    Keys are `jax.tree_util.keystr` paths (the spelling the trace
    plane's column manifest and the watchdog reports already use).
    With `specs` (a `PartitionSpec` pytree matching `state_abs`, e.g. a
    driver's `state_specs(...)`) and `mesh`, each leaf is counted at
    its PER-DEVICE shard shape — sharded dims divide by their mesh
    axes, replicated leaves count whole, exactly as placed.
    """
    import jax
    from jax.tree_util import keystr, tree_flatten_with_path

    shardings = None
    if specs is not None:
        if mesh is None:
            raise ValueError("plane_bytes: specs without a mesh — "
                             "per-device accounting needs axis sizes")
        from jax.sharding import NamedSharding

        shardings = [
            NamedSharding(mesh, s)
            for _, s in tree_flatten_with_path(
                specs, is_leaf=lambda x: x is None)[0]
            if s is not None]

    out: Dict[str, int] = {}
    leaves = tree_flatten_with_path(state_abs)[0]
    if shardings is not None and len(shardings) != len(leaves):
        raise ValueError(
            f"plane_bytes: {len(shardings)} partition specs for "
            f"{len(leaves)} state leaves — the spec tree does not "
            f"match the state")
    for i, (path, leaf) in enumerate(leaves):
        shape = tuple(leaf.shape)
        if shardings is not None:
            shape = shardings[i].shard_shape(shape)
        n = 1
        for d in shape:
            n *= d
        out[keystr(path)] = int(n) * int(
            jax.dtypes.canonicalize_dtype(leaf.dtype).itemsize
            if not hasattr(leaf.dtype, "itemsize") else leaf.dtype.itemsize)
    return out


def footprint(state_abs, specs=None, mesh=None) -> Dict:
    """``{"total_bytes": N, "planes": {path: bytes}}`` for a state
    pytree — the analytic footprint model (see `plane_bytes`)."""
    planes = plane_bytes(state_abs, specs, mesh)
    return {"total_bytes": sum(planes.values()), "planes": planes}


def memory_record(compiled) -> Dict[str, int]:
    """The compiled program's memory ledger, from
    ``compiled.memory_analysis()`` (an XLA `CompiledMemoryStats`).

    ``live_peak_bytes`` is the donation-adjusted high-water mark:
    argument + output - aliased + temp (aliased output buffers ARE
    their argument buffers at runtime, so they count once).
    """
    ma = compiled.memory_analysis()
    rec = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    rec["live_peak_bytes"] = (rec["argument_bytes"] + rec["output_bytes"]
                              - rec["alias_bytes"] + rec["temp_bytes"])
    return rec


def check_memory(record: Dict[str, int], analytic_total: int, *,
                 donated: bool = True, extra_output_ok: bool = False,
                 rel_tol: float = 0.02, abs_tol: int = 4096,
                 what: str = "program") -> List[str]:
    """Assert a compiled `memory_record` against the analytic footprint.

    * the ARGUMENT bytes must match `analytic_total` within tolerance —
      a surplus means the program takes buffers the state model does
      not account for, a deficit means a state plane never reached the
      device;
    * the OUTPUT bytes must match too (`extra_output_ok=True` relaxes
      to >=, for scan programs that return stacked telemetry next to
      the evolved state);
    * with `donated=True`, the ALIASED bytes must COVER the state: an
      undonated copy (jit without donate_argnums, a plane silently
      un-donated by a dtype/layout mismatch, an explicit clone) leaves
      alias short of argument and fails loudly.

    Returns failure strings (empty = clean).  Tolerance is
    ``max(rel_tol * analytic_total, abs_tol)`` — XLA may pad tiny
    bookkeeping buffers (tuple tables, predicates) that are real but
    not planes.
    """
    tol = max(int(rel_tol * analytic_total), abs_tol)
    failures: List[str] = []
    arg = record["argument_bytes"]
    out = record["output_bytes"]
    alias = record["alias_bytes"]
    if abs(arg - analytic_total) > tol:
        failures.append(
            f"{what}: compiled argument bytes {arg} != analytic state "
            f"footprint {analytic_total} (tol {tol}) — "
            f"{'an unaccounted input buffer rides the program' if arg > analytic_total else 'a state plane never reached the entry signature'}")
    if extra_output_ok:
        if out + tol < analytic_total:
            failures.append(
                f"{what}: compiled output bytes {out} < analytic state "
                f"footprint {analytic_total} (tol {tol}) — the evolved "
                f"state is not among the outputs")
    elif abs(out - analytic_total) > tol:
        failures.append(
            f"{what}: compiled output bytes {out} != analytic state "
            f"footprint {analytic_total} (tol {tol}) — "
            f"{'an unaccounted buffer clone is returned next to the state' if out > analytic_total else 'a state plane is missing from the outputs'}")
    if donated and alias + tol < analytic_total:
        failures.append(
            f"{what}: aliased bytes {alias} do not cover the analytic "
            f"state footprint {analytic_total} (tol {tol}) — "
            f"{analytic_total - alias} bytes of state double-buffer "
            f"instead of updating in place (an undonated copy)")
    return failures


def banded_compare(archived: Dict[str, int], current: Dict[str, int],
                   band: float = 0.10, what: str = "program"
                   ) -> List[str]:
    """Tolerance-banded comparison of two memory records (the mem-pin
    tier-1 check).  Argument/output/alias bytes are shape arithmetic
    and must match EXACTLY; temp and generated-code bytes are compiler
    decisions and may drift within `band` (fractional) before the pin
    is declared moved."""
    failures: List[str] = []
    for key in ("argument_bytes", "output_bytes", "alias_bytes"):
        if archived.get(key) != current.get(key):
            failures.append(
                f"{what}: {key} moved {archived.get(key)} -> "
                f"{current.get(key)} — the program's buffer interface "
                f"changed (re-pin with --update if intended)")
    for key in ("temp_bytes", "generated_code_bytes"):
        a, c = archived.get(key, 0), current.get(key, 0)
        lo = min(a, c)
        if abs(a - c) > max(band * max(a, 1), 64):
            failures.append(
                f"{what}: {key} drifted {a} -> {c} "
                f"({100.0 * abs(a - c) / max(lo, 1):.1f}% > "
                f"{100 * band:.0f}% band) — the compiler's scratch "
                f"plan changed (re-pin with --update if intended)")
    return failures


def sharded_driver_records(drivers: Optional[List[str]] = None) -> Dict:
    """`memory_record` + analytic per-device footprint for each sharded
    driver's base audit-shape program on the 2x2 audit mesh
    (`parallel.footprint_cases` — the same states and program seams the
    contract auditor lowers).  Returns ``{driver: {"record": ...,
    "footprint": ..., "hlo": sha256}}``; raises
    `analysis.hlo_audit.AuditUnavailable` under 4 devices.
    """
    from benchmarks.hlo_pin import hlo_hash
    from go_avalanche_tpu import parallel

    out: Dict[str, Dict] = {}
    for name, case in parallel.footprint_cases(drivers).items():
        lowered = case.program_builder(case.mesh).lower(case.state_abs)
        out[name] = {
            "record": memory_record(lowered.compile()),
            "footprint": footprint(case.state_abs, case.specs,
                                   case.mesh),
            "hlo": hlo_hash(lowered.as_text()),
        }
    return out
