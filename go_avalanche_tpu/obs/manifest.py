"""Run-manifest writer: the provenance record next to every metrics file.

A metrics trace without its construction context is unreplayable — the
round-3 postmortem pattern (BENCH artifacts whose shape/backend had to
be reverse-engineered from the metric string).  The manifest captures,
at run time:

  * the full `AvalancheConfig` as a dict (enums by value);
  * jax / jaxlib versions and the device topology (platform, kind,
    count) the run actually saw;
  * the current `benchmarks/hlo_pin.json` program hashes, so a trace is
    joinable against the exact compiled-program generation it came from;
  * the git commit (best-effort: absent outside a checkout);
  * any caller extras (workload shape, CLI argv, metric tag).

`bench.py` and `run_sim.py` write one next to every metrics file
(`manifest_path_for`: ``<metrics>.manifest.json``).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import subprocess
from pathlib import Path
from typing import Optional

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_HLO_PIN = _REPO_ROOT / "benchmarks" / "hlo_pin.json"


def _config_dict(cfg) -> dict:
    out = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, enum.Enum):
            v = v.value
        elif isinstance(v, tuple):
            v = list(v)
        out[f.name] = v
    return out


def _git_sha() -> Optional[str]:
    try:
        proc = subprocess.run(
            ["git", "-C", str(_REPO_ROOT), "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.SubprocessError):
        return None
    sha = proc.stdout.strip()
    return sha if proc.returncode == 0 and sha else None


def _pin_hashes() -> Optional[dict]:
    try:
        archive = json.loads(_HLO_PIN.read_text())
    except (OSError, ValueError):
        return None
    return {name: entry.get("hashes", {})
            for name, entry in archive.get("programs", {}).items()}


def manifest_dict(cfg=None, extra: Optional[dict] = None) -> dict:
    """Assemble the manifest (see module docstring); pure, no I/O writes.

    Every field is best-effort — a manifest from a stripped environment
    (no git, no pin archive, no devices) still records what it can.
    """
    import jax

    try:
        devices = jax.devices()
        topology = {
            "platform": devices[0].platform,
            "device_kind": getattr(devices[0], "device_kind", None),
            "device_count": len(devices),
        }
    except Exception:  # noqa: BLE001 — backend init can fail outright
        topology = None

    manifest = {
        "jax": jax.__version__,
        "jaxlib": getattr(jax, "jaxlib_version", None) or _jaxlib_version(),
        # `backend` duplicates devices.platform ON PURPOSE: it is the
        # perf ledger's comparison key (benchmarks/ledger.py), and a
        # consumer must never have to dig through the topology dict —
        # or worse, the metric string — to learn it.  Manifests
        # predating the field read as backend="unknown" and are
        # gate-excluded, never silently compared.
        "backend": topology["platform"] if topology else "unknown",
        "devices": topology,
        "git_sha": _git_sha(),
        "hlo_pins": _pin_hashes(),
    }
    if cfg is not None:
        manifest["config"] = _config_dict(cfg)
        manifest["tap"] = _tap_dict(cfg)
    if extra:
        manifest.update(extra)
    return manifest


def _tap_dict(cfg) -> dict:
    """Which telemetry tap(s) the run's config selected, with strides —
    a trace file's consumer must know whether its rows came from the
    io_callback tap or the on-device trace plane (obs/trace.py) and at
    what stride, without re-deriving it from the config dump."""
    metrics = getattr(cfg, "metrics_every", 0)
    trace = getattr(cfg, "trace_every", 0)
    if metrics > 0 and trace > 0:
        kind = "callback+trace"
    elif trace > 0:
        kind = "trace"
    elif metrics > 0:
        kind = "callback"
    else:
        kind = "none"
    return {"kind": kind, "metrics_every": metrics, "trace_every": trace}


def _jaxlib_version() -> Optional[str]:
    try:
        import jaxlib
        return jaxlib.__version__
    except Exception:  # noqa: BLE001
        return None


def manifest_path_for(metrics_path) -> Path:
    """``<metrics file>.manifest.json`` — always NEXT TO the metrics
    file, whatever its own suffix."""
    p = Path(metrics_path)
    return p.with_name(p.name + ".manifest.json")


def write_manifest(metrics_path, cfg=None,
                   extra: Optional[dict] = None) -> Path:
    """Write the manifest next to `metrics_path`; returns its path."""
    path = manifest_path_for(metrics_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(manifest_dict(cfg, extra), indent=2,
                               sort_keys=True) + "\n")
    return path
