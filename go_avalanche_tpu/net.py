"""Peer registry — layer L3 (`net.go:3-31`).

The reference's `Connman` is a pure membership map (no sockets, no transport);
ours is the same seam, kept as the host-side plugin boundary (SURVEY.md
section 2.4 item 6), with two additions the simulator needs: removal (churn)
and deterministic ordering (the reference's `NodesIDs` inherits Go map
iteration randomness; we return sorted IDs so runs are reproducible).
"""

from __future__ import annotations

from typing import Dict, List

from go_avalanche_tpu.types import NodeID


class _Node:
    """Per-peer record (`net.go:3-9`); a latency weight for weighted sampling."""

    __slots__ = ("id", "latency_weight")

    def __init__(self, node_id: NodeID, latency_weight: float = 1.0) -> None:
        self.id = node_id
        self.latency_weight = latency_weight


class Connman:
    """Node membership registry (`net.go:11-31`)."""

    def __init__(self) -> None:
        self._nodes: Dict[NodeID, _Node] = {}

    def add_node(self, node_id: NodeID,
                 latency_weight: float = 1.0) -> None:
        """Register a peer (`net.go:21-23`)."""
        self._nodes[node_id] = _Node(node_id, latency_weight)

    def remove_node(self, node_id: NodeID) -> bool:
        """Deregister a peer (churn support; absent in the reference)."""
        return self._nodes.pop(node_id, None) is not None

    def nodes_ids(self) -> List[NodeID]:
        """All registered peer IDs, ascending (`net.go:25-31`, made
        deterministic)."""
        return sorted(self._nodes)

    def latency_weight(self, node_id: NodeID) -> float:
        return self._nodes[node_id].latency_weight

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node_id: NodeID) -> bool:
        return node_id in self._nodes

    # Reference-spelling aliases for drop-in familiarity.
    AddNode = add_node
    NodesIDs = nodes_ids
