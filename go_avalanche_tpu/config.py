"""Protocol and simulation configuration.

The reference hard-codes four protocol constants (reference `avalanche.go:8-22`)
and buries two more in the vote kernel (window size 8 implicit in the `uint8`
sliding window, `vote.go:55`; quorum 7 implicit in the `> 6` popcount test,
`vote.go:58`).  Here every protocol parameter is an explicit, sweepable field of
a frozen dataclass so whole parameter sweeps can be expressed as configs.

The config is *static* with respect to jit: it is hashable and is closed over
(or passed as a static argument) by the compiled step functions, so every field
participates in XLA constant folding rather than being traced.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Optional, Tuple


class VoteMode(enum.Enum):
    """How one simulated round turns k sampled peer preferences into votes.

    SEQUENTIAL — faithful to the reference's ingest path: each peer's vote is
    pushed through the 8-vote sliding window one at a time, in sample order
    (`processor.go:94-117` applies votes one by one via `vote.go:54`).

    MAJORITY — Avalanche-paper style: the k sampled preferences are reduced to
    a single conclusive yes/no chit per round when >= alpha*k agree, else a
    neutral vote; the chit is pushed through the window once.  This matches how
    Bitcoin ABC uses the window (one aggregated poll result per round).
    """

    SEQUENTIAL = "sequential"
    MAJORITY = "majority"


class AdversaryStrategy(enum.Enum):
    """What a byzantine peer answers when it lies (see `ops/adversary.py`).

    FLIP — the opposite of its true preference: the reference's
    commented-out hook (`examples/basic-preconcensus/main.go:184-187`).
    EQUIVOCATE — a fresh coin per (querier, draw[, target]); the same peer
    tells different queriers different things within one round.
    OPPOSE_MAJORITY — the current global minority color; the Avalanche
    paper's liveness adversary, pulling the network back toward a split.
    """

    FLIP = "flip"
    EQUIVOCATE = "equivocate"
    OPPOSE_MAJORITY = "oppose_majority"


# Adaptive adversary policies (`cfg.adversary_policy`, ops/adversary.py):
# jit-static attack KINDS that read the current network state each round
# — the arXiv 2401.02811 class of adversaries the static strategies
# can't express (a strategy decides what one lie says; a policy decides
# WHERE/WHEN/WHAT as a function of the observed state).  "off" is the
# exact pre-policy code path: no context plane is built and every
# archived hlo pin is byte-identical (hlo_pin.py --verify-off-path).
#
#   split_vote           — lies vote the HONEST population's minority
#                          color per target (equivocation coins on an
#                          exact tie), holding honest preferences at an
#                          even split — the 2401.02811 stall attack.
#                          Overrides the strategy's lie CONTENT.
#   withhold_near_quorum — lying draws go SILENT (no responded bit;
#                          with the async engine on they get the
#                          never-delivers sentinel and expire through
#                          the existing timeout machinery) exactly when
#                          the querier holds a record within
#                          `adversary_margin` window votes of the
#                          conclusive quorum — denying the finishing
#                          votes.
#   stake_eclipse        — lies concentrate on the top-stake HONEST
#                          queriers (the most-sampled responders, whose
#                          poisoned preferences propagate furthest
#                          through stake-weighted committees); needs a
#                          stake_mode.  Eclipse-set size is
#                          max(1, round(byzantine_fraction * N)),
#                          saturating at the honest population.
#   timing               — lying responses are DELAYED via the latency
#                          plane to land at age timeout_rounds() - 1,
#                          just before expiry (stalest-possible lies,
#                          maximum time-in-flight); needs the async
#                          engine.
ADVERSARY_POLICIES = ("off", "split_vote", "withhold_near_quorum",
                      "stake_eclipse", "timing")


# Fault-script event schema: kind -> positional field names after the
# kind tag — the one source for both spellings (tuple arity/shape in
# `_validate_fault_script`, JSON object keys in `fault_script_from_json`,
# the `run_sim --fault-script file.json` / scenario-file format in
# docs/observability.md).  Every event is a plain tuple so the whole
# script stays hashable (the config is a jit-static argument) and every
# ROUND FIELD is jit-STATIC: the script compiles into per-round masks
# inside the round's existing cond structure (`ops/inflight.py`), never
# into traced control flow.  All windows are END-EXCLUSIVE ([start,
# end), like `partition_spec`).
_FAULT_EVENT_FIELDS = {
    "partition": ("start", "end", "frac"),
    "regional_outage": ("start", "end", "cluster"),
    "latency_spike": ("start", "end", "extra_rounds"),
    "churn_burst": ("round", "frac"),
    # Stochastic events (PR 7, the Monte-Carlo fleet): every field is a
    # [lo, hi] RANGE (inclusive), not a scalar — the realized value is
    # drawn per SIMULATION from the sim's init key
    # (`ops/inflight.draw_fault_params`, stored as `state.fault_params`),
    # so each fleet trial sees a different realized schedule while the
    # event STRUCTURE (how many events, which kind, which ranges) stays
    # jit-static.  Windows are [start, start + length) — length replaces
    # the end field because a stochastic end could precede a stochastic
    # start.
    "stochastic_partition": ("start", "length", "frac"),
    "stochastic_spike": ("start", "length", "extra_rounds"),
    # PR 10 (the ROADMAP "more stochastic kinds" follow-up): a regional
    # outage whose CLUSTER is drawn per trial — `cluster` is a [lo, hi]
    # integer range inside [0, n_clusters), realized per sim alongside
    # start/length by `ops/inflight.draw_fault_params`.
    "stochastic_regional_outage": ("start", "length", "cluster"),
}

# The event kinds whose parameters are drawn at init rather than fixed
# in the script; their realized windows are per-trial, so they are
# exempt from the static overlap check (realized cut masks OR and spike
# extras ADD, so overlapping realizations compose deterministically).
_STOCHASTIC_KINDS = ("stochastic_partition", "stochastic_spike",
                     "stochastic_regional_outage")


def fault_script_from_json(data) -> Tuple[Tuple, ...]:
    """Parse a JSON-decoded fault script into the `cfg.fault_script`
    tuple spelling — STRUCTURAL errors only (semantic validation —
    ranges, overlaps, topology — stays in `AvalancheConfig`, so both
    spellings hit the one validator).

    Two event spellings, freely mixed in one list:

      [["partition", 2, 6, 0.5], ...]                     — tuples
      [{"kind": "partition", "start": 2, "end": 6,
        "frac": 0.5}, ...]                                — objects

    Raises `ValueError` with the offending index; `run_sim` funnels
    that into `parser.error` so a malformed script dies at the parser,
    never in the worker (the PR 5 `--metrics-every` rule).
    """
    if not isinstance(data, (list, tuple)):
        raise ValueError(
            f"a fault script is a JSON LIST of events, got "
            f"{type(data).__name__}")
    events = []
    for i, ev in enumerate(data):
        if isinstance(ev, dict):
            kind = ev.get("kind")
            if kind not in _FAULT_EVENT_FIELDS:
                raise ValueError(
                    f"event[{i}]: unknown event kind {kind!r}; known "
                    f"kinds: {', '.join(sorted(_FAULT_EVENT_FIELDS))}")
            fields = _FAULT_EVENT_FIELDS[kind]
            extra = set(ev) - {"kind", *fields}
            missing = [f for f in fields if f not in ev]
            if missing or extra:
                raise ValueError(
                    f"event[{i}]: {kind} events carry fields "
                    f"{', '.join(fields)}"
                    + (f" — missing {', '.join(missing)}" if missing
                       else "")
                    + (f" — unknown {', '.join(sorted(extra))}" if extra
                       else ""))
            events.append((kind,) + tuple(ev[f] for f in fields))
        elif isinstance(ev, (list, tuple)):
            events.append(tuple(ev))
        else:
            raise ValueError(
                f"event[{i}]: an event is a [kind, ...] list or a "
                f"{{'kind': ...}} object, got {type(ev).__name__}")
    return tuple(events)


@dataclasses.dataclass(frozen=True)
class AvalancheConfig:
    """All protocol constants of the reference plus simulator knobs.

    Reference constants (same defaults, now sweepable):
      finalization_score  — `avalanche.go:10`  (confidence needed to finalize)
      time_step_s         — `avalanche.go:13`  (event-loop tick, 10ms)
      max_element_poll    — `avalanche.go:17`  (max invs per query, 4096)
      request_timeout_s   — `avalanche.go:21`  (query expiry, 1 minute)
      window              — `vote.go:55`       (sliding vote window, uint8 => 8)
      quorum              — `vote.go:58`       (conclusive needs > quorum-1 of
                                                the non-neutral window bits)

    Simulator knobs (capability gaps, SURVEY.md section 2.4):
      k                — peers sampled per node per round (replaces the
                         lowest-id placeholder in `processor.go:173-182` and the
                         example's round-robin, `examples/.../main.go:111`).
      alpha            — majority threshold for VoteMode.MAJORITY.
      vote_mode        — see VoteMode.
      sample_with_replacement — True: k independent draws per node (cheapest);
                         False: k *distinct* peers per node, the protocol's
                         real query semantics (`ops/sampling.py:
                         sample_peers_distinct`).  Distinct draws are not
                         supported together with weighted_sampling (exact
                         weighted sampling without replacement needs per-row
                         O(N) Gumbel top-k state — O(N^2) at fleet scale).
      exclude_self     — never sample yourself (`main.go:114-116`).
      gossip           — gossip-on-poll admission: a polled peer admits targets
                         it has not seen (`main.go:177`).
      strict_validation — the request/response validation contract that the
                         reference compiled out behind `if false`
                         (`processor.go:62-90`); here it is an explicit mode
                         and both paths stay tested.
    """

    # --- protocol constants (reference parity) ---
    finalization_score: int = 128
    time_step_s: float = 0.010
    max_element_poll: int = 4096
    request_timeout_s: float = 60.0
    window: int = 8
    quorum: int = 7

    # --- simulator knobs ---
    k: int = 8
    alpha: float = 0.8
    vote_mode: VoteMode = VoteMode.SEQUENTIAL
    sample_with_replacement: bool = True
    exclude_self: bool = True
    weighted_sampling: bool = False   # draw peers prop. to latency weights
                                      #   (times aliveness); self-draws
                                      #   become abstentions
    n_clusters: int = 1               # > 1: clustered topology — nodes in
                                      #   contiguous-block clusters; draws
                                      #   prefer the own cluster (below).
                                      #   Composes with latency weights.
    cluster_locality: float = 0.8     # P(draw lands in own cluster), for
                                      #   equal-size clusters / uniform base
    gossip: bool = True
    fused_exchange: bool = True       # peer-exchange engine selector
                                      #   (ops/exchange.py).  True: ONE
                                      #   flattened gather of the packed
                                      #   preference plane produces all k
                                      #   vote planes, and gossip admission
                                      #   is one scatter over the flattened
                                      #   (peer, polled-plane) pairs.
                                      #   False: the legacy k-pass loops
                                      #   (k row-gathers, k scatter-ORs).
                                      #   Bit-exact either way — pinned by
                                      #   tests/test_exchange.py golden
                                      #   parity across every config axis.
    ingest_engine: str = "u8"         # RegisterVotes ingest engine
                                      #   (ops/voterecord.py
                                      #   register_packed_votes_engine).
                                      #   "u8": per-vote uint8 window
                                      #   updates + per-vote confidence
                                      #   fold — the golden-parity
                                      #   reference.  "swar32": 4 tx
                                      #   columns lane-packed per uint32
                                      #   word (ops/swar.py) with the
                                      #   closed-form confidence
                                      #   transition — native i32 VPU
                                      #   width, zero u8 widening.
                                      #   Bit-exact either way — pinned
                                      #   by tests/test_swar.py across
                                      #   every config axis.
    round_engine: str = "phased"      # whole-round execution engine for
                                      #   the dense avalanche SYNC round
                                      #   (models/avalanche.round_step).
                                      #   "phased": the pinned per-phase
                                      #   path — exchange gather, vote
                                      #   ingest and confidence fold as
                                      #   separate fused-op islands (the
                                      #   archived flagship program).
                                      #   "megakernel": ONE Pallas
                                      #   program (ops/megakernel.py)
                                      #   runs gather -> SWAR ingest ->
                                      #   closed-form confidence with a
                                      #   block's record planes resident
                                      #   in VMEM across all k draws —
                                      #   no [N, k] vote pack and no
                                      #   intermediate [N, T] planes
                                      #   round-trip HBM.  Sync
                                      #   SEQUENTIAL rounds only (see
                                      #   _validate_round_engine);
                                      #   dag/snowball and the sharded
                                      #   drivers keep phased and reject
                                      #   the knob as inert.  Bit-exact
                                      #   either way — pinned by
                                      #   tests/test_megakernel.py in
                                      #   interpreter mode.
    fused_sharded_gossip: bool = False
                                      # sharded gossip-admission scatter
                                      #   (parallel/sharded.py
                                      #   _gossip_heard_packed): False =
                                      #   8 serial per-bit scatter-maxes
                                      #   on the packed plane; True = ONE
                                      #   batched scatter over an
                                      #   [8, N, T/8] per-bit stack (same
                                      #   ICI traffic — the OR-fold
                                      #   precedes the all_to_all — at 8x
                                      #   the scatter scratch).  Opt-in
                                      #   until a hardware A/B prices the
                                      #   dispatch-vs-scratch trade
                                      #   (ROADMAP).  Bit-exact either
                                      #   way (tests/test_sharding.py).
    strict_validation: bool = False
    latency_mode: str = "none"        # asynchronous query lifecycle
                                      #   (ops/inflight.py).  "none": the
                                      #   synchronous ideal — every poll
                                      #   resolves within its issuing
                                      #   round, request_timeout_s is
                                      #   inert (the pre-PR-3 scale
                                      #   path).  Any other mode turns on
                                      #   the in-flight engine: each
                                      #   (querier, draw) gets a response
                                      #   latency in ROUNDS —
                                      #   "fixed":     every draw takes
                                      #                latency_rounds;
                                      #   "geometric": iid geometric with
                                      #                mean
                                      #                latency_rounds;
                                      #   "weighted":  coupled to the
                                      #                latency_weight
                                      #                plane — the
                                      #                highest-weight
                                      #                (nearest) peer
                                      #                answers in 0
                                      #                rounds, the lowest
                                      #                in latency_rounds,
                                      #                linear in between
                                      #   — and responses older than
                                      #   timeout_rounds() expire
                                      #   UNANSWERED (host Processor
                                      #   reaping semantics,
                                      #   processor.py:262-269), flowing
                                      #   into skip_absent_votes
                                      #   exactly like drops.
                                      #   SEQUENTIAL vote mode only.
    latency_rounds: int = 0           # see latency_mode; 0 with mode
                                      #   "fixed" is bit-exact with the
                                      #   synchronous round (pinned by
                                      #   tests/test_inflight.py)
    partition_spec: Optional[Tuple[int, int, float]] = None
                                      # (round_start, round_end,
                                      #   split_frac): a network
                                      #   partition active for rounds
                                      #   [start, end) — END-EXCLUSIVE:
                                      #   the cut fires in rounds start
                                      #   .. end-1 and round `end` is
                                      #   the first healed round, so
                                      #   start == end is a zero-length
                                      #   window that never fires and
                                      #   is REJECTED.  Nodes split at
                                      #   floor(split_frac * N) —
                                      #   cluster-aligned when
                                      #   n_clusters > 1 (the cut lands
                                      #   on a cluster boundary, so no
                                      #   cluster straddles it).
                                      #   Cross-partition queries TIME
                                      #   OUT (expire unanswered at
                                      #   timeout_rounds()) rather than
                                      #   silently vanishing; after
                                      #   `end` the partition heals and
                                      #   in-flight cross-cut entries
                                      #   still expire (the queries were
                                      #   lost, not delayed).  Setting
                                      #   this turns on the in-flight
                                      #   engine even with latency_mode
                                      #   "none" semantics (latency 0
                                      #   within each side).  SUGAR: it
                                      #   is exactly the one-event
                                      #   fault_script
                                      #   (("partition", start, end,
                                      #   frac),) — `fault_events()`
                                      #   merges the two spellings.
    fault_script: Optional[Tuple[Tuple, ...]] = None
                                      # Scheduled fault-script engine
                                      #   (ops/inflight.py): a static,
                                      #   validated tuple of timed
                                      #   events compiled into
                                      #   jit-static per-round masks.
                                      #   Event tuples (windows all
                                      #   END-EXCLUSIVE, like
                                      #   partition_spec):
                                      #   ("partition", start, end,
                                      #    frac) — cluster-aligned node
                                      #    split, cross-cut queries
                                      #    time out (partition_spec
                                      #    semantics);
                                      #   ("regional_outage", start,
                                      #    end, cluster) — cluster
                                      #    `cluster` unreachable: every
                                      #    query INTO or OUT OF it
                                      #    times out, intra-region and
                                      #    outside traffic unaffected
                                      #    (needs n_clusters > 1);
                                      #   ("latency_spike", start, end,
                                      #    extra_rounds) — queries
                                      #    ISSUED during the window
                                      #    take extra_rounds longer;
                                      #    latencies pushed to
                                      #    timeout_rounds() expire
                                      #    unanswered;
                                      #   ("churn_burst", round, frac)
                                      #    — at `round` each node
                                      #    toggles dead<->alive with
                                      #    probability frac (a one-shot
                                      #    churn_probability impulse).
                                      #   Same-kind events (same
                                      #   cluster for outages) must not
                                      #   overlap.  Any non-churn event
                                      #   turns the in-flight engine on
                                      #   (async_queries()); None / ()
                                      #   leaves every compiled program
                                      #   byte-identical (hlo_pin
                                      #   --verify-off-path).
    rtt_matrix: Optional[Tuple[Tuple[int, ...], ...]] = None
                                      # Cluster-pair RTT matrix for
                                      #   latency_mode "rtt": a static
                                      #   C x C tuple-of-tuples
                                      #   (C == n_clusters) of response
                                      #   latencies in ROUNDS — a draw
                                      #   from querier cluster i to
                                      #   responder cluster j takes
                                      #   rtt_matrix[i][j] rounds,
                                      #   composing topology-coupled
                                      #   latency with the clustered
                                      #   sampler (ops/sampling.py)
                                      #   without an O(N^2) plane.
                                      #   Entries >= timeout_rounds()
                                      #   never deliver (expire
                                      #   unanswered).  A uniform
                                      #   matrix of value L is
                                      #   trajectory-identical to
                                      #   latency_mode="fixed",
                                      #   latency_rounds=L (pinned by
                                      #   tests/test_faults.py).
    inflight_engine: str = "walk"     # async delivery engine
                                      #   (ops/inflight.py), active only
                                      #   when async_queries().  "walk":
                                      #   the reference pass — a
                                      #   fori_loop visiting every ring
                                      #   age each round (one gather +
                                      #   one k-vote ingest per age;
                                      #   compiled size O(1) in depth,
                                      #   runtime O(depth)).
                                      #   "walk_earlyout": the same walk
                                      #   with a per-age lax.cond that
                                      #   skips ages whose slot has no
                                      #   deliverable/expiring entry —
                                      #   the cheap win when latency <<
                                      #   timeout.  "coalesced": ONE
                                      #   ring drain — whole-ring
                                      #   deliverable mask, a single
                                      #   flattened gather over every
                                      #   candidate entry, and one
                                      #   fused present-masked ingest
                                      #   over the [rows, D*k] vote
                                      #   plane, with the ring's
                                      #   poll-mask planes bit-packed
                                      #   (per-shard byte padding, so
                                      #   the plane shards over txs at
                                      #   any per-shard width).
                                      #   Bit-exact all three ways —
                                      #   pinned by tests/test_inflight
                                      #   the way tests/test_exchange.py
                                      #   pins cfg.fused_exchange.
    metrics_every: int = 0            # in-graph metrics stride
                                      #   (go_avalanche_tpu/obs): every
                                      #   this-many rounds the dense
                                      #   round_step emits its
                                      #   SimTelemetry scalars to the
                                      #   active JSONL sink through ONE
                                      #   unordered `io_callback` under a
                                      #   round-mod `lax.cond` — no extra
                                      #   dispatches, no device->host
                                      #   sync in the fused loop.  0
                                      #   (default) = statically absent:
                                      #   the traced program is
                                      #   byte-identical to the pre-obs
                                      #   one (every existing hlo_pin
                                      #   hash unchanged; the on path is
                                      #   pinned as `flagship_metrics`).
                                      #   Sharded drivers ignore it —
                                      #   they stream stacked telemetry
                                      #   host-side instead
                                      #   (obs.MetricsSink.write_stacked)
    trace_every: int = 0              # on-device trace-plane stride
                                      #   (go_avalanche_tpu/obs/trace.py):
                                      #   every this-many rounds the
                                      #   round/scheduler step writes its
                                      #   flattened telemetry row into a
                                      #   [S, M] int32 buffer carried IN
                                      #   the sim state — one
                                      #   dynamic_update_slice under a
                                      #   round-mod lax.cond; no
                                      #   callback, no host sync, legal
                                      #   under shard_map (replicated
                                      #   plane) and under the fleet
                                      #   vmap ([F, S, M] per-trial
                                      #   traces).  0 (default) =
                                      #   statically absent: the state
                                      #   carries no buffer and every
                                      #   archived hlo pin is
                                      #   byte-identical (the on path is
                                      #   pinned as flagship_trace).
                                      #   Decode: obs.trace
                                      #   trace_records / write_trace
    stream_retire_cap: Optional[int] = None
                                      # streaming_dag scheduler: cap the
                                      #   set-slots retired+refilled per
                                      #   round and update only their
                                      #   window columns (scatter) instead
                                      #   of rewriting every [N, W] record
                                      #   plane; over-cap slots defer one
                                      #   round (they stay settled).  None
                                      #   = dense rewrite (exact legacy
                                      #   trajectory).  See PERF_NOTES.md.

    # --- live-traffic service mode (go_avalanche_tpu/traffic.py) ---
    arrival_mode: str = "off"         # streaming schedulers (backlog /
                                      #   streaming_dag) only: how fresh
                                      #   work ARRIVES instead of being
                                      #   fully pre-seeded.  "off" (the
                                      #   drain-a-fixed-backlog seed
                                      #   path; the traffic plane is
                                      #   statically absent and every
                                      #   archived hlo pin is
                                      #   byte-identical).  "poisson":
                                      #   Poisson(arrival_rate) new
                                      #   admission units (txs for
                                      #   backlog, conflict SETS for
                                      #   streaming_dag) per round.
                                      #   "bursty": Poisson whose rate is
                                      #   arrival_rate *
                                      #   arrival_burst_factor during the
                                      #   first arrival_duty fraction of
                                      #   every arrival_period-round
                                      #   cycle, arrival_rate otherwise.
                                      #   "diurnal": Poisson whose rate
                                      #   follows arrival_rate * (1 +
                                      #   arrival_depth *
                                      #   sin(2*pi*round/arrival_period))
                                      #   — the day/night load curve.
                                      #   "external": the schedule draws
                                      #   NOTHING; arrivals are pushed by
                                      #   an external load generator
                                      #   (`traffic.push_arrivals`, the
                                      #   Connector SIM_SUBMIT message).
                                      #   The schedule is jit-static; the
                                      #   per-round draw is realized from
                                      #   the sim's init key, so dense
                                      #   and sharded runs (and every
                                      #   fleet trial) see the same
                                      #   arrival sequence for the same
                                      #   key (tests/test_traffic.py).
    arrival_rate: float = 0.0         # mean admission units per round
                                      #   (the offered load); > 0 for
                                      #   every schedule except
                                      #   off/external
    arrival_period: int = 0           # bursty/diurnal cycle length in
                                      #   rounds (>= 2 there, unread
                                      #   elsewhere)
    arrival_burst_factor: float = 1.0  # bursty: peak rate multiplier
                                      #   (> 1) during the duty window
    arrival_duty: float = 0.5         # bursty: fraction of the period
                                      #   at the peak, in (0, 1)
    arrival_depth: float = 0.0        # diurnal: sinusoid modulation
                                      #   depth in [0, 1]
    arrival_backpressure: Optional[Tuple[float, float]] = None
                                      # closed-loop admission control:
                                      #   (lo, hi) working-set occupancy
                                      #   fractions.  Below lo the full
                                      #   scheduled rate is offered;
                                      #   above hi arrivals are fully
                                      #   throttled; linear in between —
                                      #   occupancy is the backpressure
                                      #   signal that turns the
                                      #   simulator into a
                                      #   capacity-planning tool
                                      #   (examples/capacity_planning.py)
    arrival_cluster_weights: Optional[Tuple[float, ...]] = None
                                      # per-cluster arrival skew (hot
                                      #   regions — the ROADMAP
                                      #   live-traffic follow-up): a [C]
                                      #   tuple of positive rate
                                      #   multipliers, C == n_clusters.
                                      #   Each admission unit's home
                                      #   region derives from its
                                      #   position in the admission
                                      #   order via the one cluster_of
                                      #   spelling (contiguous blocks
                                      #   over the backlog, exactly as
                                      #   nodes partition), and the
                                      #   in-graph arrival draw's rate
                                      #   is scaled by the stream
                                      #   head's region weight — a hot
                                      #   region's units arrive
                                      #   proportionally faster.
                                      #   Requires n_clusters > 1 (the
                                      #   region structure) and an
                                      #   in-graph schedule mode
                                      #   (external draws nothing);
                                      #   inert combinations are
                                      #   rejected.  None = statically
                                      #   absent (flagship_traffic pin
                                      #   unchanged)
    arrival_latency_buckets: int = 512
                                      # finality-latency histogram depth
                                      #   (rounds): per-tx arrival ->
                                      #   finalized latencies clamp into
                                      #   [0, buckets); the in-graph
                                      #   p50/p99/p999 percentiles are
                                      #   EXACT (nearest-rank) for
                                      #   latencies under the cap

    # --- stake subsystem (go_avalanche_tpu/stake.py) ---
    stake_mode: str = "off"           # per-node stake distribution.  "off"
                                      #   (default): every node is
                                      #   weightless — the exact pre-stake
                                      #   code path, every archived hlo
                                      #   pin byte-identical (machine-
                                      #   checked by hlo_pin.py
                                      #   --verify-off-path).  Any other
                                      #   mode realizes a jit-static
                                      #   per-node stake vector
                                      #   (stake.node_stake) that is
                                      #   FOLDED INTO the latency_weight
                                      #   sampling-propensity plane at
                                      #   init, so peer draws become
                                      #   stake-weighted committee draws
                                      #   ("Committee Selection is More
                                      #   Similar Than You Think",
                                      #   PAPERS.md arXiv 1904.09839):
                                      #   "uniform" — equal stake (the
                                      #   weighted machinery with a flat
                                      #   distribution); "zipf" — node i
                                      #   holds stake 1/(i+1)^s with
                                      #   s = stake_zipf_s (id 0
                                      #   richest; with
                                      #   byzantine_fraction > 0 the
                                      #   adversary holds the TOP stake
                                      #   — the worst case); "explicit"
                                      #   — the stake_weights vector.
                                      #   With n_clusters > 1 the draw
                                      #   runs through the two-level
                                      #   HIERARCHICAL sampler
                                      #   (ops/sampling.
                                      #   sample_peers_hierarchical):
                                      #   cluster from the [C]
                                      #   stake-mass boundaries, then
                                      #   peer within the cluster —
                                      #   bit-identical to the flat CDF
                                      #   (tests/test_stake.py), and
                                      #   SOURCE-INDEPENDENT:
                                      #   cluster_locality is a
                                      #   clustered-sampler knob the
                                      #   stake family never reads
                                      #   (committee draws are global).
    stake_zipf_s: float = 1.0         # zipf exponent (stake_mode "zipf"
                                      #   only; s > 0, larger = more
                                      #   concentrated).  Rejected at any
                                      #   non-default value under other
                                      #   modes — a silently ignored
                                      #   exponent would mislabel the run
    stake_weights: Optional[Tuple[float, ...]] = None
                                      # stake_mode "explicit": the
                                      #   per-node stake vector (positive
                                      #   finite numbers; length must
                                      #   match the node count at
                                      #   realization — and
                                      #   registry_nodes when the node
                                      #   registry is on, validated
                                      #   here).  Required there,
                                      #   rejected elsewhere
    registry_nodes: int = 0           # node-axis streaming scheduler
                                      #   (models/node_stream.py): the
                                      #   REGISTRY size R — the full node
                                      #   population, of which only
                                      #   active_nodes rows are resident
                                      #   in the dense [W, T] window at a
                                      #   time (the DAG-Sword
                                      #   active-working-set regime,
                                      #   PAPERS.md arXiv 2311.04638:
                                      #   nodes >> devices*VMEM as a
                                      #   supported regime instead of an
                                      #   OOM).  0 (default) = off; > 0
                                      #   requires active_nodes in
                                      #   (0, registry_nodes) and a
                                      #   stake_mode (the working set is
                                      #   drawn STAKE-proportionally —
                                      #   "uniform" gives uniform
                                      #   residency)
    active_nodes: int = 0             # node_stream working-set rows W
                                      #   (see registry_nodes); the dense
                                      #   window the consensus round
                                      #   runs on.  Both-or-neither with
                                      #   registry_nodes
    node_churn_rate: float = 0.0      # node_stream: P(an active row
                                      #   rotates out, per step).
                                      #   Departing rows' vote records
                                      #   retire; arriving rows are drawn
                                      #   stake-proportionally from the
                                      #   non-resident registry (exact
                                      #   weighted-without-replacement
                                      #   Gumbel top-k) and initialize
                                      #   from the registry prior.  In
                                      #   [0, 1]; > 0 requires the
                                      #   registry (inert otherwise)

    # --- fault / adversary model (SURVEY.md section 2.4 item 5) ---
    byzantine_fraction: float = 0.0   # nodes that vote adversarially
    flip_probability: float = 1.0     # P(byzantine node lies, per draw)
    adversary_strategy: AdversaryStrategy = AdversaryStrategy.FLIP
                                      # what the lie says (ops/adversary.py)
    adversary_policy: str = "off"     # adaptive adversary policy (see
                                      #   ADVERSARY_POLICIES): a
                                      #   jit-static attack kind that
                                      #   reads the CURRENT network
                                      #   state each round — per-round
                                      #   context planes built by
                                      #   ops/adversary.policy_ctx,
                                      #   composing with
                                      #   byzantine_fraction (who) and
                                      #   flip_probability (how often);
                                      #   the strategy supplies the lie
                                      #   content except under
                                      #   split_vote, which overrides
                                      #   it.  "off" = statically
                                      #   absent — every archived hlo
                                      #   pin byte-identical.  All
                                      #   adversary knobs are rejected
                                      #   as inert when
                                      #   byzantine_fraction == 0 (the
                                      #   _validate_stake /
                                      #   _validate_arrival precedent)
    adversary_margin: int = 1         # withhold_near_quorum only: a
                                      #   querier is "near quorum" when
                                      #   some live record's window
                                      #   yes- or no-count is within
                                      #   this many votes of the
                                      #   conclusive quorum (>= quorum
                                      #   - margin).  Rejected at any
                                      #   non-default value under other
                                      #   policies — a silently ignored
                                      #   margin would mislabel the run
    drop_probability: float = 0.0     # P(a sampled peer fails to respond
                                      #   => neutral vote, vote.go:56 semantics)
    churn_probability: float = 0.0    # P(a node toggles dead<->alive, per
                                      #   round) — dynamic membership
    skip_absent_votes: bool = False   # what a NON-response (dead peer,
                                      #   drop, self-draw) does to the
                                      #   vote window.  False: a delivered
                                      #   neutral — shifts the window with
                                      #   its consider bit off
                                      #   (vote.go:54-75), making finality
                                      #   degrade ~8*a^7 in availability a
                                      #   (RESULTS.md churn study).  True:
                                      #   registers nothing, like the
                                      #   reference HOST path where an
                                      #   expired/missing response never
                                      #   reaches RegisterVotes
                                      #   (processor.go:61-122,
                                      #   response.go expiry) — cost
                                      #   becomes linear dilution.
                                      #   SEQUENTIAL vote mode only.

    # ------------------------------------------------------- derived (async)

    def fault_events(self) -> Tuple[Tuple, ...]:
        """The canonical merged fault script: `partition_spec` (the
        one-event sugar spelling) first, then `fault_script` in given
        order.  Every consumer of the fault model reads THIS, so the
        two spellings can never diverge."""
        events = tuple(self.fault_script or ())
        if self.partition_spec is not None:
            events = (("partition",) + tuple(self.partition_spec),) + events
        return events

    def cut_events(self) -> Tuple[Tuple, ...]:
        """STATIC events that sever (querier, responder) pairs —
        partitions and regional outages; their draws get the
        never-delivers sentinel at issue time
        (`ops/inflight.partition_cut`)."""
        return tuple(e for e in self.fault_events()
                     if e[0] in ("partition", "regional_outage"))

    def spike_events(self) -> Tuple[Tuple, ...]:
        """STATIC latency_spike events — additive latency on queries
        ISSUED during the window (`ops/inflight.apply_latency_spikes`)."""
        return tuple(e for e in self.fault_events()
                     if e[0] == "latency_spike")

    def stochastic_cut_events(self) -> Tuple[Tuple, ...]:
        """stochastic_partition events — cut events whose realized
        (start, length, frac) is drawn per sim from the init key
        (`ops/inflight.draw_fault_params`); every range field here is a
        validated (lo, hi) tuple."""
        return tuple(e for e in self.fault_events()
                     if e[0] == "stochastic_partition")

    def stochastic_spike_events(self) -> Tuple[Tuple, ...]:
        """stochastic_spike events — latency spikes whose realized
        (start, length, extra_rounds) is drawn per sim from the init
        key."""
        return tuple(e for e in self.fault_events()
                     if e[0] == "stochastic_spike")

    def stochastic_region_events(self) -> Tuple[Tuple, ...]:
        """stochastic_regional_outage events — regional outages whose
        realized (start, length, cluster) is drawn per sim from the init
        key; every field here is a validated (lo, hi) range (the cluster
        range is integer, inside [0, n_clusters))."""
        return tuple(e for e in self.fault_events()
                     if e[0] == "stochastic_regional_outage")

    def stochastic_events(self) -> Tuple[Tuple, ...]:
        """All stochastic events, in script order — the list
        `ops/inflight.draw_fault_params` realizes (its PRNG stream folds
        the index into THIS ordering, so a sim's realized schedule is a
        pure function of (config, init key))."""
        return tuple(e for e in self.fault_events()
                     if e[0] in _STOCHASTIC_KINDS)

    def churn_burst_events(self) -> Tuple[Tuple, ...]:
        """churn_burst events — one-shot alive-toggle impulses applied by
        every model's churn stage (`ops/inflight.apply_churn_bursts`);
        the only event kind that does NOT need the in-flight engine."""
        return tuple(e for e in self.fault_events()
                     if e[0] == "churn_burst")

    def arrivals_enabled(self) -> bool:
        """True when the live-traffic arrival plane
        (`go_avalanche_tpu/traffic.py`) is on: the streaming schedulers
        carry a `TrafficState` (arrival key, arrived watermark, per-unit
        arrival-round plane, finality-latency histogram) and admission
        is gated on arrived work.  False = the drain-a-fixed-backlog
        seed path; the plane is statically absent and every archived
        hlo pin is untouched."""
        return self.arrival_mode != "off"

    def async_queries(self) -> bool:
        """True when the in-flight query engine (`ops/inflight.py`) is on:
        a latency distribution is selected or any cut/spike fault event
        is scheduled (partition_spec or fault_script; churn bursts alone
        need no ring).  Stochastic events always need the ring — their
        realized windows are unknown until the init key draws them.
        False = the synchronous ideal, the exact pre-async code path
        (flagship `hlo_pin` program unchanged)."""
        return (self.latency_mode != "none" or bool(self.cut_events())
                or bool(self.spike_events())
                or bool(self.stochastic_events()))

    def timeout_rounds(self) -> int:
        """First round-AGE at which an outstanding query is expired.

        Host parity: `RequestRecord.is_expired` is ``timestamp +
        timeout_s < now`` (types.py:119-125, strict), so a response
        arriving at age ``a`` is accepted iff ``a * time_step_s <=
        request_timeout_s``; the smallest non-deliverable age is
        ``floor(timeout/dt) + 1`` when the ratio is integral and
        ``ceil(timeout/dt)`` otherwise — both spelled here as one
        floor+1 (the epsilon absorbs float division noise like
        ``60/0.01 = 5999.999...``).  The in-flight ring buffer holds
        ages ``0 .. timeout_rounds()`` inclusive, so async configs must
        keep this small (validated <= 64): pick ``request_timeout_s``
        and ``time_step_s`` together, e.g. ``time_step_s=1.0,
        request_timeout_s=7.0`` for an 8-round timeout.
        """
        return int(math.floor(self.request_timeout_s / self.time_step_s
                              + 1e-9)) + 1

    def __post_init__(self) -> None:
        if not (0 < self.window <= 8):
            raise ValueError("window must be in (0, 8]: packed into uint8")
        if not (0 < self.quorum <= self.window):
            raise ValueError("quorum must be in (0, window]")
        if self.finalization_score <= 0 or self.finalization_score > 0x7FFF:
            raise ValueError("finalization_score must fit in 15 bits "
                             "(confidence counter is uint16 >> 1)")
        if self.k <= 0:
            raise ValueError("k must be positive")
        if self.weighted_sampling and not self.sample_with_replacement:
            raise ValueError(
                "weighted_sampling requires sample_with_replacement: exact "
                "weighted draws without replacement need per-row Gumbel "
                "top-k over all N peers (O(N^2) state)")
        if self.n_clusters < 1:
            raise ValueError("n_clusters must be >= 1 (1 = no clustering)")
        if self.skip_absent_votes and self.vote_mode is not VoteMode.SEQUENTIAL:
            raise ValueError(
                "skip_absent_votes applies to the SEQUENTIAL vote mode only "
                "(the QUORUM mode's alpha-threshold already consumes "
                "absence as its neutral outcome)")
        if self.n_clusters > 1 and not self.sample_with_replacement:
            raise ValueError(
                "clustered topology requires sample_with_replacement "
                "(same O(N^2) argument as weighted_sampling)")
        if not (0.0 <= self.cluster_locality <= 1.0):
            raise ValueError("cluster_locality must be in [0, 1]")
        if not (0.5 < self.alpha <= 1.0):
            raise ValueError("alpha must be in (0.5, 1.0]")
        if self.ingest_engine not in ("u8", "swar32"):
            raise ValueError(
                f"ingest_engine must be 'u8' or 'swar32', "
                f"got {self.ingest_engine!r}")
        if self.metrics_every < 0:
            raise ValueError("metrics_every must be >= 0 (0 disables the "
                             "in-graph metrics tap)")
        if self.trace_every < 0:
            raise ValueError("trace_every must be >= 0 (0 disables the "
                             "on-device trace plane)")
        if self.stream_retire_cap is not None and self.stream_retire_cap < 1:
            raise ValueError("stream_retire_cap must be >= 1 (None "
                             "disables the cap)")
        if self.inflight_engine not in ("walk", "walk_earlyout",
                                        "coalesced"):
            raise ValueError(
                f"inflight_engine must be 'walk', 'walk_earlyout' or "
                f"'coalesced', got {self.inflight_engine!r}")
        if self.latency_mode not in ("none", "fixed", "geometric",
                                     "weighted", "rtt"):
            raise ValueError(
                f"latency_mode must be 'none', 'fixed', 'geometric', "
                f"'weighted' or 'rtt', got {self.latency_mode!r}")
        if self.latency_rounds < 0:
            raise ValueError("latency_rounds must be >= 0")
        if self.partition_spec is not None:
            if len(self.partition_spec) != 3:
                raise ValueError("partition_spec is (round_start, "
                                 "round_end, split_frac)")
            object.__setattr__(self, "partition_spec",
                               tuple(self.partition_spec))
            start, end, frac = self.partition_spec
            if start == end:
                raise ValueError(
                    f"partition_spec window [{start}, {end}) is "
                    f"zero-length: windows are END-EXCLUSIVE, so a "
                    f"start == end cut never fires — rounds must "
                    f"satisfy 0 <= start < end")
            if not (0 <= start < end):
                raise ValueError("partition_spec rounds must satisfy "
                                 "0 <= start < end (end-exclusive "
                                 "window)")
            if not (0.0 < frac < 1.0):
                raise ValueError("partition_spec split_frac must be in "
                                 "(0, 1)")
        self._validate_fault_script()
        self._validate_rtt_matrix()
        self._validate_arrival()
        self._validate_stake()
        self._validate_adversary()
        self._validate_round_engine()
        if self.latency_mode == "rtt":
            if self.rtt_matrix is None:
                raise ValueError(
                    "latency_mode 'rtt' needs an rtt_matrix (a "
                    "C x C tuple of per-cluster-pair latencies in "
                    "rounds, C == n_clusters)")
        elif self.rtt_matrix is not None:
            raise ValueError(
                f"rtt_matrix is only read by latency_mode 'rtt', got "
                f"latency_mode {self.latency_mode!r} — a silently "
                f"ignored matrix would mislabel the run")
        if self.async_queries():
            if self.vote_mode is not VoteMode.SEQUENTIAL:
                raise ValueError(
                    "the async query engine applies to the SEQUENTIAL "
                    "vote mode only (MAJORITY reduces all k draws at "
                    "once, which has no per-draw delivery time)")
            if self.timeout_rounds() < 1:
                raise ValueError(
                    f"async queries need timeout_rounds() >= 1, got "
                    f"{self.timeout_rounds()} from request_timeout_s="
                    f"{self.request_timeout_s} / time_step_s="
                    f"{self.time_step_s}: a non-positive timeout makes "
                    f"EVERY query expire before any response can "
                    f"deliver, so a run-until-settled driver spins "
                    f"forever")
            if self.timeout_rounds() > 64:
                raise ValueError(
                    f"async queries need timeout_rounds() <= 64 (the "
                    f"in-flight ring depth), got "
                    f"{self.timeout_rounds()} from request_timeout_s="
                    f"{self.request_timeout_s} / time_step_s="
                    f"{self.time_step_s}; lower request_timeout_s or "
                    f"raise time_step_s (e.g. time_step_s=1.0, "
                    f"request_timeout_s=7.0 for an 8-round timeout)")

    def _validate_fault_script(self) -> None:
        """Reject malformed / out-of-range / overlapping fault events at
        CONSTRUCTION, never at trace time: run_sim mirrors these errors
        at its parser (the PR 5 `--metrics-every` lesson — a bad script
        must fail before the worker retry loop ever sees it)."""
        if self.fault_script is None:
            return

        def _canon(ev):
            # Deep-tuple: stochastic range fields arrive as JSON lists;
            # the whole script must stay hashable (jit-static config).
            ev = tuple(ev)
            if ev and ev[0] in _STOCHASTIC_KINDS:
                return (ev[0],) + tuple(
                    tuple(f) if isinstance(f, (list, tuple)) else f
                    for f in ev[1:])
            return ev

        script = tuple(_canon(e) for e in self.fault_script)
        object.__setattr__(self, "fault_script", script)
        for i, ev in enumerate(script):
            if not ev or ev[0] not in _FAULT_EVENT_FIELDS:
                raise ValueError(
                    f"fault_script[{i}]: unknown event kind "
                    f"{ev[0] if ev else ev!r}; known kinds: "
                    f"{', '.join(sorted(_FAULT_EVENT_FIELDS))}")
            kind = ev[0]
            fields = _FAULT_EVENT_FIELDS[kind]
            if len(ev) != 1 + len(fields):
                raise ValueError(
                    f"fault_script[{i}]: {kind} events are "
                    f"(kind, {', '.join(fields)}), got {len(ev)} fields")
            if kind == "churn_burst":
                _, round_, frac = ev
                if int(round_) != round_ or round_ < 0:
                    raise ValueError(
                        f"fault_script[{i}]: churn_burst round must be "
                        f"a non-negative integer, got {round_!r}")
                if not (0.0 < frac <= 1.0):
                    raise ValueError(
                        f"fault_script[{i}]: churn_burst frac must be "
                        f"in (0, 1], got {frac!r}")
                continue
            if kind in _STOCHASTIC_KINDS:
                self._validate_stochastic_event(i, ev)
                continue
            _, start, end, param = ev
            if int(start) != start or int(end) != end:
                raise ValueError(
                    f"fault_script[{i}]: {kind} start/end must be "
                    f"integer rounds, got ({start!r}, {end!r})")
            if start == end:
                raise ValueError(
                    f"fault_script[{i}]: {kind} window [{start}, {end}) "
                    f"is zero-length: windows are END-EXCLUSIVE, so a "
                    f"start == end event never fires — use "
                    f"0 <= start < end")
            if not (0 <= start < end):
                raise ValueError(
                    f"fault_script[{i}]: {kind} rounds must satisfy "
                    f"0 <= start < end (end-exclusive window), got "
                    f"[{start}, {end})")
            if kind == "partition" and not (0.0 < param < 1.0):
                raise ValueError(
                    f"fault_script[{i}]: partition split_frac must be "
                    f"in (0, 1), got {param!r}")
            if kind == "regional_outage":
                if self.n_clusters < 2:
                    raise ValueError(
                        f"fault_script[{i}]: regional_outage needs a "
                        f"clustered topology (n_clusters > 1), got "
                        f"n_clusters={self.n_clusters}")
                if int(param) != param or not (0 <= param
                                               < self.n_clusters):
                    raise ValueError(
                        f"fault_script[{i}]: regional_outage cluster "
                        f"must be an integer in [0, "
                        f"{self.n_clusters}), got {param!r}")
            if kind == "latency_spike" and (int(param) != param
                                            or param < 1):
                raise ValueError(
                    f"fault_script[{i}]: latency_spike extra_rounds "
                    f"must be an integer >= 1, got {param!r}")
        # Overlap: two same-kind events (same cluster for outages)
        # active in the same round are ambiguous — which frac?  double
        # the spike? — so the merged script (partition_spec sugar
        # included) rejects them; different clusters / different kinds
        # compose freely (cascading regional failures are the point).
        # Stochastic events are EXEMPT: their realized windows are
        # per-trial, and overlap is well-defined anyway (cut masks OR,
        # spike extras add).
        windows: dict = {}
        for ev in self.fault_events():
            kind = ev[0]
            if kind in _STOCHASTIC_KINDS:
                continue
            if kind == "churn_burst":
                key, span = (kind,), (ev[1], ev[1] + 1)
            elif kind == "regional_outage":
                key, span = (kind, ev[3]), (ev[1], ev[2])
            else:
                key, span = (kind,), (ev[1], ev[2])
            for other in windows.setdefault(key, []):
                if span[0] < other[1] and other[0] < span[1]:
                    raise ValueError(
                        f"fault_script: overlapping {kind} events"
                        f"{' for cluster ' + str(ev[3]) if kind == 'regional_outage' else ''}"
                        f" — [{other[0]}, {other[1]}) and [{span[0]}, "
                        f"{span[1]}) are both active in round "
                        f"{max(other[0], span[0])} (partition_spec "
                        f"counts as a partition event)")
            windows[key].append(span)

    def _validate_stochastic_event(self, i: int, ev: Tuple) -> None:
        """One stochastic event: every field a (lo, hi) range with
        lo <= hi — start/length/extra integer rounds, frac a float in
        (0, 1).  The realized draw is uniform over [lo, hi] (inclusive
        for the integer fields), so a degenerate lo == hi range pins
        that parameter while the others stay random."""
        kind = ev[0]
        fields = _FAULT_EVENT_FIELDS[kind]

        def _range(name, value, *, integer, lo_min):
            if (not isinstance(value, tuple) or len(value) != 2):
                raise ValueError(
                    f"fault_script[{i}]: {kind} {name} must be a "
                    f"[lo, hi] range, got {value!r}")
            lo, hi = value
            for v in (lo, hi):
                # bools, strings and nulls all reject with the indexed
                # message (int("a") would escape as a raw ValueError,
                # int(True) would validate as the range [1, 1],
                # None as a raw TypeError from the comparison — the
                # --rtt-matrix bug class the PR 6 review closed).
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"fault_script[{i}]: {kind} {name} bounds must "
                        f"be numbers, got {value!r}")
                if integer and int(v) != v:
                    raise ValueError(
                        f"fault_script[{i}]: {kind} {name} bounds must "
                        f"be integers, got {value!r}")
            if not (lo_min <= lo <= hi):
                raise ValueError(
                    f"fault_script[{i}]: {kind} {name} range must "
                    f"satisfy {lo_min} <= lo <= hi, got {value!r}")

        _range(fields[0], ev[1], integer=True, lo_min=0)       # start
        _range(fields[1], ev[2], integer=True, lo_min=1)       # length
        if kind == "stochastic_regional_outage":
            if self.n_clusters < 2:
                raise ValueError(
                    f"fault_script[{i}]: stochastic_regional_outage "
                    f"needs a clustered topology (n_clusters > 1), got "
                    f"n_clusters={self.n_clusters}")
            _range(fields[2], ev[3], integer=True, lo_min=0)   # cluster
            if ev[3][1] >= self.n_clusters:
                raise ValueError(
                    f"fault_script[{i}]: stochastic_regional_outage "
                    f"cluster range must stay inside [0, "
                    f"{self.n_clusters}), got {ev[3]!r}")
            return
        if kind == "stochastic_partition":
            # frac needs OPEN bounds on both sides, which _range's
            # lo_min<=lo<=hi shape doesn't spell — validated here with
            # the same non-numeric rejection (None/str/bool all take
            # the indexed message, never a raw TypeError).
            lo, hi = (ev[3] if isinstance(ev[3], tuple) and len(ev[3]) == 2
                      else (None, None))
            for v in (lo, hi):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    lo = None
                    break
            if lo is None or not (0.0 < lo <= hi < 1.0):
                raise ValueError(
                    f"fault_script[{i}]: stochastic_partition frac must "
                    f"be a [lo, hi] range inside (0, 1), got {ev[3]!r}")
        else:                                                  # spike
            _range(fields[2], ev[3], integer=True, lo_min=1)

    def _validate_arrival(self) -> None:
        """Live-traffic knobs (`go_avalanche_tpu/traffic.py`): reject
        inert or out-of-range arrival configs at CONSTRUCTION (the
        rtt_matrix rule — a silently ignored rate would mislabel the
        run); run_sim mirrors these at its parser."""
        modes = ("off", "poisson", "bursty", "diurnal", "external")
        if self.arrival_mode not in modes:
            raise ValueError(
                f"arrival_mode must be one of {', '.join(modes)}, got "
                f"{self.arrival_mode!r}")
        if self.arrival_mode == "off":
            if self.arrival_rate != 0.0:
                raise ValueError(
                    f"arrival_rate is only read when arrival_mode is on, "
                    f"got rate {self.arrival_rate!r} with mode 'off' — a "
                    f"silently ignored rate would mislabel the run")
            if self.arrival_backpressure is not None:
                raise ValueError(
                    "arrival_backpressure is only read when arrival_mode "
                    "is on (occupancy throttles the arrival draw); with "
                    "mode 'off' it would be silently ignored")
            if self.arrival_cluster_weights is not None:
                raise ValueError(
                    "arrival_cluster_weights is only read when "
                    "arrival_mode is on (it scales the in-graph arrival "
                    "draw per region); with mode 'off' it would be "
                    "silently ignored")
            return
        if self.arrival_mode == "external":
            if self.arrival_rate != 0.0:
                raise ValueError(
                    f"arrival_mode 'external' draws nothing in-graph "
                    f"(arrivals are pushed via traffic.push_arrivals / "
                    f"the Connector SIM_SUBMIT message); got "
                    f"arrival_rate {self.arrival_rate!r} — use a "
                    f"schedule mode for in-graph offered load")
            if self.arrival_backpressure is not None:
                raise ValueError(
                    "arrival_backpressure throttles the in-graph "
                    "arrival DRAW, which arrival_mode 'external' never "
                    "performs (pushed arrivals are admitted as-is) — "
                    "a silently inert backpressure band would mislabel "
                    "the run as closed-loop")
        elif not (self.arrival_rate > 0.0):
            raise ValueError(
                f"arrival_mode {self.arrival_mode!r} needs "
                f"arrival_rate > 0 (mean admission units per round), "
                f"got {self.arrival_rate!r}")
        if self.arrival_mode in ("bursty", "diurnal"):
            if self.arrival_period < 2:
                raise ValueError(
                    f"arrival_mode {self.arrival_mode!r} needs "
                    f"arrival_period >= 2 rounds (the modulation cycle), "
                    f"got {self.arrival_period}")
        if self.arrival_mode == "bursty":
            if not (self.arrival_burst_factor > 1.0):
                raise ValueError(
                    f"bursty arrivals need arrival_burst_factor > 1 "
                    f"(otherwise the schedule is plain poisson), got "
                    f"{self.arrival_burst_factor!r}")
            if not (0.0 < self.arrival_duty < 1.0):
                raise ValueError(
                    f"arrival_duty must be in (0, 1) (the burst fraction "
                    f"of each cycle), got {self.arrival_duty!r}")
        if self.arrival_mode == "diurnal" and not (
                0.0 <= self.arrival_depth <= 1.0):
            raise ValueError(
                f"arrival_depth must be in [0, 1] (sinusoid modulation "
                f"depth), got {self.arrival_depth!r}")
        if self.arrival_backpressure is not None:
            bp = tuple(self.arrival_backpressure)
            object.__setattr__(self, "arrival_backpressure", bp)
            if len(bp) != 2:
                raise ValueError(
                    f"arrival_backpressure is (lo, hi) occupancy "
                    f"fractions, got {bp!r}")
            lo, hi = bp
            for v in bp:
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"arrival_backpressure bounds must be numbers, "
                        f"got {bp!r}")
            if not (0.0 <= lo < hi <= 1.0):
                raise ValueError(
                    f"arrival_backpressure needs 0 <= lo < hi <= 1 "
                    f"(full rate below lo, fully throttled above hi), "
                    f"got {bp!r}")
        if self.arrival_cluster_weights is not None:
            if self.arrival_mode == "external":
                raise ValueError(
                    "arrival_cluster_weights scales the in-graph arrival "
                    "DRAW, which arrival_mode 'external' never performs "
                    "(pushed arrivals are admitted as-is) — a silently "
                    "inert skew would mislabel the run as hot-region "
                    "traffic")
            if self.n_clusters < 2:
                raise ValueError(
                    "arrival_cluster_weights needs a clustered topology "
                    "(n_clusters > 1): the per-region admission blocks "
                    "derive from the same cluster_of partition as the "
                    "node clusters — with one cluster the skew is inert")
            wts = tuple(self.arrival_cluster_weights)
            object.__setattr__(self, "arrival_cluster_weights", wts)
            if len(wts) != self.n_clusters:
                raise ValueError(
                    f"arrival_cluster_weights is one rate multiplier per "
                    f"cluster (n_clusters = {self.n_clusters}), got "
                    f"{len(wts)} entries")
            for i, w in enumerate(wts):
                if isinstance(w, bool) or not isinstance(w, (int, float)) \
                        or not (w > 0.0) or not math.isfinite(w):
                    raise ValueError(
                        f"arrival_cluster_weights[{i}] must be a "
                        f"positive finite rate multiplier, got {w!r}")
        if self.arrival_latency_buckets < 2:
            raise ValueError(
                f"arrival_latency_buckets must be >= 2 (latencies clamp "
                f"into [0, buckets)), got {self.arrival_latency_buckets}")

    def _validate_stake(self) -> None:
        """Stake / node-registry knobs (`go_avalanche_tpu/stake.py`,
        `models/node_stream.py`): reject inert or out-of-range configs
        at CONSTRUCTION (the rtt_matrix rule); run_sim mirrors these at
        its parser."""
        modes = ("off", "uniform", "zipf", "explicit")
        if self.stake_mode not in modes:
            raise ValueError(
                f"stake_mode must be one of {', '.join(modes)}, got "
                f"{self.stake_mode!r}")
        if self.stake_mode == "zipf":
            if not (isinstance(self.stake_zipf_s, (int, float))
                    and not isinstance(self.stake_zipf_s, bool)
                    and self.stake_zipf_s > 0.0
                    and math.isfinite(self.stake_zipf_s)):
                raise ValueError(
                    f"stake_zipf_s must be a positive finite zipf "
                    f"exponent, got {self.stake_zipf_s!r}")
        elif self.stake_zipf_s != 1.0:
            raise ValueError(
                f"stake_zipf_s is only read by stake_mode 'zipf', got "
                f"exponent {self.stake_zipf_s!r} with mode "
                f"{self.stake_mode!r} — a silently ignored exponent "
                f"would mislabel the run")
        if self.stake_mode == "explicit":
            if self.stake_weights is None:
                raise ValueError(
                    "stake_mode 'explicit' needs a stake_weights vector "
                    "(one positive stake per node)")
            wts = tuple(self.stake_weights)
            object.__setattr__(self, "stake_weights", wts)
            if not wts:
                raise ValueError("stake_weights must be non-empty")
            for i, w in enumerate(wts):
                if isinstance(w, bool) or not isinstance(w, (int, float)) \
                        or not (w > 0.0) or not math.isfinite(w):
                    raise ValueError(
                        f"stake_weights[{i}] must be a positive finite "
                        f"stake, got {w!r}")
        elif self.stake_weights is not None:
            raise ValueError(
                f"stake_weights is only read by stake_mode 'explicit', "
                f"got a vector with mode {self.stake_mode!r} — a "
                f"silently ignored vector would mislabel the run")
        if self.stake_mode != "off":
            if not self.sample_with_replacement:
                raise ValueError(
                    "stake-weighted sampling requires "
                    "sample_with_replacement (same O(N^2) Gumbel-top-k "
                    "argument as weighted_sampling)")
            if self.latency_mode == "weighted":
                raise ValueError(
                    "stake_mode folds the stake vector into the "
                    "latency_weight sampling-propensity plane at init; "
                    "latency_mode 'weighted' reads that same plane to "
                    "derive response latency, which would silently "
                    "couple delay to stake — use fixed/geometric/rtt "
                    "latency with stake")
        # --- node registry (models/node_stream.py) ---
        if (self.registry_nodes > 0) != (self.active_nodes > 0):
            raise ValueError(
                f"registry_nodes and active_nodes come together (the "
                f"node-stream scheduler streams active_nodes resident "
                f"rows out of a registry_nodes population), got "
                f"registry_nodes={self.registry_nodes}, "
                f"active_nodes={self.active_nodes}")
        if self.registry_nodes < 0 or self.active_nodes < 0:
            raise ValueError("registry_nodes/active_nodes must be >= 0 "
                             "(0 disables the node registry)")
        if self.registry_nodes > 0:
            if self.stake_mode == "off":
                raise ValueError(
                    "the node registry draws its working set "
                    "STAKE-proportionally — registry_nodes > 0 needs a "
                    "stake_mode ('uniform' for uniform residency)")
            if not (self.active_nodes < self.registry_nodes):
                raise ValueError(
                    f"active_nodes ({self.active_nodes}) must be "
                    f"smaller than registry_nodes "
                    f"({self.registry_nodes}): churn rotates the window "
                    f"through a non-resident pool, which an "
                    f"active == registry config leaves empty")
            if (self.stake_mode == "explicit"
                    and len(self.stake_weights) != self.registry_nodes):
                raise ValueError(
                    f"with the node registry on, stake_weights is the "
                    f"REGISTRY's stake vector: expected "
                    f"{self.registry_nodes} entries, got "
                    f"{len(self.stake_weights)}")
        if not (0.0 <= self.node_churn_rate <= 1.0):
            raise ValueError(
                f"node_churn_rate must be in [0, 1], got "
                f"{self.node_churn_rate!r}")
        if self.node_churn_rate > 0.0 and self.registry_nodes == 0:
            raise ValueError(
                "node_churn_rate is only read by the node-stream "
                "scheduler (registry_nodes > 0) — without the registry "
                "the knob is inert and would mislabel the run")

    def _validate_adversary(self) -> None:
        """Fault / adversary knobs: reject inert or out-of-range configs
        at CONSTRUCTION (the `_validate_stake`/`_validate_arrival`
        inert-knob precedent — a silently ignored adversary knob would
        mislabel the run as attacked); run_sim mirrors these at its
        parser.

        NOTE byzantine_fraction == 0 rejects the OTHER adversary knobs
        at non-default values.  The byzantine mask itself is sim STATE
        (it enters at `init` only), so a run config paired with a
        byzantine state must keep its fraction non-zero — the
        compile-sharing idiom in examples/equivocation_threshold.py
        pins it at a shared non-zero constant for exactly this reason.
        """
        if not (0.0 <= self.byzantine_fraction <= 1.0):
            raise ValueError(
                f"byzantine_fraction must be in [0, 1], got "
                f"{self.byzantine_fraction!r}")
        if not (0.0 <= self.flip_probability <= 1.0):
            raise ValueError(
                f"flip_probability must be in [0, 1], got "
                f"{self.flip_probability!r}")
        if self.adversary_policy not in ADVERSARY_POLICIES:
            raise ValueError(
                f"adversary_policy must be one of "
                f"{', '.join(ADVERSARY_POLICIES)}, got "
                f"{self.adversary_policy!r}")
        if (isinstance(self.adversary_margin, bool)
                or not isinstance(self.adversary_margin, int)
                or self.adversary_margin < 0):
            raise ValueError(
                f"adversary_margin must be a non-negative integer "
                f"(window votes short of the quorum), got "
                f"{self.adversary_margin!r}")
        if self.byzantine_fraction == 0.0:
            inert = []
            if self.adversary_strategy is not AdversaryStrategy.FLIP:
                inert.append(
                    f"adversary_strategy={self.adversary_strategy.value}")
            if self.flip_probability != 1.0:
                inert.append(f"flip_probability={self.flip_probability!r}")
            if self.adversary_policy != "off":
                inert.append(f"adversary_policy={self.adversary_policy}")
            if self.adversary_margin != 1:
                inert.append(f"adversary_margin={self.adversary_margin}")
            if inert:
                raise ValueError(
                    f"{', '.join(inert)} set while byzantine_fraction "
                    f"== 0: with no byzantine nodes every adversary "
                    f"knob is inert and would mislabel the run as "
                    f"attacked — set byzantine_fraction > 0 (the "
                    f"byzantine mask is drawn at init from it)")
            return
        if (self.adversary_margin != 1
                and self.adversary_policy != "withhold_near_quorum"):
            raise ValueError(
                f"adversary_margin is only read by adversary_policy "
                f"'withhold_near_quorum', got margin "
                f"{self.adversary_margin} with policy "
                f"{self.adversary_policy!r} — a silently ignored margin "
                f"would mislabel the run")
        if (self.adversary_policy == "split_vote"
                and self.adversary_strategy is not AdversaryStrategy.FLIP):
            raise ValueError(
                f"adversary_policy 'split_vote' OVERRIDES the lie "
                f"content (lies vote the honest-minority color), so "
                f"adversary_strategy {self.adversary_strategy.value!r} "
                f"would be silently ignored and mislabel the run — "
                f"leave the strategy at its default under split_vote")
        if self.adversary_policy == "timing" and not self.async_queries():
            raise ValueError(
                "adversary_policy 'timing' delays lying responses "
                "through the in-flight latency plane (ops/inflight.py), "
                "which the synchronous ideal never builds — select a "
                "latency_mode (or schedule a cut/spike fault) to turn "
                "the async engine on")
        if (self.adversary_policy == "stake_eclipse"
                and self.stake_mode == "off"):
            raise ValueError(
                "adversary_policy 'stake_eclipse' concentrates lies on "
                "the top-STAKE queriers; with stake_mode 'off' every "
                "node is weightless and the eclipse set is arbitrary — "
                "select a stake_mode ('zipf' puts the adversary on top "
                "stake, the worst case)")

    def _validate_round_engine(self) -> None:
        """The whole-round megakernel covers the SYNC SEQUENTIAL round
        only (one Pallas program: gather -> SWAR ingest -> closed-form
        confidence, ops/megakernel.py).  Every knob whose machinery
        lives between the phases the kernel fuses away is rejected as
        inert at CONSTRUCTION (the `_validate_adversary` inert-knob
        precedent — a silently ignored engine knob would mislabel the
        A/B lane); run_sim and bench mirror these at their parsers.
        """
        if self.round_engine not in ("phased", "megakernel"):
            raise ValueError(
                f"round_engine must be 'phased' or 'megakernel', "
                f"got {self.round_engine!r}")
        if self.round_engine == "phased":
            return
        if self.vote_mode is not VoteMode.SEQUENTIAL:
            raise ValueError(
                "round_engine 'megakernel' fuses the SEQUENTIAL "
                "window-ingest round (the SWAR kernel body); the "
                "MAJORITY reduction has no windowed ingest to fuse")
        if self.async_queries():
            raise ValueError(
                "round_engine 'megakernel' covers the synchronous "
                "round only: the in-flight ring (latency_mode / "
                "partition_spec / fault_script events) delivers votes "
                "ACROSS rounds, outside the one fused program — run "
                "the async lanes on round_engine 'phased'")
        if self.inflight_engine != "walk":
            raise ValueError(
                f"inflight_engine {self.inflight_engine!r} set with "
                f"round_engine 'megakernel': the kernel covers the "
                f"sync round, so the delivery-engine knob is inert "
                f"and would mislabel the A/B lane — leave it at "
                f"'walk' (the default)")
        if self.skip_absent_votes:
            raise ValueError(
                "round_engine 'megakernel' does not implement the "
                "skip_absent_votes lane gating (same scoping as the "
                "SWAR Pallas ingest it embeds) — use round_engine "
                "'phased'")
        if (self.byzantine_fraction > 0.0 and self.adversary_strategy
                is AdversaryStrategy.EQUIVOCATE):
            raise ValueError(
                "round_engine 'megakernel' cannot reproduce the "
                "EQUIVOCATE strategy's per-draw host-keyed coin "
                "stream inside the kernel without materialising the "
                "[N, k, T] lie planes it exists to remove — run "
                "equivocation studies on round_engine 'phased'")
        if self.adversary_policy != "off":
            raise ValueError(
                f"adversary_policy {self.adversary_policy!r} set with "
                f"round_engine 'megakernel': the adaptive-adversary "
                f"context transforms run between the phases the "
                f"kernel fuses — run policy studies on round_engine "
                f"'phased'")

    def _validate_rtt_matrix(self) -> None:
        """The cluster-pair RTT matrix must be square, match the
        clustered topology, and carry non-negative integer rounds."""
        if self.rtt_matrix is None:
            return
        matrix = tuple(tuple(row) for row in self.rtt_matrix)
        object.__setattr__(self, "rtt_matrix", matrix)
        c = self.n_clusters
        if len(matrix) != c or any(len(row) != c for row in matrix):
            raise ValueError(
                f"rtt_matrix must be n_clusters x n_clusters = "
                f"{c} x {c} (one row per querier cluster), got "
                f"{len(matrix)} row(s) of lengths "
                f"{[len(r) for r in matrix]}")
        for i, row in enumerate(matrix):
            for j, entry in enumerate(row):
                if int(entry) != entry or entry < 0:
                    raise ValueError(
                        f"rtt_matrix[{i}][{j}] must be a non-negative "
                        f"integer latency in rounds, got {entry!r}")


def suppress_taps(cfg: AvalancheConfig) -> AvalancheConfig:
    """The inner-round config a streaming scheduler passes to its
    wrapped consensus round: BOTH telemetry taps zeroed (the io_callback
    metrics tap and the on-device trace plane), so the scheduler emits /
    writes exactly one record per round itself.  THE one spelling,
    shared by the backlog / streaming_dag / node_stream schedulers and
    their sharded twins — a drifted copy would double-emit rounds.
    Returns `cfg` unchanged (same object — jit caches unaffected) when
    no tap is on."""
    if cfg.metrics_every == 0 and cfg.trace_every == 0:
        return cfg
    return dataclasses.replace(cfg, metrics_every=0, trace_every=0)


DEFAULT_CONFIG = AvalancheConfig()
