"""Sweepable simulation runner CLI.

SURVEY.md §5 (config system): the reference exposes exactly one flag
(`-logging`, `main.go:24`) over four compile-time constants; here every
protocol constant and fault knob of `AvalancheConfig` is a CLI flag, any
model family can be selected, and results are emitted as JSON for sweep
harnesses.

    python -m go_avalanche_tpu.run_sim --model avalanche --nodes 1024 \
        --txs 256 --byzantine 0.1 --json
    python -m go_avalanche_tpu.run_sim --model dag --txs 64 --conflict-size 4
    python -m go_avalanche_tpu.run_sim --model snowball --nodes 4096 \
        --trace /tmp/xprof

Models: `slush` / `snowflake` — the paper's simpler family members
(models/family); `snowball` — [nodes] single-decree with the reference's
windowed record; `avalanche` — [nodes, txs] multi-target with gossip;
`dag` — conflict-set double-spend resolution; `backlog` — `--txs` pending
txs streamed through a `--slots` working-set window in bounded HBM (the
north-star 1M-tx path); `streaming_dag` — the composition: `--txs` pending
txs in `--conflict-size` conflict sets streamed through a `--slots`-set
window (the north-star 1M-tx UTXO-conflict path).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from go_avalanche_tpu.config import (
    ADVERSARY_POLICIES,
    AdversaryStrategy,
    AvalancheConfig,
    VoteMode,
    fault_script_from_json,
)
from go_avalanche_tpu.utils import metrics, tracing


def _parse_rtt_matrix(spec: str):
    """`--rtt-matrix` SPEC -> tuple-of-tuples: inline ``'1,3;3,1'`` rows
    or a path to a JSON file holding a list of lists.  Structural errors
    raise `ValueError` (funnelled into `parser.error`); squareness /
    topology-match / entry-range checks live in `AvalancheConfig`."""
    import os

    if os.path.exists(spec) or spec.endswith(".json"):
        with open(spec) as fh:
            data = json.load(fh)
        if (not isinstance(data, list)
                or not all(isinstance(r, list) for r in data)):
            raise ValueError(
                f"{spec} must hold a JSON list of lists (one row per "
                f"querier cluster)")
        if not all(isinstance(x, (int, float))
                   for row in data for x in row):
            raise ValueError(
                f"{spec}: matrix entries must be numbers (latencies "
                f"in rounds)")
        return tuple(map(tuple, data))
    try:
        return tuple(tuple(int(x) for x in row.split(","))
                     for row in spec.split(";"))
    except ValueError:
        raise ValueError(
            f"inline matrix rows are ';'-separated integer lists "
            f"(e.g. '1,3;3,1'), got {spec!r}")


def _async_on(args: argparse.Namespace) -> bool:
    """Will this flag set turn the in-flight engine on?  THE one
    parser-level spelling of `cfg.async_queries()`'s derivation —
    shared by `build_config` (the timing-knob mapping) and the
    `--phase-grid` adversary check (the timing-policy mirror), so the
    two can never desynchronize."""
    script = getattr(args, "fault_script_events", None)
    return (args.latency_mode != "none" or args.partition is not None
            or any(e and e[0] != "churn_burst" for e in script or ()))


def build_config(args: argparse.Namespace) -> AvalancheConfig:
    # Async axes: --timeout-rounds R maps to (time_step_s=1.0,
    # request_timeout_s=R-1), which makes cfg.timeout_rounds() == R
    # exactly; the seconds-based fields stay at reference defaults when
    # the async engine is off so the synchronous configs are unchanged.
    script = getattr(args, "fault_script_events", None)
    async_on = _async_on(args)
    timing = {}
    if async_on:
        if args.timeout_rounds < 1:
            raise SystemExit("--timeout-rounds must be >= 1 (a query "
                             "needs at least one round to be answerable)")
        timing = dict(time_step_s=1.0,
                      request_timeout_s=float(args.timeout_rounds - 1))
    partition = None
    if args.partition is not None:
        try:
            start_s, end_s, frac_s = args.partition.split(",")
            partition = (int(start_s), int(end_s), float(frac_s))
        except ValueError:
            raise SystemExit(f"--partition must be START,END,FRAC "
                             f"(e.g. 50,150,0.5), got {args.partition!r}")
    return AvalancheConfig(
        finalization_score=args.finalization_score,
        max_element_poll=args.max_element_poll,
        arrival_mode=getattr(args, "arrival_mode", "off"),
        arrival_rate=getattr(args, "arrival_rate", 0.0),
        arrival_period=getattr(args, "arrival_period", 0),
        arrival_burst_factor=getattr(args, "arrival_burst_factor", 1.0),
        arrival_duty=getattr(args, "arrival_duty", 0.5),
        arrival_depth=getattr(args, "arrival_depth", 0.0),
        arrival_backpressure=getattr(args, "arrival_backpressure_parsed",
                                     None),
        latency_mode=args.latency_mode,
        latency_rounds=args.latency_rounds,
        partition_spec=partition,
        fault_script=script,
        rtt_matrix=getattr(args, "rtt_matrix_parsed", None),
        **timing,
        window=args.window,
        quorum=args.quorum,
        k=args.k,
        alpha=args.alpha,
        vote_mode=VoteMode(args.vote_mode),
        gossip=not args.no_gossip,
        weighted_sampling=args.weighted,
        sample_with_replacement=not args.distinct_peers,
        n_clusters=args.clusters,
        cluster_locality=args.cluster_locality,
        byzantine_fraction=args.byzantine,
        flip_probability=args.flip_probability,
        adversary_strategy=AdversaryStrategy(args.adversary),
        adversary_policy=getattr(args, "adversary_policy", "off"),
        adversary_margin=getattr(args, "adversary_margin", 1),
        drop_probability=args.drop,
        churn_probability=args.churn,
        skip_absent_votes=args.skip_absent_votes,
        stream_retire_cap=getattr(args, "stream_retire_cap", None),
        stake_mode=getattr(args, "stake_mode", "off"),
        stake_zipf_s=getattr(args, "stake_zipf_s", 1.0),
        stake_weights=getattr(args, "stake_weights_parsed", None),
        registry_nodes=getattr(args, "registry_nodes", 0),
        active_nodes=getattr(args, "active_nodes", 0),
        node_churn_rate=getattr(args, "node_churn_rate", 0.0),
        arrival_cluster_weights=getattr(
            args, "arrival_cluster_weights_parsed", None),
        ingest_engine=getattr(args, "ingest_engine", "u8"),
        round_engine=getattr(args, "round_engine", "phased"),
        inflight_engine=getattr(args, "inflight_engine", "walk"),
        metrics_every=(getattr(args, "metrics_every", 0)
                       if getattr(args, "metrics", None) else 0),
        trace_every=getattr(args, "trace_every", 0),
    )


def _watchdog_run(state, cfg: AvalancheConfig, max_rounds: int,
                  round_step, settled) -> tuple:
    """`--check-invariants` driver: jitted single-round stepping with the
    host-side invariant watchdog (`obs/watchdog.py`) between rounds.

    Trades the fused while-loop for one dispatch + one device_get per
    round — the debug mode whose whole point is observing every
    intermediate state.  Returns ``(final_state, checks_run)``; raises
    `obs.InvariantViolation` (with offender indices) on the first
    violated invariant.
    """
    from go_avalanche_tpu import obs

    step = jax.jit(lambda s: round_step(s, cfg)[0])
    settled_fn = jax.jit(lambda s: settled(s, cfg))
    wd = obs.Watchdog(cfg)
    wd.check(state)
    for _ in range(max_rounds):
        if bool(jax.device_get(settled_fn(state))):
            break
        state = step(state)
        wd.check(state)
    return state, wd.checks


def run_snowball(args, cfg: AvalancheConfig) -> Dict:
    from go_avalanche_tpu.models import snowball as sb
    from go_avalanche_tpu.ops import voterecord as vr

    state = sb.init(jax.random.key(args.seed), args.nodes, cfg,
                    yes_fraction=args.yes_fraction)
    state = sb.with_trace(state, cfg, args.max_rounds)
    out = {}
    if args.check_invariants:
        def settled(s, cfg):
            return jnp.logical_not((jnp.logical_not(vr.has_finalized(
                s.records.confidence, cfg)) & s.alive).any())

        state, out["invariant_checks"] = _watchdog_run(
            state, cfg, args.max_rounds, sb.round_step, settled)
    else:
        state = jax.jit(sb.run, static_argnames=("cfg", "max_rounds"))(
            state, cfg, args.max_rounds)
    out.update(_emit_trace(args, cfg, state.trace))
    fin = np.asarray(jax.device_get(
        vr.has_finalized(state.records.confidence, cfg)))
    pref = np.asarray(jax.device_get(
        vr.is_accepted(state.records.confidence)))
    return {
        "rounds": int(jax.device_get(state.round)),
        "finalized_fraction": float(fin.mean()),
        "yes_fraction": float(pref[fin].mean()) if fin.any() else None,
        **out,
    }


def _parse_mesh(mesh_arg: str):
    """`--mesh N,T` -> a (nodes, txs) device mesh over available devices."""
    from go_avalanche_tpu.parallel.mesh import make_mesh

    n_shards, t_shards = (int(x) for x in mesh_arg.split(","))
    return make_mesh(n_node_shards=n_shards, n_tx_shards=t_shards)


def _maybe_restore(path, state):
    """Resume `--chunk` runs: restore from `--checkpoint` if it exists."""
    import os

    if path and os.path.exists(path):
        from go_avalanche_tpu.utils.checkpoint import restore_checkpoint
        return restore_checkpoint(path, state)
    return state


def _emit_trace(args, cfg: AvalancheConfig, buf, fleet: bool = False
                ) -> Dict:
    """Decode a finished run's trace plane (obs/trace.py) and stream it
    to its sink: `--trace-out` when given (its own file + manifest),
    else the active `--metrics` sink.  Fleet buffers decode to the
    fleet-stacked record format (per-trial lists).  Returns the result
    keys to merge ({} when the run carried no trace)."""
    if buf is None:
        return {}
    from go_avalanche_tpu import obs
    from go_avalanche_tpu.obs import trace as obs_trace
    from go_avalanche_tpu.obs.sink import active_sink

    def _write(sink) -> int:
        if fleet:
            wrote = 0
            for rec in obs_trace.fleet_trace_records(buf):
                sink.write(rec)
                wrote += 1
            return wrote
        return obs_trace.write_trace(sink, buf)

    if args.trace_out:
        with obs.metrics_sink(args.trace_out,
                              tag=obs.tag_from_config(cfg)) as sink:
            wrote = _write(sink)
        obs.write_manifest(args.trace_out, cfg, extra={
            "model": args.model,
            "workload": {"nodes": args.nodes, "txs": args.txs,
                         "max_rounds": args.max_rounds,
                         "seed": args.seed},
        })
        return {"trace_records": wrote, "trace_file": args.trace_out}
    sink = active_sink()
    if sink is None:
        return {}
    return {"trace_records": _write(sink)}


def run_avalanche(args, cfg: AvalancheConfig) -> Dict:
    from go_avalanche_tpu.models import avalanche as av
    from go_avalanche_tpu.ops import voterecord as vr

    init_pref = (av.contested_init_pref(args.seed, args.nodes, args.txs)
                 if args.contested else None)
    state = av.init(jax.random.key(args.seed), args.nodes, args.txs, cfg,
                    init_pref=init_pref)
    state = av.with_trace(state, cfg, args.max_rounds)
    extra = {}
    if args.mesh:
        from go_avalanche_tpu.parallel import sharded

        mesh = _parse_mesh(args.mesh)
        state = sharded.shard_state(state, mesh)
        state = sharded.run_sharded(mesh, state, cfg,
                                    max_rounds=args.max_rounds,
                                    donate=args.donate)
    elif args.check_invariants:
        state, extra["invariant_checks"] = _watchdog_run(
            state, cfg, args.max_rounds, av.round_step, av.all_settled)
    else:
        # av.run jits itself (static cfg/max_rounds); donate frees the
        # double-buffered [N, T] planes — the init state is not reused.
        state = av.run(state, cfg, args.max_rounds, donate=True)
    extra.update(_emit_trace(args, cfg, state.trace))
    fin = np.asarray(jax.device_get(
        vr.has_finalized(state.records.confidence, cfg)))
    out = {
        "rounds": int(jax.device_get(state.round)),
        "finalized_fraction": float(fin.mean()),
        "nodes_fully_finalized": int(fin.all(axis=1).sum()),
        **extra,
    }
    out.update({f"finality_{k}": v for k, v in
                metrics.rounds_to_finality(state.finalized_at).items()})
    return out


def run_dag(args, cfg: AvalancheConfig) -> Dict:
    from go_avalanche_tpu.models import dag

    conflict_set = jnp.arange(args.txs, dtype=jnp.int32) // args.conflict_size
    state = dag.init(jax.random.key(args.seed), args.nodes, conflict_set, cfg)
    state = dag.with_trace(state, cfg, args.max_rounds)
    extra = {}
    if args.mesh:
        from go_avalanche_tpu.parallel import sharded_dag

        mesh = _parse_mesh(args.mesh)
        state = sharded_dag.shard_dag_state(state, mesh)
        state = sharded_dag.run_sharded_dag(mesh, state, cfg,
                                            max_rounds=args.max_rounds,
                                            donate=args.donate)
    elif args.check_invariants:
        state, extra["invariant_checks"] = _watchdog_run(
            state, cfg, args.max_rounds, dag.round_step, dag.settled)
    else:
        state = jax.jit(dag.run, static_argnames=("cfg", "max_rounds"))(
            state, cfg, args.max_rounds)
    from go_avalanche_tpu.ops import voterecord as vr

    extra.update(_emit_trace(args, cfg, state.base.trace))
    conf = state.base.records.confidence
    fin_acc = np.asarray(jax.device_get(
        vr.has_finalized(conf, cfg) & vr.is_accepted(conf)))
    cs = np.asarray(jax.device_get(conflict_set))
    n_sets = int(cs.max()) + 1
    # Every (node, set) must have exactly one finalized-accepted winner.
    winners_per_set = np.zeros((args.nodes, n_sets), np.int64)
    for s in range(n_sets):
        winners_per_set[:, s] = fin_acc[:, cs == s].sum(axis=1)
    return {
        "rounds": int(jax.device_get(state.base.round)),
        "sets_resolved_fraction": float((winners_per_set == 1).mean()),
        "conflict_sets": n_sets,
        **extra,
    }


def run_slush(args, cfg: AvalancheConfig) -> Dict:
    from go_avalanche_tpu.models import family as fam

    state = fam.slush_init(jax.random.key(args.seed), args.nodes, cfg,
                           yes_fraction=args.yes_fraction)
    final, tel = jax.jit(fam.slush_run,
                         static_argnames=("cfg", "m_rounds"))(
        state, cfg, args.max_rounds)
    colors = np.asarray(jax.device_get(final.color))
    return {
        "rounds": int(jax.device_get(final.round)),
        "yes_fraction_final": float(colors.mean()),
        "converged": bool(colors.mean() > 0.95 or colors.mean() < 0.05),
    }


def run_snowflake(args, cfg: AvalancheConfig) -> Dict:
    from go_avalanche_tpu.models import family as fam

    state = fam.snowflake_init(jax.random.key(args.seed), args.nodes, cfg,
                               yes_fraction=args.yes_fraction)
    final = jax.jit(fam.snowflake_run,
                    static_argnames=("cfg", "max_rounds"))(
        state, cfg, args.max_rounds)
    acc = np.asarray(jax.device_get(final.accepted_at))
    colors = np.asarray(jax.device_get(final.color))
    done = acc >= 0
    return {
        "rounds": int(jax.device_get(final.round)),
        "accepted_fraction": float(done.mean()),
        "yes_fraction_final": float(colors[done].mean())
        if done.any() else None,
        "accept_round_median": float(np.median(acc[done]))
        if done.any() else None,
    }


def run_streaming_dag(args, cfg: AvalancheConfig) -> Dict:
    """Streaming conflict-set run: `--txs` pending txs in conflict sets of
    `--conflict-size`, streamed through a `--slots`-set window
    (models/streaming_dag) — the north-star 1M-tx UTXO-conflict path."""
    from go_avalanche_tpu.models import streaming_dag as sdg

    c = args.conflict_size
    if args.txs % c:
        raise SystemExit(f"--txs ({args.txs}) must divide by "
                         f"--conflict-size ({c})")
    n_sets = args.txs // c
    backlog = sdg.make_set_backlog(
        jnp.arange(args.txs, dtype=jnp.int32).reshape(n_sets, c))
    state = sdg.init(jax.random.key(args.seed), args.nodes, args.slots,
                     backlog, cfg)
    state = sdg.with_trace(state, cfg, args.max_rounds)
    if args.mesh:
        from go_avalanche_tpu.parallel import sharded_streaming_dag as ssd

        mesh = _parse_mesh(args.mesh)
        state = ssd.shard_streaming_dag_state(state, mesh)
        final = ssd.run_sharded_streaming_dag(mesh, state, cfg,
                                              max_rounds=args.max_rounds,
                                              donate=args.donate)
    elif args.chunk:
        # Host-chunked dispatch (bit-identical to the single dispatch):
        # long runs survive runtime dispatch watchdogs, and --checkpoint
        # resumes a killed run from the last saved chunk boundary.
        state = _maybe_restore(args.checkpoint, state)
        final = sdg.run_chunked(state, cfg, max_rounds=args.max_rounds,
                                chunk=args.chunk,
                                checkpoint_path=args.checkpoint)
        if args.checkpoint and bool(jax.device_get(sdg.drained(final, cfg))):
            # Drained: remove the checkpoint so rerunning the same command
            # starts a fresh simulation instead of silently resuming (and
            # instantly "finishing") the completed one.  A max_rounds-capped
            # run keeps its checkpoint — resuming that is the point.
            import os

            try:
                os.remove(args.checkpoint)
            except FileNotFoundError:
                pass
    else:
        final = jax.jit(sdg.run, static_argnames=("cfg", "max_rounds"))(
            state, cfg, args.max_rounds)
    from go_avalanche_tpu import traffic as tf

    out = {
        "rounds": int(jax.device_get(final.dag.base.round)),
        "window_sets": args.slots,
        "conflict_sets": n_sets,
        **sdg.resolution_summary(final),
        **tf.latency_percentiles(final.traffic),
        **_emit_trace(args, cfg, final.dag.base.trace),
    }
    return out


def run_backlog(args, cfg: AvalancheConfig) -> Dict:
    """Streaming working-set run: `--txs` pending txs through a `--slots`
    working-set window (models/backlog) — the bounded-HBM north-star path."""
    from go_avalanche_tpu.models import backlog as bl

    b = bl.make_backlog(jnp.arange(args.txs, dtype=jnp.int32))
    state = bl.init(jax.random.key(args.seed), args.nodes, args.slots, b,
                    cfg)
    state = bl.with_trace(state, cfg, args.max_rounds)
    if args.mesh:
        from go_avalanche_tpu.parallel import sharded_backlog

        mesh = _parse_mesh(args.mesh)
        state = sharded_backlog.shard_backlog_state(state, mesh)
        final = sharded_backlog.run_sharded_backlog(
            mesh, state, cfg, max_rounds=args.max_rounds,
            donate=args.donate)
    else:
        final = jax.jit(bl.run, static_argnames=("cfg", "max_rounds"))(
            state, cfg, args.max_rounds)
    from go_avalanche_tpu import traffic as tf

    trace_extra = _emit_trace(args, cfg, final.sim.trace)
    out = jax.device_get(final.outputs)
    settled = np.asarray(out.settled)
    latency = (np.asarray(out.settle_round)
               - np.asarray(out.admit_round))[settled]
    return {
        "rounds": int(jax.device_get(final.sim.round)),
        "slots": args.slots,
        "settled_fraction": float(settled.mean()),
        "accepted_fraction": float(np.asarray(out.accepted)[settled].mean())
        if settled.any() else None,
        "settle_latency_median": float(np.median(latency))
        if settled.any() else None,
        **tf.latency_percentiles(final.traffic),
        **trace_extra,
    }


def run_node_stream(args, cfg: AvalancheConfig) -> Dict:
    """Node-axis streaming run: a `--registry-nodes` population of which
    `--active-nodes` rows are resident in the dense window at a time,
    churn rotating the working set stake-proportionally
    (models/node_stream) — the million-node-axis path."""
    from go_avalanche_tpu.models import node_stream as ns

    state = ns.init(jax.random.key(args.seed), args.txs, cfg)
    state = ns.with_trace(state, cfg, args.max_rounds)
    if args.mesh:
        from go_avalanche_tpu.parallel import sharded_node_stream as sns

        mesh = _parse_mesh(args.mesh)
        state = sns.shard_node_stream_state(state, mesh)
        final, _ = sns.run_scan_sharded_node_stream(
            mesh, state, cfg, n_rounds=args.max_rounds,
            donate=args.donate)
    else:
        final, _ = jax.jit(ns.run_scan,
                           static_argnames=("cfg", "n_rounds"))(
            state, cfg, args.max_rounds)
    return {
        # Overrides the generic "nodes" key (--nodes is unread here —
        # the window height is --active-nodes).
        "nodes": cfg.active_nodes,
        "rounds": int(jax.device_get(final.sim.round)),
        "registry_nodes": cfg.registry_nodes,
        "active_nodes": cfg.active_nodes,
        **ns.window_summary(final, cfg),
        **_emit_trace(args, cfg, final.sim.trace),
    }


def run_fleet_mode(args, cfg: AvalancheConfig) -> Dict:
    """`--fleet` driver: one vmapped Monte-Carlo fleet per config point
    (go_avalanche_tpu/fleet.py), Wilson-CI estimates out; with
    `--phase-grid`, one fleet per cartesian point.  Phase rows stream
    to the active `--metrics` sink as phase-diagram JSONL
    (docs/observability.md)."""
    from go_avalanche_tpu import fleet as fl
    from go_avalanche_tpu import obs
    from go_avalanche_tpu.obs.sink import active_sink

    sink = active_sink()
    mesh = getattr(args, "fleet_mesh", None)
    mesh_extra = ({"fleet_mesh": args.mesh,
                   "fleet_devices": int(mesh.devices.size)}
                  if mesh is not None else {})
    common = dict(fleet=args.fleet, n_nodes=args.nodes, n_txs=args.txs,
                  n_rounds=args.max_rounds, seed=args.seed,
                  conflict_size=args.conflict_size,
                  yes_fraction=args.yes_fraction,
                  contested=args.contested,
                  window=args.slots, mesh=mesh)
    if args.phase_grid_parsed is not None:
        rows = fl.run_phase_grid(args.model, cfg,
                                 args.phase_grid_parsed, sink=sink,
                                 **common)
        return {"fleet": args.fleet, "phase_points": len(rows),
                "grid_rows": rows, **mesh_extra}
    res = fl.run_fleet(args.model, cfg, **common)
    row = res.summary()
    row.update(mesh_extra)
    realized = res.realizations()
    if realized:
        row["realizations"] = realized
    if sink is not None:
        sink.write({**row, "point": {}, "tag": obs.tag_from_config(cfg)})
    if res.trace is not None:
        # Per-trial round-by-round traces (the vmap-lifted [F, S, M]
        # plane): fleet-stacked rows to the trace sink — --trace-out
        # when given, else the phase-row sink (rows are distinguishable
        # by their `round` key).
        row.update(_emit_trace(args, cfg, res.trace, fleet=True))
    return row


def _report_memory(args, cfg) -> None:
    """`--report-memory`: compile the exact program the parsed flags
    select (the `--audit` program seams, analysis/hlo_audit.py) and
    print its compiled memory ledger + the analytic per-plane state
    footprint (obs/resources.py) to stderr.  Reporting only — the
    assertions live in `benchmarks/mem_pin.py` and the contract
    auditor's memory budget; stdout keeps the one-result contract."""
    from go_avalanche_tpu.analysis import hlo_audit
    from go_avalanche_tpu.obs import resources

    specs = mesh = state_abs = None
    if args.fleet is not None:
        from go_avalanche_tpu import fleet as fl

        fleet_mesh = getattr(args, "fleet_mesh", None)
        keys_abs = jax.eval_shape(
            lambda: jax.random.split(jax.random.key(args.seed),
                                     args.fleet))
        jitted = fl.compiled_fleet_program(
            args.model, cfg, args.nodes, args.txs, args.max_rounds,
            args.conflict_size, args.yes_fraction, args.contested,
            args.slots, mesh=fleet_mesh)
        compiled = jitted.lower(keys_abs).compile()
        scope = (f"fleet{args.fleet} (argument = the per-trial key "
                 f"plane; states build in-graph)")
        if fleet_mesh is not None and fleet_mesh.devices.size > 1:
            scope += (f", trial axis over {fleet_mesh.devices.size} "
                      f"devices (per-device ledger)")
    elif args.mesh:
        from go_avalanche_tpu import parallel

        mesh, program, state_abs = hlo_audit._run_sim_mesh_program(
            args, cfg)
        specs = parallel._specs_for(args.model, state_abs)
        compiled = program.lower(state_abs).compile()
        scope = "per-device (sharded planes divide by their mesh axes)"
    else:
        program, state_abs = hlo_audit._run_sim_dense_program(args, cfg)
        compiled = program.lower(state_abs).compile()
        scope = "single device"

    rec = resources.memory_record(compiled)
    print(f"memory report [{args.model}, {scope}]:", file=sys.stderr)
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "generated_code_bytes",
                "live_peak_bytes"):
        print(f"  {key:>22}: {rec[key]:>15,}", file=sys.stderr)
    if state_abs is not None:
        fp = resources.footprint(state_abs, specs, mesh)
        print(f"  analytic state footprint: {fp['total_bytes']:,} B "
              f"across {len(fp['planes'])} planes; aliased "
              f"{rec['alias_bytes']:,} B update in place", file=sys.stderr)
        top = sorted(fp["planes"].items(), key=lambda kv: -kv[1])[:5]
        for path, nbytes in top:
            print(f"    {path:>24}: {nbytes:>15,}", file=sys.stderr)


def main(argv=None) -> Dict:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--model",
                        choices=["slush", "snowflake", "snowball",
                                 "avalanche", "dag", "backlog",
                                 "streaming_dag", "node_stream"],
                        default="avalanche")
    parser.add_argument("--nodes", type=int, default=256)
    parser.add_argument("--txs", type=int, default=64)
    parser.add_argument("--max-rounds", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=0)
    # protocol constants (reference parity defaults)
    parser.add_argument("--finalization-score", type=int, default=128)
    parser.add_argument("--max-element-poll", type=int, default=4096)
    parser.add_argument("--window", type=int, default=8)
    parser.add_argument("--quorum", type=int, default=7)
    parser.add_argument("--k", type=int, default=8)
    parser.add_argument("--alpha", type=float, default=0.8)
    parser.add_argument("--vote-mode", choices=["sequential", "majority"],
                        default="sequential")
    # simulator knobs
    parser.add_argument("--no-gossip", action="store_true")
    parser.add_argument("--weighted", action="store_true",
                        help="latency-weighted peer sampling")
    parser.add_argument("--distinct-peers", action="store_true",
                        help="sample k DISTINCT peers per node per round "
                             "(without replacement; the protocol's real "
                             "query semantics)")
    parser.add_argument("--clusters", type=int, default=1,
                        help="clustered topology: nodes in this many "
                             "contiguous clusters; draws prefer the own "
                             "cluster (1 = off; models: avalanche, dag, "
                             "backlog, streaming_dag — like --weighted)")
    parser.add_argument("--cluster-locality", type=float, default=0.8,
                        help="P(a draw lands in the drawing node's own "
                             "cluster)")
    # stake subsystem (go_avalanche_tpu/stake.py)
    parser.add_argument("--stake-mode",
                        choices=["off", "uniform", "zipf", "explicit"],
                        default="off",
                        help="per-node stake distribution "
                             "(cfg.stake_mode): peer draws become "
                             "stake-weighted COMMITTEE draws — "
                             "'uniform' equal stake, 'zipf' node i "
                             "holds 1/(i+1)^s with s = --stake-zipf-s "
                             "(id 0 richest), 'explicit' the "
                             "--stake-weights vector.  With "
                             "--clusters > 1 the draw runs the "
                             "two-level hierarchical sampler "
                             "(bit-identical to the flat CDF).  "
                             "Models with a peer-draw dispatch only "
                             "(avalanche, dag, backlog, "
                             "streaming_dag, node_stream); 'off' = "
                             "the weightless pre-stake path")
    parser.add_argument("--stake-zipf-s", type=float, default=1.0,
                        help="zipf exponent for --stake-mode zipf "
                             "(> 0; larger = more concentrated stake)")
    parser.add_argument("--stake-weights", type=str, default=None,
                        metavar="W1,W2,...",
                        help="--stake-mode explicit: the per-node "
                             "stake vector (comma-separated positive "
                             "numbers; one per node — or per REGISTRY "
                             "entry with --registry-nodes)")
    parser.add_argument("--registry-nodes", type=int, default=0,
                        metavar="R",
                        help="node-axis streaming scheduler "
                             "(models/node_stream, --model "
                             "node_stream): the full node-registry "
                             "size, of which only --active-nodes rows "
                             "are resident in the dense window at a "
                             "time — the nodes >> HBM regime.  Needs "
                             "a --stake-mode (the working set is "
                             "drawn stake-proportionally)")
    parser.add_argument("--active-nodes", type=int, default=0,
                        metavar="W",
                        help="node_stream: active working-set rows "
                             "(the dense window height; "
                             "< --registry-nodes)")
    parser.add_argument("--node-churn-rate", type=float, default=0.0,
                        help="node_stream: P(an active row rotates "
                             "out, per round); departures retire "
                             "their vote records, arrivals are drawn "
                             "stake-proportionally from the "
                             "non-resident registry")
    parser.add_argument("--yes-fraction", type=float, default=1.0,
                        help="slush/snowflake/snowball: initial "
                             "yes-preference fraction")
    parser.add_argument("--contested", action="store_true",
                        help="avalanche: per-NODE 50/50 initial preferences "
                             "(the network must actually converge per tx)")
    parser.add_argument("--conflict-size", type=int, default=2,
                        help="dag: txs per conflict set")
    parser.add_argument("--slots", type=int, default=64,
                        help="backlog: active working-set slots; "
                             "streaming_dag: active working-set SETS")
    # live-traffic service mode (go_avalanche_tpu/traffic.py)
    parser.add_argument("--arrival-mode",
                        choices=["off", "poisson", "bursty", "diurnal",
                                 "external"],
                        default="off",
                        help="live-traffic arrival schedule (streaming "
                             "models backlog/streaming_dag, dense, "
                             "--mesh, or --fleet backlog): instead of "
                             "draining a fully pre-seeded backlog, "
                             "admission units (txs / conflict sets) "
                             "ARRIVE per round — 'poisson' at "
                             "--arrival-rate, 'bursty' with a "
                             "--arrival-burst-factor peak for the "
                             "first --arrival-duty of every "
                             "--arrival-period rounds, 'diurnal' on a "
                             "--arrival-depth sinusoid, 'external' "
                             "(arrivals pushed via the Connector "
                             "SIM_SUBMIT message only).  Finality "
                             "latency (arrival round -> settle round) "
                             "is recorded in-graph with p50/p99/p999 "
                             "percentiles (docs/observability.md).  "
                             "'off' = the seed drain path, statically "
                             "absent from every compiled program")
    parser.add_argument("--arrival-rate", type=float, default=0.0,
                        help="mean admission units per round (the "
                             "offered load); > 0 for every schedule "
                             "except off/external")
    parser.add_argument("--arrival-period", type=int, default=0,
                        help="bursty/diurnal: modulation cycle length "
                             "in rounds (>= 2)")
    parser.add_argument("--arrival-burst-factor", type=float, default=1.0,
                        help="bursty: peak rate multiplier (> 1) during "
                             "the duty window")
    parser.add_argument("--arrival-duty", type=float, default=0.5,
                        help="bursty: fraction of each period at the "
                             "peak, in (0, 1)")
    parser.add_argument("--arrival-depth", type=float, default=0.0,
                        help="diurnal: sinusoid modulation depth in "
                             "[0, 1]")
    parser.add_argument("--arrival-cluster-weights", type=str,
                        default=None, metavar="W1,W2,...",
                        help="per-cluster arrival skew (hot regions): "
                             "one positive rate multiplier per "
                             "cluster (--clusters entries) — the "
                             "admission order splits into contiguous "
                             "region blocks (the clustered topology's "
                             "own cluster_of partition) and each "
                             "block's arrivals draw at rate x its "
                             "region weight.  Needs --clusters > 1 "
                             "and an in-graph schedule mode")
    parser.add_argument("--arrival-backpressure", type=str, default=None,
                        metavar="LO,HI",
                        help="closed-loop admission control: working-set "
                             "occupancy fractions — full scheduled rate "
                             "below LO, fully throttled above HI, "
                             "linear in between (0 <= LO < HI <= 1); "
                             "occupancy is the backpressure signal "
                             "(examples/capacity_planning.py)")
    # fault model
    parser.add_argument("--byzantine", type=float, default=0.0)
    parser.add_argument("--flip-probability", type=float, default=1.0)
    parser.add_argument("--adversary",
                        choices=[s.value for s in AdversaryStrategy],
                        default=AdversaryStrategy.FLIP.value,
                        help="what a lying byzantine peer answers")
    parser.add_argument("--adversary-policy",
                        choices=list(ADVERSARY_POLICIES),
                        default="off",
                        help="adaptive adversary policy "
                             "(cfg.adversary_policy, ops/adversary.py): "
                             "a jit-static attack kind that reads the "
                             "CURRENT network state each round — "
                             "'split_vote' lies vote the HONEST "
                             "population's minority color (the arXiv "
                             "2401.02811 stall attack; overrides "
                             "--adversary's lie content), "
                             "'withhold_near_quorum' lying draws go "
                             "silent when the querier is within "
                             "--adversary-margin window votes of the "
                             "conclusive quorum (async configs expire "
                             "them through the timeout machinery), "
                             "'stake_eclipse' concentrates lies on the "
                             "top-stake honest queriers (needs "
                             "--stake-mode), 'timing' delays lies to "
                             "land just before --timeout-rounds (needs "
                             "an async --latency-mode).  Composes with "
                             "--byzantine/--flip-probability; 'off' = "
                             "the static strategies only, statically "
                             "absent from every compiled program")
    parser.add_argument("--adversary-margin", type=int, default=1,
                        help="withhold_near_quorum: window votes short "
                             "of the conclusive quorum at which a "
                             "querier counts as near-quorum (>= quorum "
                             "- margin)")
    parser.add_argument("--drop", type=float, default=0.0)
    parser.add_argument("--churn", type=float, default=0.0)
    parser.add_argument("--latency-mode",
                        choices=["none", "fixed", "geometric", "weighted",
                                 "rtt"],
                        default="none",
                        help="async query lifecycle (ops/inflight.py): "
                             "per-(querier, draw) response latency in "
                             "rounds — 'fixed' = always "
                             "--latency-rounds, 'geometric' = iid with "
                             "that mean, 'weighted' = coupled to the "
                             "latency_weight plane (nearest peer 0, "
                             "farthest --latency-rounds; snowball has "
                             "no such plane, so 'weighted' there "
                             "degenerates to latency 0 — use "
                             "fixed/geometric), 'rtt' = topology-"
                             "coupled from the --rtt-matrix cluster-"
                             "pair matrix (needs --clusters > 1).  "
                             "'none' = the synchronous ideal.  Works "
                             "with every model; sequential vote mode "
                             "only")
    parser.add_argument("--latency-rounds", type=int, default=0,
                        help="latency parameter (see --latency-mode); "
                             "draws beyond --timeout-rounds expire "
                             "unanswered")
    parser.add_argument("--partition", type=str, default=None,
                        metavar="START,END,FRAC",
                        help="network partition: for rounds [START, END) "
                             "split the nodes at FRAC (cluster-aligned "
                             "with --clusters); cross-partition queries "
                             "TIME OUT (expire unanswered) rather than "
                             "silently vanishing, then the partition "
                             "heals.  Turns on the async engine even "
                             "with --latency-mode none")
    parser.add_argument("--fault-script", type=str, default=None,
                        metavar="PATH.json",
                        help="scheduled fault-script engine "
                             "(cfg.fault_script): a JSON list of timed "
                             "events — partition / regional_outage / "
                             "latency_spike / churn_burst, tuple or "
                             "object spelling (see docs/observability.md "
                             "for the schema; examples/fault_scenarios.py "
                             "for worked scenarios).  Windows are "
                             "END-EXCLUSIVE rounds; composes with "
                             "--partition (the one-event sugar).  "
                             "Malformed, out-of-range or overlapping "
                             "events are rejected HERE at the parser, "
                             "never in the worker.  Works with every "
                             "model")
    parser.add_argument("--rtt-matrix", type=str, default=None,
                        metavar="SPEC",
                        help="cluster-pair RTT matrix for --latency-mode "
                             "rtt (cfg.rtt_matrix): 'C x C' latencies in "
                             "rounds, either inline rows "
                             "('1,3;3,1' — rows ';'-separated) or a "
                             "path to a JSON file holding a list of "
                             "lists.  Row i column j = latency of a "
                             "query from cluster i to cluster j; "
                             "entries >= --timeout-rounds never deliver. "
                             "Needs --clusters == C")
    parser.add_argument("--timeout-rounds", type=int, default=8,
                        help="async modes: rounds before an outstanding "
                             "query expires unanswered (the in-flight "
                             "ring depth; maps onto request_timeout_s / "
                             "time_step_s — host Processor reaping "
                             "parity).  Expiry flows into "
                             "--skip-absent-votes exactly like drops")
    parser.add_argument("--skip-absent-votes", action="store_true",
                        help="reference-HOST non-response semantics: a "
                             "dead/dropped peer registers NOTHING instead "
                             "of a window-shifting neutral (see RESULTS.md "
                             "churn study; linear vs ~a^7 availability "
                             "cost)")
    parser.add_argument("--fleet", type=int, default=None, metavar="F",
                        help="Monte-Carlo fleet mode (go_avalanche_tpu/"
                             "fleet.py): vmap F whole sims — init, "
                             "--max-rounds rounds, in-graph safety/"
                             "finality reduction — over a batched seed "
                             "axis as ONE compiled program, and report "
                             "P(safety violation) / P(settled) / "
                             "E(finality round) with Wilson confidence "
                             "intervals.  Models: snowball, avalanche, "
                             "dag, backlog (backlog streams --txs "
                             "through a --slots window per trial and, "
                             "with --arrival-*, reports per-trial "
                             "finality-latency percentiles — the "
                             "offered-load capacity diagram).  With "
                             "--metrics, streams phase-diagram JSONL "
                             "rows (one per config point) instead of "
                             "per-round telemetry")
    parser.add_argument("--phase-grid", type=str, default=None,
                        metavar="JSON",
                        help="with --fleet: sweep a config-axis grid — "
                             "inline JSON or a path to a JSON file, "
                             "e.g. '{\"byzantine_fraction\": [0.0, 0.2, "
                             "0.4], \"k\": [8, 16]}' — one fleet per "
                             "cartesian point (re-jit per point), one "
                             "summary row each.  Sweepable axes: k, "
                             "quorum, window, alpha, finalization_"
                             "score, byzantine_fraction, flip_"
                             "probability, drop_probability, churn_"
                             "probability, latency_rounds, adversary_"
                             "strategy.  Malformed grids (non-numeric "
                             "entries, unknown axes) are rejected HERE "
                             "at the parser")
    parser.add_argument("--mesh", type=str, default=None, metavar="N,T",
                        help="run the sharded backend over an "
                             "(n node shards, t tx shards) device mesh "
                             "(models: avalanche, dag, backlog).  With "
                             "--fleet the axes read (A, B) TRIAL "
                             "shards instead (parallel/"
                             "sharded_fleet.py): the Monte-Carlo trial "
                             "axis is laid over A*B devices — each "
                             "runs F/(A*B) whole sims in one compiled "
                             "program per config point, bit-identical "
                             "to the dense fleet on the same seeds — "
                             "so F must divide by A*B")
    parser.add_argument("--fleet-shape", choices=("auto",), default=None,
                        help="knee-table-driven fleet sizing "
                             "(benchmarks/vmem_knee.py, the archived "
                             "[F, N, T] VMEM/HBM-knee table for the "
                             "active device profile): without --fleet, "
                             "PICKS F — the deepest trials-per-device "
                             "row whose largest safe N=T square still "
                             "fits --nodes/--txs, times the --mesh "
                             "device count; with --fleet, VALIDATES it "
                             "— a shape above the knee is rejected "
                             "here with the table row cited")
    parser.add_argument("--donate", action="store_true",
                        help="with --mesh: donate the sharded state into "
                             "the while-loop drivers so the [N, T] planes "
                             "update in place instead of double-buffering "
                             "in HBM.  Opt-in until a hardware soak "
                             "confirms no shard_map aliasing surprises "
                             "(ROADMAP); the single-chip avalanche path "
                             "already donates unconditionally")
    parser.add_argument("--ingest-engine", choices=["u8", "swar32"],
                        default="u8",
                        help="RegisterVotes ingest engine "
                             "(cfg.ingest_engine): 'u8' = per-vote uint8 "
                             "window updates (reference), 'swar32' = 4 tx "
                             "columns lane-packed per uint32 word with the "
                             "closed-form confidence fold (ops/swar.py). "
                             "Bit-exact either way")
    parser.add_argument("--round-engine",
                        choices=["phased", "megakernel"],
                        default="phased",
                        help="whole-round execution engine for the dense "
                             "avalanche round (cfg.round_engine): "
                             "'phased' = the per-phase chain "
                             "(reference), 'megakernel' = ONE Pallas "
                             "program fusing the exchange gather, the "
                             "SWAR window ingest, and the closed-form "
                             "confidence fold (ops/megakernel.py).  "
                             "Bit-exact either way; --model avalanche "
                             "synchronous rounds only — async/in-flight "
                             "knobs, adaptive adversary policies, and "
                             "the other models reject it as inert")
    parser.add_argument("--inflight-engine",
                        choices=["walk", "walk_earlyout", "coalesced"],
                        default="walk",
                        help="async delivery engine (cfg.inflight_engine; "
                             "any model, active only with --latency-mode/"
                             "--partition): 'walk' = the per-age "
                             "fori_loop (reference), 'walk_earlyout' = "
                             "walk + per-age lax.cond skip of inert "
                             "ages, 'coalesced' = one-pass ring drain "
                             "(whole-ring masks, active ages compacted "
                             "oldest-first, bit-packed ring poll "
                             "masks; cost tracks deliveries, not ring "
                             "depth).  Bit-exact all three ways")
    parser.add_argument("--chunk", type=int, default=0, metavar="ROUNDS",
                        help="streaming_dag: dispatch the run in host-driven "
                             "chunks of this many rounds (0 = one device "
                             "dispatch). Bit-identical results; long runs "
                             "survive runtime dispatch watchdogs")
    parser.add_argument("--checkpoint", type=str, default=None,
                        metavar="PATH",
                        help="streaming_dag with --chunk: save state here "
                             "at chunk boundaries and resume from it if it "
                             "exists")
    parser.add_argument("--stream-retire-cap", type=int, default=None,
                        metavar="SETS",
                        help="streaming_dag: cap set-slots retired+refilled "
                             "per round and rewrite only their window "
                             "columns.  Free above ~2-4x the settle rate "
                             "W/L and 1.3-1.5x faster on TPU at mid-sized "
                             "node counts (RESULTS.md retire-cap tradeoff; "
                             "PERF_NOTES r05 A/B).  Default: dense rewrite")
    # output / tooling
    parser.add_argument("--audit", action="store_true",
                        help="run the HLO contract auditor "
                             "(go_avalanche_tpu/analysis/hlo_audit.py) "
                             "on the EXACT program these flags select "
                             "before executing it: host-callback "
                             "budget, dtype budget, collective "
                             "allowlist (--mesh: the driver's "
                             "DECLARED_COLLECTIVES manifest), donation "
                             "coverage.  Lowering never compiles, so "
                             "the audited program still compiles "
                             "exactly once at execution (--fleet "
                             "audits lower through the same lru-cached "
                             "jit the fleet executes).  Exits 1 with "
                             "the contract failures instead of running")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON line instead of key=value text")
    parser.add_argument("--trace", type=str, default=None,
                        help="write a JAX profiler trace to this directory")
    parser.add_argument("--metrics", type=str, default=None, metavar="PATH",
                        help="stream per-round telemetry to this JSONL file "
                             "through the in-graph metrics tap "
                             "(go_avalanche_tpu/obs: one unordered "
                             "io_callback per emitted round inside the "
                             "compiled loop — the flight recorder) and "
                             "write a run manifest next to it "
                             "(PATH.manifest.json).  Models whose round "
                             "body carries the tap: snowball, avalanche, "
                             "dag, backlog, streaming_dag (the streaming "
                             "schedulers emit their FULL scheduler "
                             "record — inner round + retire/occupancy + "
                             "traffic fields — one line per round).  "
                             "Sharded runs stream host-side "
                             "instead (obs.MetricsSink.write_stacked — "
                             "see examples/fault_scenarios.py), so "
                             "--metrics excludes --mesh")
    parser.add_argument("--metrics-every", type=int, default=0,
                        metavar="N",
                        help="emit every N-th round (cfg.metrics_every); "
                             "defaults to 1 when --metrics is given, 0 "
                             "(tap statically absent — every hlo_pin "
                             "hash unchanged) otherwise")
    parser.add_argument("--trace-every", type=int, default=0,
                        metavar="N",
                        help="on-device trace plane (cfg.trace_every, "
                             "obs/trace.py): every N-th round the "
                             "round/scheduler step writes its telemetry "
                             "row into an [S, M] buffer carried in the "
                             "sim state — one dynamic_update_slice, no "
                             "io_callback, so it works with --mesh "
                             "(replicated plane) and --fleet (per-trial "
                             "[F, S, M] traces).  Decoded host-side "
                             "after the run to the same JSONL schema "
                             "as --metrics-every, into --trace-out if "
                             "given, else the --metrics sink.  0 "
                             "(default) = statically absent (every "
                             "hlo_pin hash unchanged)")
    parser.add_argument("--trace-out", type=str, default=None,
                        metavar="PATH",
                        help="with --trace-every: decode the trace "
                             "plane to this JSONL file (+ manifest) "
                             "instead of the --metrics sink.  REQUIRED "
                             "when --metrics-every is also nonzero — "
                             "each tap writes one line per round, and "
                             "an interleaved mix in one file would "
                             "carry duplicate rounds under one "
                             "manifest")
    parser.add_argument("--report-memory", action="store_true",
                        help="resource report (obs/resources.py): "
                             "compile the EXACT program these flags "
                             "select, print its memory_analysis() "
                             "ledger (argument / output / temp / "
                             "aliased / donation-adjusted live peak) "
                             "and the analytic per-plane state "
                             "footprint to stderr, then run.  Same "
                             "single-program rule as --audit "
                             "(rejected with --phase-grid / "
                             "--check-invariants / --chunk)")
    parser.add_argument("--check-invariants", action="store_true",
                        help="debug mode (obs/watchdog.py): step the sim "
                             "one jitted round at a time and assert the "
                             "structural invariants on the host between "
                             "rounds — confidence counter caps, window "
                             "bit hygiene, ring ages/depth, packed-plane "
                             "padding, finalized-count monotonicity.  "
                             "Raises InvariantViolation with offender "
                             "indices on the first failure.  Models: "
                             "snowball, avalanche, dag (dense; the "
                             "streaming schedulers legitimately reset "
                             "refilled columns)")
    args = parser.parse_args(argv)

    # --audit / --report-memory validation: everything parser-level
    # (the PR 5 rule).  Both lower ONE program; flag combinations with
    # no single-program meaning are rejected here, never discovered in
    # the worker.
    if args.audit or args.report_memory:
        what, verb = (("--audit", "audit") if args.audit
                      else ("--report-memory", "analyze"))
        if args.phase_grid is not None:
            parser.error(
                f"{what} with --phase-grid would compile twice per "
                f"point: every grid point re-jits its own fleet "
                f"program, so {verb}ing the sweep means lowering the "
                f"whole grid before the sweep compiles it again — "
                f"{verb} a single --fleet point (one program, lowered "
                f"once, compiled once) instead")
        if args.check_invariants:
            parser.error(f"{what} lowers the one fused program the run "
                         f"executes; --check-invariants dispatches "
                         f"per-round jits — there is no single program "
                         f"to {verb}")
        if args.chunk:
            parser.error(f"{what} lowers the one fused program the run "
                         f"executes; --chunk dispatches host-driven "
                         f"chunks — {verb} the unchunked spelling")

    # Adversary-knob validation: mirror the config's inert-knob
    # rejections at the parser (the PR 5 rule — the _validate_adversary
    # messages would otherwise surface only at build_config below; these
    # name the flags).
    if args.byzantine == 0.0:
        inert = [flag for flag, bad in (
            ("--flip-probability", args.flip_probability != 1.0),
            ("--adversary", args.adversary
             != AdversaryStrategy.FLIP.value),
            ("--adversary-policy", args.adversary_policy != "off"),
            ("--adversary-margin", args.adversary_margin != 1),
        ) if bad]
        if inert:
            parser.error(
                f"{'/'.join(inert)} set with --byzantine 0: with no "
                f"byzantine nodes every adversary knob is inert and "
                f"would mislabel the run as attacked — set "
                f"--byzantine > 0")
    if (args.adversary_policy != "off"
            and args.model in ("slush", "snowflake")):
        parser.error(
            f"--adversary-policy needs a round body carrying the "
            f"policy context (models snowball/avalanche/dag/backlog/"
            f"streaming_dag/node_stream); the family models "
            f"(slush/snowflake) predate it — got {args.model}")

    # Round-engine validation: the megakernel fuses the dense avalanche
    # SYNCHRONOUS round only (ops/megakernel.py).  Mirror the config's
    # _validate_round_engine rejections at the parser (the PR 5 rule)
    # so the flags are named instead of the config fields.
    if getattr(args, "round_engine", "phased") != "phased":
        if args.model != "avalanche":
            parser.error(
                f"--round-engine megakernel is wired for --model "
                f"avalanche (the dense synchronous round); {args.model} "
                f"keeps the phased path — the knob would be inert")
        if args.latency_mode != "none" or args.partition:
            parser.error(
                "--round-engine megakernel covers the synchronous "
                "round only; --latency-mode/--partition deliver votes "
                "ACROSS rounds through the in-flight ring, outside the "
                "one fused program — run the async lanes on the "
                "phased engine")
        if args.inflight_engine != "walk":
            parser.error(
                "--inflight-engine selects the async ring's delivery "
                "engine; --round-engine megakernel never builds the "
                "ring — the knob would be silently inert")
        if args.skip_absent_votes:
            parser.error(
                "--skip-absent-votes selects the MAJORITY-threshold "
                "ingest; the megakernel fuses the SEQUENTIAL window "
                "ingest — run the majority A/B on the phased engine")
        if args.vote_mode != VoteMode.SEQUENTIAL.value:
            parser.error(
                f"--round-engine megakernel fuses the SEQUENTIAL "
                f"window ingest; --vote-mode {args.vote_mode} keeps "
                f"the phased path")
        if args.adversary_policy != "off":
            parser.error(
                "--adversary-policy reads per-round context planes the "
                "fused program does not thread; run the adaptive-"
                "adversary lanes on the phased engine")
        if (args.byzantine > 0
                and args.adversary == AdversaryStrategy.EQUIVOCATE.value):
            parser.error(
                "--adversary equivocate draws per-draw coin streams "
                "the fused program cannot replay in-kernel; run it on "
                "the phased engine")
        if args.mesh:
            parser.error(
                "--round-engine megakernel is the single-device dense "
                "lane; the --mesh drivers keep the phased path "
                "(parallel/sharded.py rejects the knob)")
        if args.fleet is not None or args.fleet_shape is not None:
            parser.error(
                "--round-engine megakernel is the single-sim dense "
                "lane; the fleet drivers keep the phased path")
        if args.txs % 32:
            parser.error(
                f"--round-engine megakernel needs --txs divisible by "
                f"32 (whole bit-packed preference words), got "
                f"{args.txs}")

    # Fleet-mode validation: everything parser-level (the PR 5 rule).
    args.phase_grid_parsed = None
    args.fleet_mesh = None
    if args.fleet_shape is not None:
        # Knee-table-driven fleet sizing (benchmarks/vmem_knee.py):
        # resolve the active device profile from the backend, then
        # PICK F (no --fleet: the deepest trials-per-device row whose
        # knee fits --nodes/--txs, scaled by the mesh's device count)
        # or VALIDATE the explicit --fleet (a shape above the knee is
        # rejected HERE with the table row cited).
        from benchmarks.vmem_knee import select_fleet_shape

        mesh_devices = 1
        if args.mesh:
            try:
                a_s, b_s = args.mesh.split(",")
                mesh_devices = int(a_s) * int(b_s)
            except ValueError:
                parser.error(f"--mesh must be A,B shards, got "
                             f"{args.mesh!r}")
        try:
            sel = select_fleet_shape(jax.devices()[0].platform,
                                     mesh_devices, args.nodes, args.txs,
                                     fleet=args.fleet)
        except ValueError as e:
            parser.error(str(e))
        if args.fleet is None:
            args.fleet = sel["fleet"]
    if args.fleet is not None:
        if args.fleet < 1:
            parser.error(f"--fleet must be >= 1 trials, got {args.fleet}")
        if args.model not in ("snowball", "avalanche", "dag", "backlog"):
            parser.error(f"--fleet supports models snowball/avalanche/"
                         f"dag/backlog, not {args.model}")
        if args.mesh:
            # The fleet x mesh COMPOSITION (the landed
            # fleet-of-sharded-sims item): --mesh A,B lays the trial
            # axis over an (A, B) fleet mesh — A*B devices each run
            # F/(A*B) whole sims in one compiled program, bit-identical
            # to the dense fleet on the same seeds
            # (parallel/sharded_fleet.py).
            from go_avalanche_tpu.parallel import sharded_fleet

            try:
                a_s, b_s = args.mesh.split(",")
                args.fleet_mesh = sharded_fleet.make_fleet_mesh(
                    int(a_s), int(b_s))
                sharded_fleet.check_fleet_divisible(args.fleet,
                                                    args.fleet_mesh)
            except ValueError as e:
                parser.error(f"--fleet x --mesh: {e}")
            if args.donate:
                parser.error(
                    "--donate tunes the sharded single-sim drivers; "
                    "the sharded fleet driver's input is the per-trial "
                    "key plane (nothing worth donating — the bench "
                    "lane's state-scan program donates instead)")
        if args.check_invariants:
            parser.error("--check-invariants steps ONE sim on the host; "
                         "it has no per-trial identity under --fleet")
        if args.model == "dag" and args.txs % args.conflict_size:
            parser.error(f"--fleet dag needs --txs ({args.txs}) divisible "
                         f"by --conflict-size ({args.conflict_size})")
    if args.phase_grid is not None:
        import os

        if args.fleet is None:
            parser.error("--phase-grid requires --fleet (a grid point "
                         "IS a fleet)")
        from go_avalanche_tpu.fleet import phase_points

        try:
            if os.path.exists(args.phase_grid):
                with open(args.phase_grid) as fh:
                    grid = json.load(fh)
            else:
                grid = json.loads(args.phase_grid)
        except (OSError, json.JSONDecodeError) as e:
            parser.error(f"--phase-grid: {e}")
        try:
            phase_points(grid)   # full validation; points re-expand later
        except (ValueError, TypeError) as e:
            parser.error(f"--phase-grid: {e}")
        if "latency_rounds" in grid and args.latency_mode == "none":
            parser.error("--phase-grid sweeps latency_rounds but "
                         "--latency-mode is 'none', under which the "
                         "knob is inert — every point would measure "
                         "the same program")
        if "stake_zipf_s" in grid and args.stake_mode != "zipf":
            parser.error("--phase-grid sweeps stake_zipf_s but "
                         "--stake-mode is not 'zipf' (the exponent is "
                         "only read there) — stake-concentration "
                         "sweeps need the zipf distribution")
        if "arrival_rate" in grid:
            if args.arrival_mode == "off":
                parser.error("--phase-grid sweeps arrival_rate but "
                             "--arrival-mode is 'off', under which the "
                             "knob is inert — offered-load sweeps need "
                             "a live-traffic schedule")
            if args.model != "backlog":
                parser.error("an arrival_rate phase axis needs "
                             "--model backlog (the fleet's streaming "
                             "model with the traffic plane)")
        args.phase_grid_parsed = grid

    if args.arrival_mode != "off" and args.model not in ("backlog",
                                                         "streaming_dag"):
        parser.error(f"--arrival-* is a streaming-scheduler axis "
                     f"(models backlog/streaming_dag — they admit from "
                     f"a backlog as slots retire), not {args.model}")
    if args.arrival_mode == "external":
        parser.error("--arrival-mode external has no push path in "
                     "run_sim (arrivals come only from "
                     "traffic.push_arrivals — the Connector SIM_SUBMIT "
                     "message): the stream would stay empty for "
                     "--max-rounds.  Use a schedule mode here, or "
                     "drive an external stream through "
                     "connector.client.sim_submit")
    # Stake / node-registry validation: everything parser-level (the
    # PR 5 rule — a bad stake config must die here, not in the worker).
    args.stake_weights_parsed = None
    if args.stake_weights is not None:
        try:
            args.stake_weights_parsed = tuple(
                float(x) for x in args.stake_weights.split(","))
        except ValueError:
            parser.error(f"--stake-weights must be comma-separated "
                         f"numbers, got {args.stake_weights!r}")
    if args.stake_mode != "off" and args.model in ("slush", "snowflake",
                                                   "snowball"):
        parser.error(f"--stake-mode is a peer-draw-dispatch axis "
                     f"(models avalanche/dag/backlog/streaming_dag/"
                     f"node_stream); the {args.model} model samples "
                     f"uniformly, so a stake config would be silently "
                     f"inert there")
    if args.model == "node_stream":
        if args.registry_nodes <= 0 or args.active_nodes <= 0:
            parser.error("--model node_stream streams --active-nodes "
                         "resident rows out of a --registry-nodes "
                         "population — both must be set (> 0)")
    elif args.registry_nodes or args.active_nodes or args.node_churn_rate:
        parser.error("--registry-nodes/--active-nodes/"
                     "--node-churn-rate are node-stream scheduler axes "
                     "(--model node_stream); with other models they "
                     "would be silently inert")
    args.arrival_cluster_weights_parsed = None
    if args.arrival_cluster_weights is not None:
        try:
            args.arrival_cluster_weights_parsed = tuple(
                float(x) for x in args.arrival_cluster_weights.split(","))
        except ValueError:
            parser.error(f"--arrival-cluster-weights must be "
                         f"comma-separated numbers, got "
                         f"{args.arrival_cluster_weights!r}")
    args.arrival_backpressure_parsed = None
    if args.arrival_backpressure is not None:
        try:
            lo_s, hi_s = args.arrival_backpressure.split(",")
            args.arrival_backpressure_parsed = (float(lo_s), float(hi_s))
        except ValueError:
            parser.error(f"--arrival-backpressure must be LO,HI "
                         f"occupancy fractions (e.g. 0.7,0.9), got "
                         f"{args.arrival_backpressure!r}")

    if (args.mesh and args.fleet is None
            and args.model not in ("avalanche", "dag", "backlog",
                                   "streaming_dag", "node_stream")):
        # Under --fleet the mesh shards the TRIAL axis and every trial
        # runs the dense per-trial program, so the fleet models
        # (snowball included) all compose — the single-sim driver
        # restriction applies only without --fleet.
        parser.error(f"--mesh supports models avalanche/dag/backlog/"
                     f"streaming_dag/node_stream, not {args.model}")
    if args.donate and not args.mesh:
        parser.error("--donate is a --mesh option (the single-chip "
                     "avalanche path already donates unconditionally)")
    if args.chunk and args.model != "streaming_dag":
        parser.error("--chunk is a streaming_dag option")
    if args.chunk < 0:
        parser.error("--chunk must be >= 0 (0, the default, disables "
                     "chunking)")
    if args.chunk and args.mesh:
        parser.error("--chunk and --mesh are mutually exclusive (the "
                     "sharded backend has its own dispatch loop)")
    if args.checkpoint and not args.chunk:
        parser.error("--checkpoint requires --chunk")
    if args.check_invariants:
        if args.model not in ("snowball", "avalanche", "dag"):
            parser.error(f"--check-invariants supports models snowball/"
                         f"avalanche/dag, not {args.model}")
        if args.mesh:
            parser.error("--check-invariants is a dense debug mode (the "
                         "sharded while-loop drivers never surface "
                         "intermediate states to the host)")
    # Trace-plane validation (the PR 5 rule: everything parser-level).
    if args.trace_every < 0:
        parser.error("--trace-every must be >= 0 (0 disables the "
                     "on-device trace plane)")
    if args.trace_every:
        if args.model in ("slush", "snowflake"):
            parser.error(f"--trace-every needs a round body carrying "
                         f"the trace plane; the family models "
                         f"(slush/snowflake) predate it — got "
                         f"{args.model}")
        if args.trace_every > args.max_rounds:
            parser.error(f"--trace-every ({args.trace_every}) exceeds "
                         f"--max-rounds ({args.max_rounds}): only round "
                         f"0 would ever be sampled — the stride is "
                         f"inert at this horizon (mirrors "
                         f"obs.trace.alloc)")
        if not (args.metrics or args.trace_out):
            parser.error("--trace-every needs a sink for the decoded "
                         "trace: --metrics PATH (shared) or --trace-out "
                         "PATH (its own file)")
        if args.phase_grid is not None:
            parser.error("--trace-every x --phase-grid is not supported: "
                         "every grid point would decode its own "
                         "[F, S, M] trace into one file with repeating "
                         "rounds — trace single --fleet points instead")
    elif args.trace_out:
        parser.error("--trace-out requires --trace-every (without the "
                     "trace plane there is nothing to decode)")
    if args.metrics:
        if args.model in ("slush", "snowflake"):
            parser.error(f"--metrics needs a round body carrying the "
                         f"in-graph tap; the family models "
                         f"(slush/snowflake) predate it — got "
                         f"{args.model}")
        if (args.mesh and args.fleet is None
                and (args.metrics_every or not args.trace_every)):
            # Fleet runs stream PHASE ROWS host-side regardless of the
            # mesh (the in-graph tap is forced off below), so the
            # sharded-driver tap restriction applies only without
            # --fleet.
            parser.error("--metrics is the dense in-graph tap; sharded "
                         "drivers stream stacked telemetry host-side "
                         "(obs.MetricsSink.write_stacked) — or use "
                         "--trace-every: the trace plane is replicated "
                         "and legal under shard_map")
        if args.metrics_every == 0 and (args.trace_every == 0
                                        or args.trace_out):
            # The historic default: a sink implies the callback tap at
            # stride 1.  With the trace plane selected AND no
            # --trace-out, the --metrics sink serves the decoded trace
            # instead and the callback stays off; with --trace-out the
            # trace has its own file, so a bare --metrics keeps its
            # callback meaning (never an opened-but-empty sink).
            args.metrics_every = 1
        if args.metrics_every and args.trace_every and not args.trace_out:
            parser.error("--metrics-every and --trace-every are two "
                         "taps, one JSONL line per round EACH — an "
                         "interleaved mix in one file would carry "
                         "duplicate rounds under one manifest; give "
                         "the trace plane its own sink with "
                         "--trace-out")
    elif args.metrics_every:
        parser.error("--metrics-every requires --metrics (without a sink "
                     "the tap's records are dropped)")
    # Fault-script / RTT-matrix files parse HERE and the whole config
    # validates HERE: a malformed scenario must die at the parser with
    # the validator's message, never as a worker traceback (the PR 5
    # --metrics-every rule).
    args.fault_script_events = None
    if args.fault_script:
        try:
            with open(args.fault_script) as fh:
                data = json.load(fh)
            args.fault_script_events = fault_script_from_json(data)
        except OSError as e:
            parser.error(f"--fault-script: {e}")
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            parser.error(f"--fault-script {args.fault_script}: {e}")
    args.rtt_matrix_parsed = None
    if args.rtt_matrix:
        try:
            args.rtt_matrix_parsed = _parse_rtt_matrix(args.rtt_matrix)
        except (OSError, json.JSONDecodeError, ValueError, TypeError) as e:
            parser.error(f"--rtt-matrix: {e}")
    if args.phase_grid_parsed is not None:
        # Adversary-axis inert combinations (the fleet's one spelling,
        # fleet.check_adversary_grid) die HERE, not mid-sweep.  Sits
        # after the fault-script parse: the timing-policy check reads
        # `_async_on` (build_config's own derivation).
        from go_avalanche_tpu.fleet import check_adversary_grid

        try:
            check_adversary_grid(
                args.phase_grid_parsed, byz_base=args.byzantine,
                strategy_base=args.adversary,
                flip_base=args.flip_probability,
                policy_base=args.adversary_policy,
                async_base=_async_on(args),
                stake_base=args.stake_mode,
                margin_base=args.adversary_margin)
        except ValueError as e:
            parser.error(f"--phase-grid: {e}")
    try:
        cfg = build_config(args)
    except (ValueError, TypeError) as e:
        # validation arithmetic on a non-numeric JSON value (e.g. a
        # null event field) raises TypeError, not ValueError
        parser.error(str(e))
    if args.fleet is not None:
        # The in-graph tap has no per-trial identity under the fleet
        # vmap; a --metrics sink receives PHASE ROWS host-side instead
        # (each row carries its own point tag, so the sink opens
        # untagged).
        import dataclasses

        cfg = dataclasses.replace(cfg, metrics_every=0)
        runner = run_fleet_mode
    else:
        runner = {"slush": run_slush, "snowflake": run_snowflake,
                  "snowball": run_snowball, "avalanche": run_avalanche,
                  "dag": run_dag, "backlog": run_backlog,
                  "streaming_dag": run_streaming_dag,
                  "node_stream": run_node_stream}[args.model]

    if args.audit:
        # Static contract audit of the exact program the flags above
        # selected (analysis/hlo_audit.py) — BEFORE any execution, so a
        # contract violation never produces a half-run artifact.  The
        # report goes to stderr; stdout keeps the one-result contract.
        from go_avalanche_tpu.analysis import hlo_audit

        failures = hlo_audit.audit_run_sim(args, cfg)
        if failures:
            print("AUDIT FAILURES:\n  " + "\n  ".join(failures),
                  file=sys.stderr)
            raise SystemExit(1)
        print(f"audit ok: {args.model} program passes its contracts "
              f"(callbacks/dtype/collectives/donation)", file=sys.stderr)

    if args.report_memory:
        # Resource report of the exact program the flags above selected
        # (obs/resources.py) — BEFORE execution, like --audit, so an
        # out-of-budget shape is visible without paying for the run.
        _report_memory(args, cfg)

    ctx = tracing.trace(args.trace) if args.trace else contextlib.nullcontext()
    if args.metrics:
        from go_avalanche_tpu import obs

        sink_ctx = obs.metrics_sink(
            args.metrics,
            tag="" if args.fleet is not None else obs.tag_from_config(cfg))
    else:
        sink_ctx = contextlib.nullcontext()
    t0 = time.perf_counter()
    with ctx, sink_ctx as sink:
        result = runner(args, cfg)
    extra = {}
    if sink is not None:
        # The sink context drained in-flight callbacks and closed on
        # exit; records_written is final here.
        obs.write_manifest(args.metrics, cfg, extra={
            "model": args.model,
            "workload": {"nodes": args.nodes, "txs": args.txs,
                         "max_rounds": args.max_rounds,
                         "seed": args.seed,
                         **({"fleet": args.fleet}
                            if args.fleet is not None else {})},
            "tag": obs.tag_from_config(cfg),
        })
        extra = {"metrics_records": sink.records_written,
                 "metrics_file": str(sink.path)}
    result = {
        "model": args.model,
        "nodes": args.nodes,
        "txs": args.txs
        if args.model not in ("snowball", "slush", "snowflake") else 1,
        "backend": jax.devices()[0].platform,
        **result,
        **extra,
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
    if args.json:
        print(json.dumps(result))
    else:
        print(" ".join(f"{k}={v}" for k, v in result.items()))
    return result


def cli(argv=None) -> int:
    """Console-script entry point: results go to stdout, exit status 0."""
    main(argv)
    return 0


if __name__ == "__main__":
    raise SystemExit(cli())
