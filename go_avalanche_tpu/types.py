"""Wire/data types: votes, polls, requests, statuses, targets.

This is layer L1 of the reference (SURVEY.md section 1): `Vote`/`Response`
(`response.go:5-25`, `vote.go:3-22`), `RequestRecord` (`response.go:27-51`),
`Inv`/`Hash`/`Status`/`StatusUpdate`/`NodeID` (`avalanche.go:24-71`), and the
`Target` interface (`avalanche.go:73-91`).

Conventions:
 * `Hash` is an `int` — same toy stand-in for a 32-byte digest the reference
   uses (`avalanche.go:71`).
 * Vote errors follow the reference encoding (`vote.go:5`, "this is called
   'error' in abc"): 0 = yes, any other non-negative value = no, negative
   (canonically -1, i.e. uint32 0xFFFFFFFF) = neutral/abstain.  We normalise to
   a signed int so the sign test `int32(err) >= 0` (`vote.go:56`) is direct.
"""

from __future__ import annotations

import enum
from typing import List, NamedTuple, Protocol, Sequence, runtime_checkable

Hash = int
NodeID = int

#: Sentinel for "no suitable node available" (`avalanche.go:28`).
NO_NODE: NodeID = -1

#: Canonical neutral/abstain vote error (`avalanche_test.go:8-11`: uint32(-1)).
VOTE_NEUTRAL = -1
#: Yes vote error (`vote.go:55`: err == 0).
VOTE_YES = 0
#: Conventional no vote error (any non-negative non-zero value is a no).
VOTE_NO = 1


def normalize_err(err: int) -> int:
    """Map a raw (possibly uint32) vote error to the signed convention.

    The reference stores `uint32` and tests the sign of `int32(err)`
    (`vote.go:56`); callers that hand us 0xFFFFFFFF mean "neutral".
    """
    if err >= 0x8000_0000:
        err -= 0x1_0000_0000
    return err


class Status(enum.IntEnum):
    """Consensus status of a target (`avalanche.go:44-56`, same ordering).

    The (finalized, accepted) -> status mapping lives in `vote.go:77-91`:
    not finalized & accepted -> ACCEPTED; not finalized & not accepted ->
    REJECTED; finalized & accepted -> FINALIZED; finalized & not accepted ->
    INVALID.
    """

    INVALID = 0
    REJECTED = 1
    ACCEPTED = 2
    FINALIZED = 3


class StatusUpdate(NamedTuple):
    """A change in consensus status for a target (`avalanche.go:59-62`)."""

    hash: Hash
    status: Status


class Vote(NamedTuple):
    """A single vote for a target (`vote.go:3-12`)."""

    err: int
    hash: Hash

    def get_hash(self) -> Hash:
        return self.hash

    def get_error(self) -> int:
        return self.err


class Inv(NamedTuple):
    """A poll request item for a target (`avalanche.go:64-68`)."""

    target_type: str
    target_hash: Hash


class Response(NamedTuple):
    """A list of votes answering a poll (`response.go:5-25`).

    `cooldown` is carried for wire parity but never read — true of the
    reference as well (`response.go:8`, stored and never used).
    """

    round: int
    cooldown: int
    votes: Sequence[Vote]

    def get_votes(self) -> Sequence[Vote]:
        return self.votes

    def get_round(self) -> int:
        return self.round


class RequestRecord(NamedTuple):
    """An outstanding poll awaiting a response (`response.go:27-46`)."""

    timestamp: float
    invs: Sequence[Inv]

    def get_timestamp(self) -> float:
        return self.timestamp

    def get_invs(self) -> Sequence[Inv]:
        return self.invs

    def is_expired(self, now: float, timeout_s: float) -> bool:
        """True if the request is older than the timeout (`response.go:49-51`).

        Unlike the reference (which reads a package-global clock), the current
        time and timeout are explicit arguments — the processor owns the clock.
        """
        return self.timestamp + timeout_s < now


@runtime_checkable
class Target(Protocol):
    """Something being decided by consensus (`avalanche.go:73-91`).

    e.g. a transaction or a block.  Snake-case spellings of the reference's
    interface methods; semantics are identical.
    """

    def hash(self) -> Hash:
        """Digest used as the target's identity (`avalanche.go:76`)."""
        ...

    def type(self) -> str:
        """Kind of thing, e.g. "transaction" or "block" (`avalanche.go:79`)."""
        ...

    def is_accepted(self) -> bool:
        """Initial preference when first considered (`avalanche.go:83`)."""
        ...

    def score(self) -> int:
        """Ordering weight, e.g. cumulative work (`avalanche.go:86`)."""
        ...

    def is_valid(self) -> bool:
        """Polling stops when a target becomes invalid (`avalanche.go:90`)."""
        ...


class Block:
    """Block test fixture implementing Target (`avalanche.go:130-160`).

    Mutable `valid` / `is_in_active_chain` so tests can invalidate mid-flight
    exactly like the reference suite does (`avalanche_test.go:534`).
    """

    def __init__(self, hash_: Hash, work: int, valid: bool,
                 is_in_active_chain: bool) -> None:
        self._hash = hash_
        self.work = work
        self.valid = valid
        self.is_in_active_chain = is_in_active_chain

    def hash(self) -> Hash:
        return self._hash

    def type(self) -> str:
        return "block"

    def score(self) -> int:
        return self.work

    def is_accepted(self) -> bool:
        return self.is_in_active_chain

    def is_valid(self) -> bool:
        return self.valid


class Tx:
    """Transaction fixture implementing Target (example `main.go:196-209`)."""

    def __init__(self, hash_: Hash, is_accepted: bool = True,
                 score: int = 1) -> None:
        self._hash = hash_
        self._is_accepted = is_accepted
        self._score = score

    def hash(self) -> Hash:
        return self._hash

    def type(self) -> str:
        return "tx"

    def score(self) -> int:
        return self._score

    def is_accepted(self) -> bool:
        return self._is_accepted

    def is_valid(self) -> bool:
        return True


def sort_invs_by_score(invs: List[Inv], targets) -> List[Inv]:
    """Deterministic score-descending inv order.

    The reference *intended* work-descending ordering but the call is commented
    out (`processor.go:163`, `avalanche.go:162-174`), leaving map-iteration
    nondeterminism; tests still assert the ordering (`avalanche_test.go:307-313`).
    We implement the intended behavior: stable sort, score descending, hash
    ascending as tiebreak for full determinism.
    """
    return sorted(invs, key=lambda inv: (-targets[inv.target_hash].score(),
                                         inv.target_hash))
