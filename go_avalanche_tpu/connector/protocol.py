"""Connector wire protocol: length-prefixed little-endian binary frames.

Frame layout (everything little-endian except the length prefix):

    u32be  frame_length            # bytes that follow (type + payload)
    u8     message_type            # MsgType
    ...    payload                 # fixed-width fields, then repeated groups

Scalar field encodings: i64 = '<q', i32 = '<i', u32 count = '<I',
bool/flag = 'B', probability = '<d'.  Repeated groups are a u32 count
followed by count fixed-width records.  Strings (ERROR only) are u32 length +
UTF-8 bytes.

The asymmetry with the reference is deliberate: the reference's seam is Go
interfaces crossed by direct method calls (`main.go:168-193`); ours is a
wire boundary, so `Target` crosses as its scalar attributes (hash /
preference / validity / score) and `StatusUpdate` as (hash, status) pairs —
the same reduction the batched simulator applies (SURVEY.md §7).

This module is the single source of truth for the format; the C++ client
(`native/connector/protocol.h`) mirrors it and the integration test drives
both ends against each other.
"""

from __future__ import annotations

import enum
import socket
import struct
from typing import List, Optional, Sequence, Tuple

MAX_FRAME = 64 * 1024 * 1024  # sanity bound, not a protocol limit

# SIM_INIT v3 model bytes, in wire order (mirrored by the Go client's
# Model* constants and native/connector/protocol.h).  "backlog" (byte 3,
# PR 8) is the streaming working-set scheduler — the live-traffic
# service-mode model; older clients never send it.
SIM_MODELS = ("avalanche", "dag", "streaming_dag", "backlog")

# SIM_INIT v4 arrival-mode bytes, in wire order (go_avalanche_tpu/
# traffic.py; "external" = arrivals pushed via SIM_SUBMIT only).
ARRIVAL_MODES = ("off", "poisson", "bursty", "diurnal", "external")


class MsgType(enum.IntEnum):
    # requests
    PING = 1
    CREATE_NODE = 3        # {node q}
    ADD_TARGET = 4         # {node q, hash q, accepted B, valid B, score q}
    GET_INVS = 5           # {node q}
    QUERY = 6              # {node q, count I, hash q ...}
    REGISTER_VOTES = 7     # {node q, from q, round q, count I, (hash q, err i)..}
    IS_ACCEPTED = 8        # {node q, hash q}
    GET_CONFIDENCE = 9     # {node q, hash q}
    GET_ROUND = 10         # {node q}
    SIM_INIT = 11          # {nodes I, txs I, seed I, k I, fin I, gossip B,
                           #  byz d, drop d}
                           #  + optional v2 tail {strategy B, flip d, churn d}
                           #  (strategy: 0=flip 1=equivocate 2=oppose_majority;
                           #   older clients omit the tail)
                           #  + optional v3 tail {model B, conflict_size I,
                           #  window_sets I} (model: 0=avalanche 1=dag
                           #  2=streaming_dag 3=backlog; window_sets 0 =
                           #  auto — set-slots for streaming_dag, tx
                           #  slots for backlog)
                           #  + optional v4 tail {arrival_mode B,
                           #  arrival_rate d, arrival_period I,
                           #  backpressure_lo d, backpressure_hi d}
                           #  (mode: 0=off 1=poisson 2=bursty 3=diurnal
                           #  4=external; lo == hi == 0 means no
                           #  backpressure; streaming models only)
    SIM_RUN = 12           # {rounds I}
    SIM_SUBMIT = 13        # {count I} — live load generator: `count`
                           #  fresh admission units arrive NOW
                           #  (traffic.push_arrivals); count 0 just
                           #  reads the traffic stats.  Needs a
                           #  streaming model with an arrival mode.
    SHUTDOWN = 16
    # replies
    PONG = 2
    OK = 14                # {flag B}
    I64 = 15               # {value q}
    INVS = 17              # {count I, hash q ...}
    VOTES = 18             # {count I, (hash q, err i) ...}
    UPDATES = 19           # {ok B, count I, (hash q, status b) ...}
    SIM_STATS = 20         # {round I, finalized_frac d, polls q, votes q,
                           #  flips q, finalizations q}
    ERROR = 21             # {len I, utf8 ...}
    SIM_TRAFFIC_STATS = 22  # {arrived q, admitted q, settled q,
                           #  lat_count q, lat_p50 q, lat_p99 q,
                           #  lat_p999 q} — the finality-latency SLO
                           #  view (percentiles -1 while nothing
                           #  settled)


# ------------------------------------------------------------------- framing


def pack_frame(msg_type: int, payload: bytes = b"") -> bytes:
    body = bytes([msg_type]) + payload
    return struct.pack(">I", len(body)) + body


def send_frame(sock: socket.socket, msg_type: int,
               payload: bytes = b"") -> None:
    sock.sendall(pack_frame(msg_type, payload))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    """Read one frame; None on clean EOF."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if not (1 <= length <= MAX_FRAME):
        raise ProtocolError(f"bad frame length {length}")
    body = _recv_exact(sock, length)
    if body is None:
        raise ProtocolError("connection closed mid-frame")
    return body[0], body[1:]


class ProtocolError(RuntimeError):
    pass


# ------------------------------------------------------- payload (de)coding


def pack_i64s(values: Sequence[int]) -> bytes:
    return struct.pack(f"<I{len(values)}q", len(values), *values)


def unpack_i64s(payload: bytes, offset: int = 0) -> Tuple[List[int], int]:
    (count,) = struct.unpack_from("<I", payload, offset)
    offset += 4
    values = list(struct.unpack_from(f"<{count}q", payload, offset))
    return values, offset + 8 * count


def pack_votes(votes: Sequence[Tuple[int, int]]) -> bytes:
    out = [struct.pack("<I", len(votes))]
    for h, err in votes:
        out.append(struct.pack("<qi", h, err))
    return b"".join(out)


def unpack_votes(payload: bytes,
                 offset: int = 0) -> Tuple[List[Tuple[int, int]], int]:
    (count,) = struct.unpack_from("<I", payload, offset)
    offset += 4
    votes = []
    for _ in range(count):
        h, err = struct.unpack_from("<qi", payload, offset)
        votes.append((h, err))
        offset += 12
    return votes, offset


def pack_updates(ok: bool, updates: Sequence[Tuple[int, int]]) -> bytes:
    out = [struct.pack("<BI", 1 if ok else 0, len(updates))]
    for h, status in updates:
        out.append(struct.pack("<qb", h, status))
    return b"".join(out)


def unpack_updates(payload: bytes) -> Tuple[bool, List[Tuple[int, int]]]:
    ok, count = struct.unpack_from("<BI", payload, 0)
    offset = 5
    updates = []
    for _ in range(count):
        h, status = struct.unpack_from("<qb", payload, offset)
        updates.append((h, status))
        offset += 9
    return bool(ok), updates


def pack_error(msg: str) -> bytes:
    raw = msg.encode("utf-8")
    return struct.pack("<I", len(raw)) + raw


def unpack_error(payload: bytes) -> str:
    (n,) = struct.unpack_from("<I", payload, 0)
    return payload[4:4 + n].decode("utf-8", "replace")
