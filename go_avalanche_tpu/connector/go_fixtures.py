"""Golden byte fixtures pinning the Go client to the Python protocol.

No Go toolchain exists in the build environment, so the vendored Go client
(`connector/go/client.go`) is kept honest by golden frames generated HERE —
from `protocol.py`, the format's single source of truth — and replayed by
`connector/go/client_test.go` wherever `go test` can run:

  * request fixtures are the exact frames the Go client must emit for a
    fixed argument set (compared byte-for-byte by the Go test);
  * reply fixtures are server frames the Go client must decode to fixed
    expected values (hard-coded in the Go test, mirrored in
    `tests/test_connector_go.py`).

`python -m go_avalanche_tpu.connector.go_fixtures` (re)writes
`connector/go/testdata/`; `tests/test_connector_go.py` fails if the files
drift from what `protocol.py` generates today.
"""

from __future__ import annotations

import os
import struct
from typing import Dict

from go_avalanche_tpu.connector import protocol as proto

TESTDATA_DIR = os.path.join(os.path.dirname(__file__), "go", "testdata")


def build_fixtures() -> Dict[str, bytes]:
    """name -> full wire frame (length prefix included)."""
    f = {}
    # ---- requests (what the Go client must emit) ----
    f["req_ping"] = proto.pack_frame(proto.MsgType.PING)
    f["req_create_node"] = proto.pack_frame(
        proto.MsgType.CREATE_NODE, struct.pack("<q", 7))
    f["req_add_target"] = proto.pack_frame(
        proto.MsgType.ADD_TARGET, struct.pack("<qqBBq", 7, 65, 1, 1, 99))
    f["req_get_invs"] = proto.pack_frame(
        proto.MsgType.GET_INVS, struct.pack("<q", 7))
    f["req_query"] = proto.pack_frame(
        proto.MsgType.QUERY, struct.pack("<q", 3) + proto.pack_i64s([65, 66]))
    f["req_register_votes"] = proto.pack_frame(
        proto.MsgType.REGISTER_VOTES,
        struct.pack("<qqq", 1, 2, 3) + proto.pack_votes([(65, 0), (66, -1)]))
    f["req_is_accepted"] = proto.pack_frame(
        proto.MsgType.IS_ACCEPTED, struct.pack("<qq", 7, 65))
    f["req_get_confidence"] = proto.pack_frame(
        proto.MsgType.GET_CONFIDENCE, struct.pack("<qq", 7, 66))
    f["req_get_round"] = proto.pack_frame(
        proto.MsgType.GET_ROUND, struct.pack("<q", 7))
    f["req_sim_init_v2"] = proto.pack_frame(
        proto.MsgType.SIM_INIT,
        struct.pack("<IIIIIBdd", 100, 50, 1, 8, 128, 1, 0.2, 0.05)
        + struct.pack("<Bdd", 1, 0.35, 0.01))
    f["req_sim_init_v3"] = proto.pack_frame(
        proto.MsgType.SIM_INIT,
        struct.pack("<IIIIIBdd", 100, 50, 1, 8, 128, 1, 0.2, 0.05)
        + struct.pack("<Bdd", 1, 0.35, 0.01)
        + struct.pack("<BII", 2, 2, 16))
    f["req_sim_run"] = proto.pack_frame(
        proto.MsgType.SIM_RUN, struct.pack("<I", 250))
    f["req_shutdown"] = proto.pack_frame(proto.MsgType.SHUTDOWN)
    # ---- replies (what the Go client must decode) ----
    f["rep_pong"] = proto.pack_frame(proto.MsgType.PONG)
    f["rep_ok_true"] = proto.pack_frame(proto.MsgType.OK,
                                        struct.pack("<B", 1))
    f["rep_invs"] = proto.pack_frame(proto.MsgType.INVS,
                                     proto.pack_i64s([66, 65]))
    f["rep_votes"] = proto.pack_frame(
        proto.MsgType.VOTES, proto.pack_votes([(65, 0), (66, 1), (67, -1)]))
    f["rep_updates"] = proto.pack_frame(
        proto.MsgType.UPDATES, proto.pack_updates(True, [(65, 3), (66, 0)]))
    f["rep_i64_minus1"] = proto.pack_frame(proto.MsgType.I64,
                                           struct.pack("<q", -1))
    f["rep_sim_stats"] = proto.pack_frame(
        proto.MsgType.SIM_STATS,
        struct.pack("<Id4q", 250, 0.875, 1000, 8000, 3, 42))
    f["rep_error"] = proto.pack_frame(proto.MsgType.ERROR,
                                      proto.pack_error("boom"))
    return f


def write_fixtures(directory: str = TESTDATA_DIR) -> None:
    os.makedirs(directory, exist_ok=True)
    for name, frame in build_fixtures().items():
        with open(os.path.join(directory, name + ".bin"), "wb") as fh:
            fh.write(frame)


if __name__ == "__main__":
    write_fixtures()
    print(f"wrote {len(build_fixtures())} fixtures to {TESTDATA_DIR}")
