"""Python Connector client — the reference API surface over the wire.

Method-per-message mirror of the seam the reference example drives in
process (`examples/basic-preconcensus/main.go`); the C++ twin is
`native/connector/client.h`.
"""

from __future__ import annotations

import socket
import struct
from typing import List, NamedTuple, Sequence, Tuple

from go_avalanche_tpu.connector import protocol as proto
from go_avalanche_tpu.config import AdversaryStrategy
from go_avalanche_tpu.types import Status, StatusUpdate


class SimStats(NamedTuple):
    round: int
    finalized_fraction: float
    polls: int
    votes_applied: int
    flips: int
    finalizations: int


class TrafficStats(NamedTuple):
    """SIM_TRAFFIC_STATS: the live-traffic SLO view — cumulative
    arrivals/admissions/settlements plus the in-graph finality-latency
    percentiles (-1 while nothing has settled)."""

    arrived: int
    admitted: int
    settled: int
    lat_count: int
    lat_p50: int
    lat_p99: int
    lat_p999: int


class ConnectorClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 60.0) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ConnectorClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ transport
    def _call(self, msg_type: int, payload: bytes,
              expect: Sequence[int]) -> Tuple[int, bytes]:
        proto.send_frame(self._sock, msg_type, payload)
        frame = proto.recv_frame(self._sock)
        if frame is None:
            raise proto.ProtocolError("server closed connection")
        reply_type, reply = frame
        if reply_type == proto.MsgType.ERROR:
            raise proto.ProtocolError(proto.unpack_error(reply))
        if reply_type not in expect:
            raise proto.ProtocolError(
                f"unexpected reply {reply_type} to {msg_type}")
        return reply_type, reply

    # ------------------------------------------------------------- messages
    def ping(self) -> bool:
        t, _ = self._call(proto.MsgType.PING, b"", [proto.MsgType.PONG])
        return t == proto.MsgType.PONG

    def create_node(self, node_id: int) -> bool:
        _, r = self._call(proto.MsgType.CREATE_NODE,
                          struct.pack("<q", node_id), [proto.MsgType.OK])
        return bool(r[0])

    def add_target(self, node_id: int, target_hash: int, accepted: bool,
                   valid: bool = True, score: int = 1) -> bool:
        _, r = self._call(
            proto.MsgType.ADD_TARGET,
            struct.pack("<qqBBq", node_id, target_hash,
                        1 if accepted else 0, 1 if valid else 0, score),
            [proto.MsgType.OK])
        return bool(r[0])

    def get_invs(self, node_id: int) -> List[int]:
        _, r = self._call(proto.MsgType.GET_INVS, struct.pack("<q", node_id),
                          [proto.MsgType.INVS])
        invs, _ = proto.unpack_i64s(r)
        return invs

    def query(self, node_id: int,
              hashes: Sequence[int]) -> List[Tuple[int, int]]:
        """Poll a peer: it gossip-admits unseen targets and answers one vote
        per inv from its own acceptance state (`main.go:168-193`)."""
        _, r = self._call(proto.MsgType.QUERY,
                          struct.pack("<q", node_id) + proto.pack_i64s(hashes),
                          [proto.MsgType.VOTES])
        votes, _ = proto.unpack_votes(r)
        return votes

    def register_votes(self, node_id: int, from_node: int, round_: int,
                       votes: Sequence[Tuple[int, int]],
                       ) -> Tuple[bool, List[StatusUpdate]]:
        _, r = self._call(
            proto.MsgType.REGISTER_VOTES,
            struct.pack("<qqq", node_id, from_node, round_)
            + proto.pack_votes(votes),
            [proto.MsgType.UPDATES])
        ok, raw = proto.unpack_updates(r)
        return ok, [StatusUpdate(h, Status(s)) for h, s in raw]

    def is_accepted(self, node_id: int, target_hash: int) -> bool:
        _, r = self._call(proto.MsgType.IS_ACCEPTED,
                          struct.pack("<qq", node_id, target_hash),
                          [proto.MsgType.OK])
        return bool(r[0])

    def get_confidence(self, node_id: int, target_hash: int) -> int:
        """-1 for unknown targets (the wire has no exceptions)."""
        _, r = self._call(proto.MsgType.GET_CONFIDENCE,
                          struct.pack("<qq", node_id, target_hash),
                          [proto.MsgType.I64])
        return struct.unpack("<q", r)[0]

    def get_round(self, node_id: int) -> int:
        _, r = self._call(proto.MsgType.GET_ROUND,
                          struct.pack("<q", node_id), [proto.MsgType.I64])
        return struct.unpack("<q", r)[0]

    def sim_init(self, n_nodes: int, n_txs: int, seed: int = 0, k: int = 8,
                 finalization_score: int = 128, gossip: bool = True,
                 byzantine_fraction: float = 0.0,
                 drop_probability: float = 0.0,
                 adversary_strategy: str = "flip",
                 flip_probability: float = 1.0,
                 churn_probability: float = 0.0,
                 model: str = "avalanche",
                 conflict_size: int = 2,
                 window_sets: int = 0,
                 arrival_mode: str = "off",
                 arrival_rate: float = 0.0,
                 arrival_period: int = 0,
                 arrival_backpressure=None) -> bool:
        """(Re)initialize the server-side batched simulator.

        `model` selects the family (v3 tail): "avalanche" (default),
        "dag" (conflict sets of `conflict_size`), "streaming_dag"
        (`window_sets` set-slots; 0 = auto-size to sets/8), or
        "backlog" (`window_sets` tx slots; 0 = auto-size to txs/8).

        The arrival args (v4 tail; streaming models only) turn on the
        live-traffic plane (go_avalanche_tpu/traffic.py): a schedule
        mode with `arrival_rate` offered units/round and optional
        `(lo, hi)` occupancy backpressure, or "external" to feed the
        stream exclusively through `sim_submit` — this client acting
        as the live load generator.
        """
        strategies = [s.value for s in AdversaryStrategy]
        bp = arrival_backpressure or (0.0, 0.0)
        _, r = self._call(
            proto.MsgType.SIM_INIT,
            struct.pack("<IIIIIBdd", n_nodes, n_txs, seed, k,
                        finalization_score, 1 if gossip else 0,
                        byzantine_fraction, drop_probability)
            + struct.pack("<Bdd", strategies.index(adversary_strategy),
                          flip_probability, churn_probability)
            + struct.pack("<BII", proto.SIM_MODELS.index(model), conflict_size,
                          window_sets)
            + struct.pack("<BdIdd",
                          proto.ARRIVAL_MODES.index(arrival_mode),
                          arrival_rate, arrival_period, bp[0], bp[1]),
            [proto.MsgType.OK])
        return bool(r[0])

    def sim_run(self, n_rounds: int) -> SimStats:
        _, r = self._call(proto.MsgType.SIM_RUN,
                          struct.pack("<I", n_rounds),
                          [proto.MsgType.SIM_STATS])
        return SimStats(*struct.unpack("<Id4q", r))

    def sim_submit(self, count: int = 0) -> TrafficStats:
        """Push `count` fresh admission units into the running streaming
        sim (they arrive at the CURRENT round) and read the traffic
        stats; `count=0` just reads.  The live-load-generator seam —
        interleave with `sim_run` to drive a closed loop from outside
        the graph."""
        _, r = self._call(proto.MsgType.SIM_SUBMIT,
                          struct.pack("<I", count),
                          [proto.MsgType.SIM_TRAFFIC_STATS])
        return TrafficStats(*struct.unpack("<7q", r))

    def shutdown_server(self) -> None:
        self._call(proto.MsgType.SHUTDOWN, b"", [proto.MsgType.OK])
