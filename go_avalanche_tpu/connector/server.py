"""Connector server: hosts consensus engines behind the wire boundary.

One server process owns a registry of per-node engines (the native C++
Processor when buildable, else the Python twin) plus, optionally, the
batched TPU simulator.  External harnesses — e.g. the C++ example in
`native/connector/harness_main.cc` — connect and reproduce the reference
example's drive loop (`examples/basic-preconcensus/main.go`) over TCP:

    CREATE_NODE x N                (the per-node Processors, main.go:73-87)
    ADD_TARGET                     (feed txs, main.go:49-53)
    loop: GET_INVS -> QUERY peer -> REGISTER_VOTES     (main.go:110-161)

`QUERY` implements the polled peer's seam (`main.go:168-193`): gossip-on-poll
admission of unseen targets (`main.go:177`, attributes from the shared target
registry, the wire stand-in for the example's global tx list) and a vote per
inv from the peer's own acceptance state (`main.go:179-183`).

Thread model: one thread per connection (ThreadingTCPServer); engines are
internally locked, the registries by `_mu`.  The sim backend initializes JAX
lazily so pure control-plane servers stay light.
"""

from __future__ import annotations

import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig
from go_avalanche_tpu.connector import protocol as proto
from go_avalanche_tpu.connector.protocol import SIM_MODELS
from go_avalanche_tpu.types import Response, Vote

try:
    from go_avalanche_tpu import native as _native
    _native.load_library()
    _HAVE_NATIVE = True
except Exception:  # pragma: no cover - env without g++
    _native = None
    _HAVE_NATIVE = False


class _ScalarTarget:
    """Target adapter for the Python engine (wire targets are scalar)."""

    def __init__(self, hash_: int, accepted: bool, valid: bool,
                 score: int) -> None:
        self._hash, self._accepted = hash_, accepted
        self.valid, self._score = valid, score

    def hash(self) -> int:
        return self._hash

    def type(self) -> str:
        return "wire"

    def is_accepted(self) -> bool:
        return self._accepted

    def is_valid(self) -> bool:
        return self.valid

    def score(self) -> int:
        return self._score


class _PyEngine:
    """Python Processor behind the same scalar API as NativeProcessor."""

    def __init__(self, cfg: AvalancheConfig) -> None:
        from go_avalanche_tpu.net import Connman
        from go_avalanche_tpu.processor import Processor

        self._targets: Dict[int, _ScalarTarget] = {}
        self._p = Processor(Connman(), cfg)

    def add_target_to_reconcile(self, h: int, accepted: bool, valid: bool,
                                score: int) -> bool:
        t = self._targets.setdefault(h, _ScalarTarget(h, accepted, valid,
                                                      score))
        return self._p.add_target_to_reconcile(t)

    def get_invs_for_next_poll(self) -> List[int]:
        return [inv.target_hash for inv in self._p.get_invs_for_next_poll()]

    def register_votes(self, node_id, resp, updates) -> bool:
        return self._p.register_votes(node_id, resp, updates)

    def is_accepted(self, h: int) -> bool:
        t = self._targets.get(h)
        return self._p.is_accepted(t) if t is not None else False

    def get_confidence(self, h: int) -> int:
        t = self._targets.get(h)
        if t is None:
            raise KeyError(h)
        return self._p.get_confidence(t)

    def get_round(self) -> int:
        return self._p.get_round()

    def close(self) -> None:
        pass


class _SimBackend:
    """Lazy wrapper over the batched TPU simulators.

    The v3 SIM_INIT tail selects the model family: plain multi-target
    (`models/avalanche`, the default), conflict DAG (`models/dag`), or
    the streaming conflict-DAG (`models/streaming_dag`).  SIM_STATS'
    `finalized_fraction` generalizes per model: record-finalized fraction,
    (node, set)-resolved fraction, or backlog-settled fraction.
    """

    def __init__(self) -> None:
        # One lock for the whole backend: SIM_INIT/SIM_RUN from different
        # connections must serialize (state/cfg/totals are read-modify-write
        # triples; handler threads are per-connection).
        self._lock = threading.Lock()
        self._state = None
        self._cfg: Optional[AvalancheConfig] = None
        self._model = "avalanche"
        self._totals = [0, 0, 0, 0]  # polls, votes, flips, finalizations

    def init(self, n_nodes: int, n_txs: int, seed: int,
             cfg: AvalancheConfig, model: str = "avalanche",
             conflict_size: int = 2, window_sets: int = 0) -> None:
        import jax
        import jax.numpy as jnp

        if cfg.arrivals_enabled() and model not in ("backlog",
                                                    "streaming_dag"):
            raise proto.ProtocolError(
                f"SIM_INIT: the arrival tail (live traffic) needs a "
                f"streaming model (backlog/streaming_dag), got {model}")
        with self._lock:
            self._cfg = cfg
            self._model = model
            if model == "avalanche":
                from go_avalanche_tpu.models import avalanche as av
                self._state = av.init(jax.random.key(seed), n_nodes, n_txs,
                                      cfg)
            elif model == "dag":
                from go_avalanche_tpu.models import dag
                if n_txs % conflict_size:
                    raise proto.ProtocolError(
                        f"SIM_INIT: txs ({n_txs}) must divide by "
                        f"conflict_size ({conflict_size})")
                cs = jnp.arange(n_txs, dtype=jnp.int32) // conflict_size
                self._state = dag.init(jax.random.key(seed), n_nodes, cs,
                                       cfg)
            elif model == "streaming_dag":
                from go_avalanche_tpu.models import streaming_dag as sdg
                if n_txs % conflict_size:
                    raise proto.ProtocolError(
                        f"SIM_INIT: txs ({n_txs}) must divide by "
                        f"conflict_size ({conflict_size})")
                n_sets = n_txs // conflict_size
                w_sets = window_sets or max(1, n_sets // 8)
                backlog = sdg.make_set_backlog(jnp.arange(
                    n_txs, dtype=jnp.int32).reshape(n_sets, conflict_size))
                self._state = sdg.init(jax.random.key(seed), n_nodes,
                                       w_sets, backlog, cfg)
            elif model == "backlog":
                from go_avalanche_tpu.models import backlog as bl
                slots = window_sets or max(1, n_txs // 8)
                b = bl.make_backlog(jnp.arange(n_txs, dtype=jnp.int32))
                self._state = bl.init(jax.random.key(seed), n_nodes,
                                      slots, b, cfg)
            else:
                raise proto.ProtocolError(f"SIM_INIT: unknown model {model}")
            self._totals = [0, 0, 0, 0]

    def run(self, n_rounds: int) -> Tuple[int, float, List[int]]:
        import jax
        import numpy as np
        from go_avalanche_tpu.ops import voterecord as vr

        with self._lock:
            if self._state is None or self._cfg is None:
                raise proto.ProtocolError("SIM_INIT required before SIM_RUN")
            if self._model == "avalanche":
                from go_avalanche_tpu.models import avalanche as av
                state, tel = jax.jit(
                    av.run_scan, static_argnames=("cfg", "n_rounds"))(
                        self._state, self._cfg, n_rounds)
                rnd = state.round
                fin_frac = float(np.asarray(jax.device_get(
                    vr.has_finalized(state.records.confidence,
                                     self._cfg))).mean())
            elif self._model == "dag":
                from go_avalanche_tpu.models import dag
                state, tel = jax.jit(
                    dag.run_scan, static_argnames=("cfg", "n_rounds"))(
                        self._state, self._cfg, n_rounds)
                rnd = state.base.round
                conf = state.base.records.confidence
                fin_acc = np.asarray(jax.device_get(
                    vr.has_finalized(conf, self._cfg)
                    & vr.is_accepted(conf)))
                c = fin_acc.shape[1] // state.n_sets
                fin_frac = float(
                    (dag.winners_per_set(fin_acc, c) == 1).mean())
            elif self._model == "streaming_dag":
                from go_avalanche_tpu.models import streaming_dag as sdg
                state, stel = jax.jit(
                    sdg.run_scan, static_argnames=("cfg", "n_rounds"))(
                        self._state, self._cfg, n_rounds)
                tel = stel.round
                rnd = state.dag.base.round
                fin_frac = float(np.asarray(jax.device_get(
                    state.outputs.settled)).mean())
            else:  # backlog
                from go_avalanche_tpu.models import backlog as bl
                state, btel = jax.jit(
                    bl.run_scan, static_argnames=("cfg", "n_rounds"))(
                        self._state, self._cfg, n_rounds)
                tel = btel.round
                rnd = state.sim.round
                fin_frac = float(np.asarray(jax.device_get(
                    state.outputs.settled)).mean())
            self._state = state
            sums = [int(np.asarray(jax.device_get(x)).sum())
                    for x in (tel.polls, tel.votes_applied, tel.flips,
                              tel.finalizations)]
            self._totals = [a + b for a, b in zip(self._totals, sums)]
            return int(jax.device_get(rnd)), fin_frac, list(self._totals)

    def submit(self, count: int) -> Tuple[int, ...]:
        """SIM_SUBMIT: the live-load-generator seam — `count` fresh
        admission units arrive NOW (`traffic.push_arrivals`); count 0
        just reads.  Returns the SIM_TRAFFIC_STATS tuple (arrived,
        admitted, settled, lat_count, p50, p99, p999)."""
        import jax
        import numpy as np
        from go_avalanche_tpu import traffic as tf

        with self._lock:
            state = self._state
            if state is None or self._cfg is None:
                raise proto.ProtocolError(
                    "SIM_INIT required before SIM_SUBMIT")
            traffic = getattr(state, "traffic", None)
            if traffic is None:
                raise proto.ProtocolError(
                    "SIM_SUBMIT needs a streaming model with the "
                    "arrival tail (SIM_INIT v4; arrival_mode "
                    "'external' for a pure push-driven stream)")
            round_ = (state.sim.round if self._model == "backlog"
                      else state.dag.base.round)
            if count > 0:
                state = state._replace(
                    traffic=tf.push_arrivals(traffic, count, round_))
                self._state = state
            stats = tf.latency_percentiles(state.traffic)
            # Same units as arrived/admitted: txs for backlog, SETS for
            # streaming_dag (whose outputs.settled is a per-member
            # plane — invalid padding lanes included — scattered row-
            # at-a-time; lat_count already counts valid members only).
            settled_plane = np.asarray(
                jax.device_get(state.outputs.settled))
            settled = int(settled_plane.sum() if self._model == "backlog"
                          else settled_plane.any(axis=1).sum())
            admitted = int(jax.device_get(state.next_idx))
            return (stats["arrived_total"], admitted, settled,
                    stats["finality_latency_count"],
                    stats["finality_latency_p50"],
                    stats["finality_latency_p99"],
                    stats["finality_latency_p999"])


class ConnectorServer:
    """Threaded TCP server exposing the Connector protocol.

    `backend` chooses the engine: "native" (default if buildable), "python".
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 cfg: Optional[AvalancheConfig] = None,
                 backend: Optional[str] = None) -> None:
        self._cfg = cfg if cfg is not None else AvalancheConfig()
        if backend is None:
            backend = "native" if _HAVE_NATIVE else "python"
        if backend == "native" and not _HAVE_NATIVE:
            raise RuntimeError("native backend requested but unavailable")
        self._backend = backend
        self._mu = threading.Lock()
        self._engines: Dict[int, object] = {}
        self._target_attrs: Dict[int, Tuple[bool, bool, int]] = {}
        self._sim = _SimBackend()
        self._shutdown_requested = threading.Event()

        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                while True:
                    try:
                        frame = proto.recv_frame(self.request)
                    except (proto.ProtocolError, OSError):
                        return
                    if frame is None:
                        return
                    msg_type, payload = frame
                    try:
                        reply = outer._dispatch(msg_type, payload)
                    except Exception as e:  # engine errors -> ERROR frame
                        reply = (proto.MsgType.ERROR, proto.pack_error(str(e)))
                    if reply is not None:
                        proto.send_frame(self.request, *reply)
                    if msg_type == proto.MsgType.SHUTDOWN:
                        outer._shutdown_requested.set()
                        return

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address  # type: ignore[return-value]

    def start(self) -> "ConnectorServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join()
        with self._mu:
            # Do NOT close() engines here: daemon handler threads may still
            # be mid-dispatch (shutdown() stops only the accept loop), and
            # freeing a native engine under a live call is a use-after-free.
            # Dropping the references instead lets refcounting destroy each
            # engine once the last in-flight handler releases it.
            self._engines.clear()

    def wait_for_shutdown_request(self, timeout: Optional[float] = None
                                  ) -> bool:
        """Block until a client sent SHUTDOWN (the harness-driven exit)."""
        return self._shutdown_requested.wait(timeout)

    def __enter__(self) -> "ConnectorServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- engines
    def _new_engine(self):
        if self._backend == "native":
            return _native.NativeProcessor(self._cfg)
        return _PyEngine(self._cfg)

    def _engine(self, node_id: int):
        with self._mu:
            engine = self._engines.get(node_id)
            if engine is None:
                raise proto.ProtocolError(f"unknown node {node_id}")
            return engine

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, msg_type: int,
                  payload: bytes) -> Optional[Tuple[int, bytes]]:
        M = proto.MsgType
        if msg_type == M.PING:
            return M.PONG, b""

        if msg_type == M.CREATE_NODE:
            (node_id,) = struct.unpack_from("<q", payload, 0)
            with self._mu:
                created = node_id not in self._engines
                if created:
                    self._engines[node_id] = self._new_engine()
            return M.OK, struct.pack("<B", 1 if created else 0)

        if msg_type == M.ADD_TARGET:
            node_id, h, accepted, valid, score = struct.unpack_from(
                "<qqBBq", payload, 0)
            with self._mu:
                self._target_attrs[h] = (bool(accepted), bool(valid), score)
            ok = self._engine(node_id).add_target_to_reconcile(
                h, bool(accepted), bool(valid), score)
            return M.OK, struct.pack("<B", 1 if ok else 0)

        if msg_type == M.GET_INVS:
            (node_id,) = struct.unpack_from("<q", payload, 0)
            invs = self._engine(node_id).get_invs_for_next_poll()
            return M.INVS, proto.pack_i64s(invs)

        if msg_type == M.QUERY:
            (node_id,) = struct.unpack_from("<q", payload, 0)
            hashes, _ = proto.unpack_i64s(payload, 8)
            engine = self._engine(node_id)
            votes = []
            for h in hashes:
                with self._mu:
                    accepted, valid, score = self._target_attrs.get(
                        h, (True, True, 1))
                engine.add_target_to_reconcile(h, accepted, valid, score)
                votes.append((h, 0 if engine.is_accepted(h) else 1))
            return M.VOTES, proto.pack_votes(votes)

        if msg_type == M.REGISTER_VOTES:
            node_id, from_node, round_ = struct.unpack_from("<qqq", payload, 0)
            votes, _ = proto.unpack_votes(payload, 24)
            resp = Response(round_, 0, [Vote(err, h) for h, err in votes])
            updates: List = []
            ok = self._engine(node_id).register_votes(from_node, resp, updates)
            return M.UPDATES, proto.pack_updates(
                ok, [(u.hash, int(u.status)) for u in updates])

        if msg_type == M.IS_ACCEPTED:
            node_id, h = struct.unpack_from("<qq", payload, 0)
            return M.OK, struct.pack(
                "<B", 1 if self._engine(node_id).is_accepted(h) else 0)

        if msg_type == M.GET_CONFIDENCE:
            node_id, h = struct.unpack_from("<qq", payload, 0)
            try:
                conf = self._engine(node_id).get_confidence(h)
            except KeyError:
                conf = -1
            return M.I64, struct.pack("<q", conf)

        if msg_type == M.GET_ROUND:
            (node_id,) = struct.unpack_from("<q", payload, 0)
            return M.I64, struct.pack("<q", self._engine(node_id).get_round())

        if msg_type == M.SIM_INIT:
            base_len = struct.calcsize("<IIIIIBdd")
            n_nodes, n_txs, seed, k, fin, gossip, byz, drop = \
                struct.unpack_from("<IIIIIBdd", payload, 0)
            extra = {}
            # v2 optional extension (older clients omit it): adversary
            # strategy byte + flip/churn probabilities.
            v2_len = struct.calcsize("<Bdd")
            if len(payload) >= base_len + v2_len:
                strat, flip_p, churn = struct.unpack_from("<Bdd", payload,
                                                          base_len)
                strategies = list(AdversaryStrategy)
                if strat >= len(strategies):
                    raise proto.ProtocolError(
                        f"SIM_INIT adversary strategy byte {strat} out of "
                        f"range (valid: 0..{len(strategies) - 1}: "
                        + ", ".join(f"{i}={s.value}"
                                    for i, s in enumerate(strategies)) + ")")
                extra = dict(
                    adversary_strategy=strategies[strat],
                    flip_probability=flip_p,
                    churn_probability=churn)
            # v3 optional extension: model byte + conflict_size + window
            # set-slots (streaming only; 0 = auto).
            model, conflict_size, window_sets = "avalanche", 2, 0
            v3_len = struct.calcsize("<BII")
            if len(payload) >= base_len + v2_len + v3_len:
                model_b, conflict_size, window_sets = struct.unpack_from(
                    "<BII", payload, base_len + v2_len)
                if model_b >= len(SIM_MODELS):
                    raise proto.ProtocolError(
                        f"SIM_INIT model byte {model_b} out of range "
                        f"(valid: 0..{len(SIM_MODELS) - 1}: "
                        + ", ".join(f"{i}={m}"
                                    for i, m in enumerate(SIM_MODELS)) + ")")
                model = SIM_MODELS[model_b]
            # v4 optional extension: live-traffic arrival tail
            # (streaming models; lo == hi == 0 means no backpressure).
            arrival = {}
            v4_off = base_len + v2_len + v3_len
            if len(payload) >= v4_off + struct.calcsize("<BdIdd"):
                mode_b, rate, period, bp_lo, bp_hi = struct.unpack_from(
                    "<BdIdd", payload, v4_off)
                if mode_b >= len(proto.ARRIVAL_MODES):
                    raise proto.ProtocolError(
                        f"SIM_INIT arrival mode byte {mode_b} out of "
                        f"range (valid: 0.."
                        f"{len(proto.ARRIVAL_MODES) - 1}: "
                        + ", ".join(f"{i}={m}" for i, m in
                                    enumerate(proto.ARRIVAL_MODES))
                        + ")")
                mode = proto.ARRIVAL_MODES[mode_b]
                if mode != "off":
                    arrival = dict(
                        arrival_mode=mode, arrival_rate=rate,
                        arrival_period=period,
                        arrival_backpressure=((bp_lo, bp_hi)
                                              if bp_lo or bp_hi
                                              else None))
            cfg = AvalancheConfig(
                k=k, finalization_score=fin, gossip=bool(gossip),
                byzantine_fraction=byz, drop_probability=drop, **extra,
                **arrival)
            self._sim.init(n_nodes, n_txs, seed, cfg, model=model,
                           conflict_size=conflict_size,
                           window_sets=window_sets)
            return M.OK, struct.pack("<B", 1)

        if msg_type == M.SIM_RUN:
            (rounds,) = struct.unpack_from("<I", payload, 0)
            rnd, fin_frac, totals = self._sim.run(rounds)
            return M.SIM_STATS, struct.pack("<Id4q", rnd, fin_frac, *totals)

        if msg_type == M.SIM_SUBMIT:
            (count,) = struct.unpack_from("<I", payload, 0)
            return (M.SIM_TRAFFIC_STATS,
                    struct.pack("<7q", *self._sim.submit(count)))

        if msg_type == M.SHUTDOWN:
            return M.OK, struct.pack("<B", 1)

        raise proto.ProtocolError(f"unknown message type {msg_type}")
