"""Host Connector boundary — the external-harness plugin seam.

SURVEY.md §2.4 item 6 / §7 phase 7: the reference's `Connman`/`Target` seam
kept as a host-side *service*, so harnesses in any language can drive the
framework the way `examples/basic-preconcensus/main.go` drives the Go
library: create nodes, `AddTargetToReconcile`, fetch polls, `query` peers
(gossip-on-poll included), `RegisterVotes`, observe `StatusUpdate`s — plus
remote control of the batched TPU simulator (init / run / stats).

Wire format: a small length-prefixed binary protocol over TCP
(`protocol.py`), chosen over gRPC so that native clients need nothing but
sockets (`native/connector/` is a complete C++ client).
"""

from go_avalanche_tpu.connector.client import ConnectorClient
from go_avalanche_tpu.connector.server import ConnectorServer

__all__ = ["ConnectorClient", "ConnectorServer"]
