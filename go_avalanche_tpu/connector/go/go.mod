module go-avalanche-tpu/connector

go 1.21
