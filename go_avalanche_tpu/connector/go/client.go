// Package connector is a Go client for the go_avalanche_tpu Connector
// server — the wire form of the seam the reference example drives in
// process (examples/basic-preconcensus/main.go:110-193): CreateNode /
// AddTarget / GetInvs / Query / RegisterVotes per node, plus remote
// control of the batched TPU simulator (SimInit / SimRun).
//
// It mirrors go_avalanche_tpu/connector/client.py and
// native/connector/client.h method-for-method, speaking the v2 frame
// format defined in go_avalanche_tpu/connector/protocol.py (the single
// source of truth):
//
//	u32be frame_length | u8 message_type | little-endian payload
//
// Vendored: this environment has no Go toolchain, so correctness is
// pinned by golden byte fixtures generated from the Python protocol
// module (testdata/*.bin, regenerated+verified by
// tests/test_connector_go.py) and replayed by client_test.go wherever a
// Go toolchain exists.
package connector

import (
	"bufio"
	"bytes"
	"encoding/binary"
	"fmt"
	"io"
	"math"
	"net"
)

// Message types (protocol.py MsgType).
const (
	msgPing          = 1
	msgPong          = 2
	msgCreateNode    = 3
	msgAddTarget     = 4
	msgGetInvs       = 5
	msgQuery         = 6
	msgRegisterVotes = 7
	msgIsAccepted    = 8
	msgGetConfidence = 9
	msgGetRound      = 10
	msgSimInit       = 11
	msgSimRun        = 12
	msgOK            = 14
	msgI64           = 15
	msgShutdown      = 16
	msgInvs          = 17
	msgVotes         = 18
	msgUpdates       = 19
	msgSimStats      = 20
	msgError         = 21
)

const maxFrame = 64 * 1024 * 1024 // sanity bound, matches protocol.py

// Vote is one (hash, err) pair; err semantics follow the reference
// (vote.go:3-22): 0 = yes, 1 = no, -1 = neutral/abstain.
type Vote struct {
	Hash int64
	Err  int32
}

// Update is one (hash, status) pair; status values follow the reference
// Status enum (avalanche.go:44-56).
type Update struct {
	Hash   int64
	Status int8
}

// SimStats is the SIM_RUN reply (protocol.py SIM_STATS).
type SimStats struct {
	Round             uint32
	FinalizedFraction float64
	Polls             int64
	VotesApplied      int64
	Flips             int64
	Finalizations     int64
}

// Adversary strategy bytes for SimInit's v2 tail (config.py
// AdversaryStrategy order).
const (
	AdversaryFlip           = 0
	AdversaryEquivocate     = 1
	AdversaryOpposeMajority = 2
)

// Model bytes for SimInit's v3 tail (server.py SIM_MODELS order).
const (
	ModelAvalanche    = 0
	ModelDag          = 1
	ModelStreamingDag = 2
)

// Client drives one Connector server connection. Not safe for concurrent
// use; open one Client per goroutine (the server is one-thread-per-conn).
type Client struct {
	conn net.Conn
	r    *bufio.Reader
}

// Dial connects to a Connector server at host:port.
func Dial(addr string) (*Client, error) {
	conn, err := net.Dial("tcp", addr)
	if err != nil {
		return nil, err
	}
	if tc, ok := conn.(*net.TCPConn); ok {
		_ = tc.SetNoDelay(true)
	}
	return &Client{conn: conn, r: bufio.NewReader(conn)}, nil
}

// Close tears down the connection.
func (c *Client) Close() error { return c.conn.Close() }

// ---------------------------------------------------------------- framing

// encodeFrame builds one wire frame: u32be length, u8 type, payload.
func encodeFrame(msgType byte, payload []byte) []byte {
	out := make([]byte, 4+1+len(payload))
	binary.BigEndian.PutUint32(out, uint32(1+len(payload)))
	out[4] = msgType
	copy(out[5:], payload)
	return out
}

func (c *Client) call(msgType byte, payload []byte, expect byte) ([]byte, error) {
	if _, err := c.conn.Write(encodeFrame(msgType, payload)); err != nil {
		return nil, err
	}
	var header [4]byte
	if _, err := io.ReadFull(c.r, header[:]); err != nil {
		return nil, err
	}
	length := binary.BigEndian.Uint32(header[:])
	if length < 1 || length > maxFrame {
		return nil, fmt.Errorf("connector: bad frame length %d", length)
	}
	body := make([]byte, length)
	if _, err := io.ReadFull(c.r, body); err != nil {
		return nil, err
	}
	replyType, reply := body[0], body[1:]
	if replyType == msgError {
		return nil, fmt.Errorf("connector: server error: %s", decodeError(reply))
	}
	if replyType != expect {
		return nil, fmt.Errorf("connector: unexpected reply %d to %d",
			replyType, msgType)
	}
	return reply, nil
}

// ------------------------------------------------------- payload encoding
//
// All little-endian, mirroring protocol.py's struct formats.

type wbuf struct{ bytes.Buffer }

func (w *wbuf) u8(v byte) { w.WriteByte(v) }

func (w *wbuf) u32(v uint32) {
	var b [4]byte
	binary.LittleEndian.PutUint32(b[:], v)
	w.Write(b[:])
}

func (w *wbuf) i32(v int32) { w.u32(uint32(v)) }

func (w *wbuf) i64(v int64) {
	var b [8]byte
	binary.LittleEndian.PutUint64(b[:], uint64(v))
	w.Write(b[:])
}

func (w *wbuf) f64(v float64) {
	var b [8]byte
	binary.LittleEndian.PutUint64(b[:], math.Float64bits(v))
	w.Write(b[:])
}

func (w *wbuf) boolByte(v bool) {
	if v {
		w.u8(1)
	} else {
		w.u8(0)
	}
}

func encodeI64s(values []int64) []byte {
	var w wbuf
	w.u32(uint32(len(values)))
	for _, v := range values {
		w.i64(v)
	}
	return w.Bytes()
}

func encodeVotes(votes []Vote) []byte {
	var w wbuf
	w.u32(uint32(len(votes)))
	for _, v := range votes {
		w.i64(v.Hash)
		w.i32(v.Err)
	}
	return w.Bytes()
}

// ------------------------------------------------------- payload decoding

type rbuf struct {
	b   []byte
	off int
	err error
}

func (r *rbuf) need(n int) []byte {
	if r.err != nil {
		return nil
	}
	if r.off+n > len(r.b) {
		r.err = fmt.Errorf("connector: truncated payload (%d+%d > %d)",
			r.off, n, len(r.b))
		return nil
	}
	s := r.b[r.off : r.off+n]
	r.off += n
	return s
}

func (r *rbuf) u8() byte {
	if s := r.need(1); s != nil {
		return s[0]
	}
	return 0
}

func (r *rbuf) u32() uint32 {
	if s := r.need(4); s != nil {
		return binary.LittleEndian.Uint32(s)
	}
	return 0
}

func (r *rbuf) i64() int64 {
	if s := r.need(8); s != nil {
		return int64(binary.LittleEndian.Uint64(s))
	}
	return 0
}

func (r *rbuf) f64() float64 {
	if s := r.need(8); s != nil {
		return math.Float64frombits(binary.LittleEndian.Uint64(s))
	}
	return 0
}

func decodeI64s(payload []byte) ([]int64, error) {
	r := rbuf{b: payload}
	n := r.u32()
	out := make([]int64, 0, n)
	for i := uint32(0); i < n; i++ {
		out = append(out, r.i64())
	}
	return out, r.err
}

func decodeVotes(payload []byte) ([]Vote, error) {
	r := rbuf{b: payload}
	n := r.u32()
	out := make([]Vote, 0, n)
	for i := uint32(0); i < n; i++ {
		h := r.i64()
		e := int32(r.u32())
		out = append(out, Vote{Hash: h, Err: e})
	}
	return out, r.err
}

func decodeUpdates(payload []byte) (bool, []Update, error) {
	r := rbuf{b: payload}
	ok := r.u8() != 0
	n := r.u32()
	out := make([]Update, 0, n)
	for i := uint32(0); i < n; i++ {
		h := r.i64()
		s := int8(r.u8())
		out = append(out, Update{Hash: h, Status: s})
	}
	return ok, out, r.err
}

func decodeSimStats(payload []byte) (SimStats, error) {
	r := rbuf{b: payload}
	st := SimStats{
		Round:             r.u32(),
		FinalizedFraction: r.f64(),
		Polls:             r.i64(),
		VotesApplied:      r.i64(),
		Flips:             r.i64(),
		Finalizations:     r.i64(),
	}
	return st, r.err
}

func decodeError(payload []byte) string {
	r := rbuf{b: payload}
	n := r.u32()
	if s := r.need(int(n)); s != nil {
		return string(s)
	}
	return "<malformed error frame>"
}

// --------------------------------------------------------------- messages

// Ping checks liveness.
func (c *Client) Ping() (bool, error) {
	_, err := c.call(msgPing, nil, msgPong)
	return err == nil, err
}

// CreateNode instantiates a per-node consensus engine on the server
// (the per-node Processor, main.go:73-87).
func (c *Client) CreateNode(nodeID int64) (bool, error) {
	var w wbuf
	w.i64(nodeID)
	r, err := c.call(msgCreateNode, w.Bytes(), msgOK)
	if err != nil {
		return false, err
	}
	return len(r) > 0 && r[0] != 0, nil
}

// AddTarget begins reconciling a target on a node (processor.go:45-58).
func (c *Client) AddTarget(nodeID, hash int64, accepted, valid bool,
	score int64) (bool, error) {
	var w wbuf
	w.i64(nodeID)
	w.i64(hash)
	w.boolByte(accepted)
	w.boolByte(valid)
	w.i64(score)
	r, err := c.call(msgAddTarget, w.Bytes(), msgOK)
	if err != nil {
		return false, err
	}
	return len(r) > 0 && r[0] != 0, nil
}

// GetInvs returns the node's next poll inventory (processor.go:144-170).
func (c *Client) GetInvs(nodeID int64) ([]int64, error) {
	var w wbuf
	w.i64(nodeID)
	r, err := c.call(msgGetInvs, w.Bytes(), msgInvs)
	if err != nil {
		return nil, err
	}
	return decodeI64s(r)
}

// Query polls a peer node: it gossip-admits unseen targets and answers
// one vote per inv from its own acceptance state (main.go:168-193).
func (c *Client) Query(nodeID int64, hashes []int64) ([]Vote, error) {
	var w wbuf
	w.i64(nodeID)
	w.Write(encodeI64s(hashes))
	r, err := c.call(msgQuery, w.Bytes(), msgVotes)
	if err != nil {
		return nil, err
	}
	return decodeVotes(r)
}

// RegisterVotes ingests a peer's response (processor.go:61-122). Returns
// the server's ok flag plus any status updates.
func (c *Client) RegisterVotes(nodeID, fromNode, round int64,
	votes []Vote) (bool, []Update, error) {
	var w wbuf
	w.i64(nodeID)
	w.i64(fromNode)
	w.i64(round)
	w.Write(encodeVotes(votes))
	r, err := c.call(msgRegisterVotes, w.Bytes(), msgUpdates)
	if err != nil {
		return false, nil, err
	}
	return decodeUpdates(r)
}

// IsAccepted reports the node's current preference for a target
// (processor.go:125-130; unknown/finalized-deleted targets are false).
func (c *Client) IsAccepted(nodeID, hash int64) (bool, error) {
	var w wbuf
	w.i64(nodeID)
	w.i64(hash)
	r, err := c.call(msgIsAccepted, w.Bytes(), msgOK)
	if err != nil {
		return false, err
	}
	return len(r) > 0 && r[0] != 0, nil
}

// GetConfidence returns the node's confidence in a target, or -1 if
// unknown (the wire has no exceptions).
func (c *Client) GetConfidence(nodeID, hash int64) (int64, error) {
	var w wbuf
	w.i64(nodeID)
	w.i64(hash)
	r, err := c.call(msgGetConfidence, w.Bytes(), msgI64)
	if err != nil {
		return 0, err
	}
	rr := rbuf{b: r}
	v := rr.i64()
	return v, rr.err
}

// GetRound returns the node's poll round counter.
func (c *Client) GetRound(nodeID int64) (int64, error) {
	var w wbuf
	w.i64(nodeID)
	r, err := c.call(msgGetRound, w.Bytes(), msgI64)
	if err != nil {
		return 0, err
	}
	rr := rbuf{b: r}
	v := rr.i64()
	return v, rr.err
}

// SimInitConfig parameterizes the batched TPU simulator (SIM_INIT v2).
type SimInitConfig struct {
	Nodes             uint32
	Txs               uint32
	Seed              uint32
	K                 uint32
	FinalizationScore uint32
	Gossip            bool
	ByzantineFraction float64
	DropProbability   float64
	// v2 tail (Adversary*: one of the Adversary* constants).
	AdversaryStrategy byte
	FlipProbability   float64
	ChurnProbability  float64
	// v3 tail: model family (one of the Model* constants), conflict-set
	// size (dag/streaming), and streaming window set-slots (0 = auto).
	Model        byte
	ConflictSize uint32
	WindowSets   uint32
}

// SimInit (re)initializes the server-side batched simulator.
func (c *Client) SimInit(cfg SimInitConfig) (bool, error) {
	var w wbuf
	w.u32(cfg.Nodes)
	w.u32(cfg.Txs)
	w.u32(cfg.Seed)
	w.u32(cfg.K)
	w.u32(cfg.FinalizationScore)
	w.boolByte(cfg.Gossip)
	w.f64(cfg.ByzantineFraction)
	w.f64(cfg.DropProbability)
	w.u8(cfg.AdversaryStrategy)
	w.f64(cfg.FlipProbability)
	w.f64(cfg.ChurnProbability)
	w.u8(cfg.Model)
	conflictSize := cfg.ConflictSize
	if conflictSize == 0 {
		conflictSize = 2
	}
	w.u32(conflictSize)
	w.u32(cfg.WindowSets)
	r, err := c.call(msgSimInit, w.Bytes(), msgOK)
	if err != nil {
		return false, err
	}
	return len(r) > 0 && r[0] != 0, nil
}

// SimRun advances the batched simulator n rounds and returns aggregate
// statistics.
func (c *Client) SimRun(rounds uint32) (SimStats, error) {
	var w wbuf
	w.u32(rounds)
	r, err := c.call(msgSimRun, w.Bytes(), msgSimStats)
	if err != nil {
		return SimStats{}, err
	}
	return decodeSimStats(r)
}

// ShutdownServer asks the server to stop accepting work.
func (c *Client) ShutdownServer() error {
	_, err := c.call(msgShutdown, nil, msgOK)
	return err
}
