"""Repo-convention AST linter: the one-spelling rules reviews keep re-fixing.

Four rules, each with a canonical-location table, an allowlist and a
PINNED violation message (tests/test_analysis.py asserts the exact
wording — a drifted message is itself a violation of the one-spelling
idea).  Pure stdlib `ast` — linting never imports jax, so the CLI's
lint subcommand runs anywhere.

  canonical-spelling   `cluster_of` / `tag_from_config` /
                       `suppress_taps` / `draw_churn_swaps` are bound
                       in exactly one module each and imported from
                       there (or a declared re-exporter) only.  Any
                       other def / assignment / import-source is a
                       drifted copy waiting to diverge (the
                       suppress_taps double-emit class).
  config-jax-free      `config.py` validators (`_validate_*`,
                       `__post_init__`) never touch `jax` / `jnp`, and
                       the module never imports jax: AvalancheConfig
                       is a hashable jit-STATIC — validation must not
                       trace.
  host-rng-in-traced   no `np.random` / `random` module use in traced
                       model/ops/parallel code: every draw comes from
                       the jax PRNG key plane (host RNG breaks vmap
                       determinism and the fleet's per-trial key
                       contract).  Host-side control-plane modules
                       (processor/net/connector) are out of scope by
                       construction.
  debug-print          no `jax.debug.print` / `jax.debug.breakpoint`
                       in library modules: telemetry flows through the
                       obs planes (metrics tap / trace plane), never
                       ad-hoc prints in compiled code.
  round-engine-seam    a library module (outside ops/) that pairs a
                       phased exchange call (`gather_vote_packs` /
                       `fused_vote_packs` / `legacy_vote_packs`) with a
                       `register_packed_votes*` ingest call must also
                       reference `round_engine` — dispatching to the
                       whole-round megakernel (`ops/megakernel.py`) or
                       rejecting the knob as inert.  A hand-wired
                       exchange→ingest pair with no seam silently
                       ignores `cfg.round_engine`.

Adding a rule: give it an id + pinned message here, a fixture test in
tests/test_analysis.py (one planted violation, one clean positive),
and a row in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

REPO_ROOT = Path(__file__).resolve().parents[2]

# ---------------------------------------------------------------- rule tables

# name -> the ONE module (repo-relative posix path) allowed to bind it.
CANONICAL_MODULES: Dict[str, str] = {
    "cluster_of": "go_avalanche_tpu/ops/sampling.py",
    "tag_from_config": "go_avalanche_tpu/obs/tags.py",
    "suppress_taps": "go_avalanche_tpu/config.py",
    "draw_churn_swaps": "go_avalanche_tpu/models/node_stream.py",
}

# name -> module paths an `from X import name` may name.  The obs
# package __init__ is the one declared re-exporter: its own import of
# `tag_from_config` is covered by the tags entry below, and importing
# from the package is canonical for everyone else — a DEF or assignment
# of the name there is still a drifted copy and still flags.
ALLOWED_IMPORT_SOURCES: Dict[str, Set[str]] = {
    "cluster_of": {"go_avalanche_tpu.ops.sampling"},
    "tag_from_config": {"go_avalanche_tpu.obs.tags", "go_avalanche_tpu.obs"},
    "suppress_taps": {"go_avalanche_tpu.config"},
    "draw_churn_swaps": {"go_avalanche_tpu.models.node_stream"},
}

# Traced library scope for host-rng-in-traced: directories (prefix
# match) + single files.
TRACED_SCOPE_PREFIXES = (
    "go_avalanche_tpu/models/",
    "go_avalanche_tpu/ops/",
    "go_avalanche_tpu/parallel/",
)
TRACED_SCOPE_FILES = {
    "go_avalanche_tpu/traffic.py",
    "go_avalanche_tpu/stake.py",
    "go_avalanche_tpu/fleet.py",
    "go_avalanche_tpu/obs/trace.py",
}

# Library scope for debug-print: the whole package.
LIBRARY_SCOPE_PREFIX = "go_avalanche_tpu/"

# round-engine-seam: the phased pipeline's two halves.  ops/ itself is
# out of scope — the engines and the megakernel live there.
ROUND_SEAM_OPS_PREFIX = "go_avalanche_tpu/ops/"
ROUND_SEAM_EXCHANGE_CALLS = {"gather_vote_packs", "fused_vote_packs",
                             "legacy_vote_packs"}
ROUND_SEAM_INGEST_PREFIX = "register_packed_votes"

# Per-rule allowlist: rule -> set of repo-relative files exempted.
# Keep empty unless a reviewed exception exists; every entry needs a
# docs/static_analysis.md row saying why.
ALLOWLIST: Dict[str, Set[str]] = {
    "canonical-spelling": set(),
    "config-jax-free": set(),
    "host-rng-in-traced": set(),
    "debug-print": set(),
    "round-engine-seam": set(),
}

_MSG_CANONICAL = ("{name} has ONE spelling — bind/import it from "
                  "{canonical} only (a drifted copy diverges silently; "
                  "docs/static_analysis.md)")
_MSG_CONFIG_JAX = ("config validators must stay jax-free: "
                   "AvalancheConfig is a hashable jit-STATIC and "
                   "validation must never trace (use plain python/math)")
_MSG_HOST_RNG = ("host RNG in traced code: models/ops/parallel draw "
                 "ONLY from the jax PRNG key plane (np.random / the "
                 "random module break vmap determinism and the fleet's "
                 "per-trial key contract)")
_MSG_DEBUG_PRINT = ("jax.debug.{attr} in a library module: telemetry "
                    "flows through the obs planes (metrics tap / trace "
                    "plane), never ad-hoc prints in compiled code")
_MSG_ROUND_SEAM = ("phased exchange+ingest pair without a round-engine "
                   "seam: a module pairing gather_vote_packs with "
                   "register_packed_votes* must dispatch on "
                   "cfg.round_engine or reject it as inert — otherwise "
                   "the whole-round megakernel knob "
                   "(ops/megakernel.py) is silently ignored")


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str      # repo-relative posix path
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _allowed(rule: str, rel: str) -> bool:
    return rel in ALLOWLIST.get(rule, ())


# ------------------------------------------------------------ rule visitors


def _canonical_spelling(tree: ast.AST, rel: str) -> List[Violation]:
    out: List[Violation] = []

    def flag(name: str, line: int) -> None:
        out.append(Violation(rel, line, "canonical-spelling",
                             _MSG_CANONICAL.format(
                                 name=name,
                                 canonical=CANONICAL_MODULES[name])))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if (node.name in CANONICAL_MODULES
                    and rel != CANONICAL_MODULES[node.name]):
                flag(node.name, node.lineno)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                for a in (*args.posonlyargs, *args.args, *args.kwonlyargs,
                          *filter(None, (args.vararg, args.kwarg))):
                    if (a.arg in CANONICAL_MODULES
                            and rel != CANONICAL_MODULES[a.arg]):
                        flag(a.arg, a.lineno)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            if (node.id in CANONICAL_MODULES
                    and rel != CANONICAL_MODULES[node.id]):
                flag(node.id, node.lineno)
        elif isinstance(node, ast.ImportFrom):
            module = node.module or ""
            for alias in node.names:
                bound = alias.asname or alias.name
                if bound not in CANONICAL_MODULES:
                    continue
                name = bound
                if rel == CANONICAL_MODULES[name]:
                    continue
                ok_sources = ALLOWED_IMPORT_SOURCES.get(name, set())
                renamed = alias.asname is not None and alias.name != name
                if renamed or module not in ok_sources:
                    flag(name, node.lineno)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if (alias.asname in CANONICAL_MODULES
                        and rel != CANONICAL_MODULES[alias.asname]):
                    flag(alias.asname, node.lineno)
    return out


def _config_jax_free(tree: ast.AST, rel: str) -> List[Violation]:
    if rel != "go_avalanche_tpu/config.py":
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax" or alias.name.startswith("jax."):
                    out.append(Violation(rel, node.lineno,
                                         "config-jax-free",
                                         _MSG_CONFIG_JAX))
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "jax":
                out.append(Violation(rel, node.lineno, "config-jax-free",
                                     _MSG_CONFIG_JAX))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if not (node.name.startswith("_validate")
                    or node.name == "__post_init__"):
                continue
            for inner in ast.walk(node):
                if (isinstance(inner, ast.Name)
                        and isinstance(inner.ctx, ast.Load)
                        and inner.id in ("jax", "jnp")):
                    out.append(Violation(rel, inner.lineno,
                                         "config-jax-free",
                                         _MSG_CONFIG_JAX))
    return out


def _in_traced_scope(rel: str) -> bool:
    return (rel in TRACED_SCOPE_FILES
            or any(rel.startswith(p) for p in TRACED_SCOPE_PREFIXES))


def _host_rng_in_traced(tree: ast.AST, rel: str) -> List[Violation]:
    if not _in_traced_scope(rel):
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "random":
                    out.append(Violation(rel, node.lineno,
                                         "host-rng-in-traced",
                                         _MSG_HOST_RNG))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random":
                out.append(Violation(rel, node.lineno,
                                     "host-rng-in-traced", _MSG_HOST_RNG))
        elif isinstance(node, ast.Attribute):
            if (node.attr == "random" and isinstance(node.value, ast.Name)
                    and node.value.id in ("np", "numpy")):
                out.append(Violation(rel, node.lineno,
                                     "host-rng-in-traced", _MSG_HOST_RNG))
    return out


def _debug_print(tree: ast.AST, rel: str) -> List[Violation]:
    if not rel.startswith(LIBRARY_SCOPE_PREFIX):
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and node.attr in ("print", "breakpoint")
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "debug"
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "jax"):
            out.append(Violation(
                rel, node.lineno, "debug-print",
                _MSG_DEBUG_PRINT.format(attr=node.attr)))
    return out


def _round_engine_seam(tree: ast.AST, rel: str) -> List[Violation]:
    if not rel.startswith(LIBRARY_SCOPE_PREFIX):
        return []
    if rel.startswith(ROUND_SEAM_OPS_PREFIX):
        return []
    exchange_line = ingest_line = None
    has_seam = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in ROUND_SEAM_EXCHANGE_CALLS:
                if exchange_line is None:
                    exchange_line = node.lineno
            elif (name and name.startswith(ROUND_SEAM_INGEST_PREFIX)
                    and ingest_line is None):
                ingest_line = node.lineno
        # The seam is any `round_engine` touch: the cfg attribute, a
        # dispatch variable, or a `_reject_round_engine`-style guard.
        if ((isinstance(node, ast.Attribute)
                and "round_engine" in node.attr)
                or (isinstance(node, ast.Name)
                    and "round_engine" in node.id)):
            has_seam = True
    if exchange_line is not None and ingest_line is not None \
            and not has_seam:
        return [Violation(rel, max(exchange_line, ingest_line),
                          "round-engine-seam", _MSG_ROUND_SEAM)]
    return []


_RULES = (
    ("canonical-spelling", _canonical_spelling),
    ("config-jax-free", _config_jax_free),
    ("host-rng-in-traced", _host_rng_in_traced),
    ("debug-print", _debug_print),
    ("round-engine-seam", _round_engine_seam),
)

RULE_IDS = tuple(rule for rule, _ in _RULES)


# ----------------------------------------------------------------- drivers


def lint_source(src: str, rel: str) -> List[Violation]:
    """Lint one file's SOURCE under its repo-relative posix path —
    the unit tests' fixture entry point."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "parse-error",
                          f"file does not parse: {e.msg}")]
    out: List[Violation] = []
    for rule, fn in _RULES:
        if _allowed(rule, rel):
            continue
        out.extend(fn(tree, rel))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


_SKIP_DIRS = {".git", "__pycache__", ".claude", ".pytest_cache",
              "node_modules", ".venv", "venv", ".tox", ".eggs",
              "build", "dist", "site-packages"}


def _require_checkout(root: Path) -> Path:
    """Refuse to treat a non-checkout directory as the repo: from an
    installed wheel, ``parents[2]`` is site-packages, and rglobbing
    every installed distribution would both take minutes and flag
    third-party files under OUR conventions."""
    if (root / "pyproject.toml").exists() \
            and (root / "go_avalanche_tpu").is_dir():
        return root
    raise RuntimeError(
        f"{root} is not a go-avalanche-tpu source checkout (no "
        f"pyproject.toml + go_avalanche_tpu/ side by side) — the "
        f"repo-convention linter needs the repo; run it from the "
        f"checkout or pass lint_repo(root=...)")


def repo_py_files(root: Path = REPO_ROOT) -> Iterable[Path]:
    for path in sorted(root.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in path.parts):
            continue
        yield path


def lint_repo(root: Optional[Path] = None) -> List[Violation]:
    """Lint every .py file in the repo; [] means lint-clean.  Raises
    `RuntimeError` when no source checkout is findable (installed-wheel
    runs must pass `root` explicitly)."""
    root = _require_checkout(root or REPO_ROOT)
    out: List[Violation] = []
    for path in repo_py_files(root):
        rel = path.relative_to(root).as_posix()
        out.extend(lint_source(path.read_text(), rel))
    return out
