"""`python -m go_avalanche_tpu.analysis` — the static-analysis CLI.

Subcommands (default ``all``):

  audit   contract-audit every archived pin (callbacks / dtype budget /
          collectives / donation), the off-path re-lowerings, the five
          sharded drivers on the 2x2 audit mesh, and the compile-level
          ``input_output_alias`` donation proof for the flagship, the
          fleet, the traffic program and every sharded driver;
  lint    the repo-convention AST linter (jax-free — runs anywhere);
  all     both.

Exit status 1 on any failure, 0 clean; one line per failure on stderr
(the hlo_pin.py convention).  Also installed as the ``avalanche-audit``
console script (pyproject.toml).

Environment: like tests/conftest.py, the audit runs on the CPU backend
with 8 virtual XLA devices so the sharded-driver mesh exists without
hardware; set ``GO_AVALANCHE_TPU_ANALYSIS_HW=1`` to audit on the real
accelerator instead (platform-specific custom calls differ, which is
the point of a hardware audit).
"""

from __future__ import annotations

import argparse
import os
import sys


def _ensure_devices() -> None:
    """Mirror tests/conftest.py: a virtual 8-device CPU mesh, forced
    AFTER the jax import because the container's axon plugin overrides
    JAX_PLATFORMS at interpreter start (see conftest.py's NOTE)."""
    if os.environ.get("GO_AVALANCHE_TPU_ANALYSIS_HW"):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


# The acceptance set for the compile-level donation proof: the flagship
# program, the fleet program and the traffic program (every sharded
# driver is proven separately on the audit mesh).
DONATION_COMPILE_PROGRAMS = ("flagship", "fleet_small",
                             "flagship_traffic")


def run_audit(compile_donation: bool = True) -> list:
    """Every lowered-program contract, as one failure list."""
    import jax

    from benchmarks import hlo_pin
    from go_avalanche_tpu.analysis import hlo_audit

    failures = []
    archive = hlo_pin._load_archive()
    platform = jax.default_backend()
    failures += hlo_audit.audit_all_pinned(archive)
    failures += hlo_audit.audit_off_path(platform, archive)
    try:
        # One pass over the five drivers; compile_donation rides along
        # so nothing is lowered (or reported) twice.  The sharded-fleet
        # pair (driver + bench scan, parallel/sharded_fleet.py) audits
        # on its own 2x2 trials-mesh alongside.
        failures += hlo_audit.audit_all_sharded(
            compile_donation=compile_donation)
        failures += hlo_audit.audit_sharded_fleet(
            compile_donation=compile_donation)
    except hlo_audit.AuditUnavailable as e:
        failures.append(f"sharded audit unavailable: {e}")
    if compile_donation:
        for name in DONATION_COMPILE_PROGRAMS:
            failures += hlo_audit.audit_donation_compiled(name)
        # The resource plane's byte-level twin (obs/resources.py): the
        # analytic state footprint must account for each lane's
        # compiled memory_analysis() — argument/output/alias bytes, not
        # just the alias-table leaf count.
        for name in hlo_audit.MEMORY_BUDGET_PROGRAMS:
            failures += hlo_audit.audit_memory_budget(name)
    return failures


def run_lint() -> list:
    from go_avalanche_tpu.analysis import lint

    try:
        return [str(v) for v in lint.lint_repo()]
    except RuntimeError as e:
        # Installed-wheel invocation with no checkout in sight: an
        # explicit failure line beats linting all of site-packages.
        return [f"lint unavailable: {e}"]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m go_avalanche_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("command", nargs="?", default="all",
                        choices=("audit", "lint", "all"),
                        help="which surface to run (default: all)")
    parser.add_argument("--no-compile-donation", action="store_true",
                        help="skip the compile-level input_output_alias "
                             "proof (lowering-level donation attrs are "
                             "still checked); the compile pass costs a "
                             "few seconds of XLA time at toy shapes")
    args = parser.parse_args(argv)

    failures = []
    if args.command in ("lint", "all"):
        failures += run_lint()
    if args.command in ("audit", "all"):
        _ensure_devices()
        failures += run_audit(
            compile_donation=not args.no_compile_donation)

    if failures:
        print("STATIC ANALYSIS FAILURES:\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 1
    what = {"audit": "contract audit", "lint": "lint",
            "all": "contract audit + lint"}[args.command]
    print(f"ok: {what} clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
