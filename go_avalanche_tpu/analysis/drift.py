"""Op-class histograms: the drift EXPLAINER behind `hlo_pin --explain`.

A pin mismatch used to print two sha256 digests — true, and useless for
deciding whether the drift was the intended one.  This module archives a
per-program histogram of StableHLO op classes next to each pin hash
(`benchmarks/hlo_pin.json`, schema bump carried backward-compatibly:
entries without a ``histograms`` key still read fine), and on mismatch
`hlo_pin.py --explain` diffs the archived histogram against the current
lowering and names the op classes that appeared, vanished or changed
count.

The histogram is computed from the SAME location-stripped text the hash
covers (`hlo_pin.strip_locations`), so the two artifacts can never
describe different programs.  Op classes:

  * ``stablehlo.<op>``         — one class per StableHLO op name;
  * ``custom_call:<target>``   — custom calls split out by target (the
                                 class that distinguishes "a callback
                                 appeared" from "a Sharding annotation
                                 moved");
  * ``<dialect>.<op>``         — any non-stablehlo dialect op (func /
                                 mhlo / chlo), counted by full name.

Two same-hash programs have identical histograms by construction; two
different-hash programs with IDENTICAL histograms are the "shape or
constant moved, structure did not" case — `diff_histograms` reports
that explicitly rather than returning an empty diff.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, List

# One op instance per SSA statement: `= stablehlo.add`, `= func.call`,
# `= "stablehlo.all_gather"(...)` (region-bearing / generic-form ops
# print quoted).  Ops that produce no results (stablehlo.return,
# func.return) appear without `=` and are matched by the bare form.
_OP_RE = re.compile(
    r'(?:^|\s)"?([a-z_]+\.[a-z_0-9]+)"?[ (]')
_CUSTOM_TARGET_RE = re.compile(r'custom_call\s*@([\w.$]+)')


def op_histogram(hlo_text: str) -> Dict[str, int]:
    """Histogram of op classes in (location-stripped) StableHLO text.

    Returns a plain ``{class: count}`` dict (JSON-ready, sorted on
    write by the archive's ``sort_keys``).  `custom_call` instances are
    classified by target; everything else by ``dialect.op`` name.
    """
    hist: Counter = Counter()
    for line in hlo_text.splitlines():
        targets = _CUSTOM_TARGET_RE.findall(line)
        if targets:
            for t in targets:
                hist[f"custom_call:{t}"] += 1
            continue
        m = _OP_RE.search(line)
        if m:
            hist[m.group(1)] += 1
    return dict(hist)


def diff_histograms(archived: Dict[str, int],
                    current: Dict[str, int]) -> List[str]:
    """Name the op classes whose counts differ, archived -> current.

    One line per differing class, vanished/appeared called out, sorted
    by |count delta| descending then name (the biggest structural move
    first — usually the one-line answer to "what drifted").  Equal
    histograms return the explicit shape-or-constant note instead of
    [] so `--explain` never prints nothing on a real hash mismatch.
    """
    classes = sorted(set(archived) | set(current))
    rows = []
    for cls in classes:
        a, c = archived.get(cls, 0), current.get(cls, 0)
        if a == c:
            continue
        if a == 0:
            note = "APPEARED"
        elif c == 0:
            note = "VANISHED"
        else:
            note = f"{c - a:+d}"
        rows.append((abs(c - a), cls, f"{cls}: {a} -> {c} ({note})"))
    if not rows:
        return ["op histograms are identical: the drift is in shapes, "
                "constants or operand wiring, not op structure "
                "(diff the lowered text directly)"]
    rows.sort(key=lambda r: (-r[0], r[1]))
    return [r[2] for r in rows]
