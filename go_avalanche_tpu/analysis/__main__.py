"""`python -m go_avalanche_tpu.analysis` entry point (see cli.py)."""

from go_avalanche_tpu.analysis.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
