"""Retrace guard: a compile-cache counter for one-compile claims.

Two of the repo's standing performance claims are COMPILE-COUNT claims,
and until now neither was machine-checked:

  * `bench.py`'s timed loop assumes the warmup call compiled everything
    — a recompile inside the measured repeats (donation changing a
    layout, a shape leaking into a static) would silently time XLA's
    compiler instead of the program;
  * `fleet.run_phase_grid`'s "one compile per config point" (the PR 7
    dispatch-amortization premise): a config field accidentally turned
    traced-to-static-hash-unstable would re-trace the whole fleet
    program per point without changing a single result.

`CompileCounter` counts backend compiles via `jax.monitoring`'s
``/jax/core/compile/backend_compile_duration`` event — fired once per
actual XLA compile, never on a cache hit (verified by
tests/test_analysis.py).  The listener is registered once per process
and only ever increments an integer, so leaving it installed costs
nothing; counters snapshot it.

    with retrace.CompileCounter() as c:
        timed_loop()
    c.expect_at_most(0, "the bench timed loop")   # raises RetraceError
"""

from __future__ import annotations

_COMPILE_EVENT_FRAGMENT = "backend_compile"

_compiles = 0
_listener_installed = False


class RetraceError(RuntimeError):
    """A compiled-program cache was violated: something (re)compiled
    where the surrounding claim says nothing may."""


def _install_listener() -> None:
    """Register the process-wide compile-event listener (idempotent).

    Deferred to first CompileCounter use so importing the analysis
    package never imports jax (the lint CLI must run jax-free)."""
    global _listener_installed
    if _listener_installed:
        return
    import jax.monitoring

    def _on_event_duration(name: str, *args, **kwargs) -> None:
        global _compiles
        if _COMPILE_EVENT_FRAGMENT in name:
            _compiles += 1

    jax.monitoring.register_event_duration_secs_listener(
        _on_event_duration)
    _listener_installed = True


class CompileCounter:
    """Context manager counting XLA backend compiles inside its scope.

    The count FREEZES at scope exit — jitted work after the with-block
    (a decode pass, a report step) never contaminates the guarded
    measurement."""

    def __enter__(self) -> "CompileCounter":
        _install_listener()
        self._start = _compiles
        self._end = None
        return self

    def __exit__(self, *exc) -> None:
        self._end = _compiles

    @property
    def count(self) -> int:
        end = self._end if self._end is not None else _compiles
        return end - self._start

    def expect_at_most(self, n: int, what: str) -> None:
        """Raise `RetraceError` if more than `n` compiles happened in
        scope — with the count, so the failure names its magnitude."""
        if self.count > n:
            raise RetraceError(
                f"{what} compiled {self.count} program(s) where at most "
                f"{n} is allowed — a static argument is unstable or a "
                f"shape/layout leaked into the cache key (the "
                f"one-compile contract, go_avalanche_tpu/analysis/"
                f"retrace.py)")


def guard_fleet_point(misses_before: int, misses_after: int,
                      point) -> None:
    """The phase-grid guard: one config point may TRACE the fleet
    program at most once (`fleet._compiled_fleet` is lru-cached — a
    repeated point legitimately costs zero).  More than one cache miss
    for a single point means the jit-static config hashed unstably and
    the sweep is recompiling per call, the exact regression the PR 7
    one-compile-per-config-point claim forbids."""
    misses = misses_after - misses_before
    if misses > 1:
        raise RetraceError(
            f"phase point {point!r} traced the fleet program {misses} "
            f"times (expected at most 1): the config is not a stable "
            f"jit-static cache key — one compile per config point is "
            f"the fleet's dispatch-amortization contract "
            f"(go_avalanche_tpu/fleet.py, PR 7)")
