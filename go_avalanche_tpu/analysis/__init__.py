"""Program-plane static analysis (PR 12).

The repo's compiled-program hygiene was hash-equality only
(`benchmarks/hlo_pin.py`): a pin mismatch said *something* drifted but
never *what*, and the contracts the codebase actually depends on were
enforced by byte-identity or not at all.  This package turns them into
machine-checked invariants over the lowered/compiled programs:

  * `hlo_audit`  — the HLO contract auditor: per-program custom-call
                   allowlists (off-path programs contain ZERO host
                   callbacks), the dtype budget (no f64 / no shaped-i64
                   anywhere), per-sharded-driver collective allowlists
                   (psum on declared axes only; an accidental
                   all-gather of an ``[N, T]`` plane is a hard
                   failure), and the donation audit (every donated
                   state leaf must alias an output — lowered
                   `tf.aliasing_output` / `jax.buffer_donor` coverage
                   at the archived shape, compiled
                   ``input_output_alias`` coverage at audit shape);
  * `drift`      — op-class histograms archived next to each pin hash
                   (`hlo_pin.py --explain` names the op classes that
                   appeared/vanished instead of printing two hashes);
  * `retrace`    — the compile-cache counter: `bench.py`'s timed loop
                   asserts ZERO recompiles inside the measurement and
                   `fleet.run_phase_grid` asserts at most one fleet
                   compile per config point;
  * `lint`       — the repo-convention AST linter: canonical-module
                   spellings (`cluster_of` / `tag_from_config` /
                   `suppress_taps` / `draw_churn_swaps`), a jax-free
                   `config.py` validation plane, no host RNG in traced
                   model/ops code, no `jax.debug.print` in library
                   modules.

CLI: ``python -m go_avalanche_tpu.analysis`` (see `cli.py`); everything
also runs in tier-1 (`tests/test_analysis.py`) — lowering is
`eval_shape`-cheap per the hlo_pin precedent.  docs/static_analysis.md
holds the contract table and the how-to-add-a-rule guide.
"""

from go_avalanche_tpu.analysis.retrace import (  # noqa: F401
    CompileCounter,
    RetraceError,
)
