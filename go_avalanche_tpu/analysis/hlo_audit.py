"""HLO contract auditor: semantic assertions over lowered/compiled programs.

`benchmarks/hlo_pin.py` pins byte-identity; this module pins MEANING.
Every program in the pin registry (plus the five sharded drivers and
the program a `run_sim` invocation selects) is statically audited for
the contracts the codebase actually depends on:

  * **custom-call allowlist** — off-path programs contain ZERO host
    callbacks (`xla_python_*callback` custom calls); the tapped
    program (`flagship_metrics`) contains exactly its one io_callback.
    Upgrades `hlo_pin --verify-off-path` from hash equality to a
    semantic assertion.
  * **dtype budget** — no f64 and no SHAPED i64/ui64 tensor anywhere
    (the engines are u8/u16/i32/f32 by design; a silent x64 promotion
    doubles every plane's HBM traffic).  The one sanctioned i64 is the
    SCALAR callback-pointer constant inside callback-allowed programs.
  * **collective allowlist** — single-chip programs carry zero
    collectives; each sharded driver's lowered program must contain
    exactly its `DECLARED_COLLECTIVES` (collective kind x mesh axes,
    inferred from replica_groups), and every `all_gather` result must
    stay strictly smaller than the unpacked ``[N, T]`` plane — the
    accidental-gather-of-a-plane hard failure.
  * **donation audit** — for every donated program, each flat state
    leaf must reach the entry signature as a donated argument
    (`tf.aliasing_output` under plain jit, `jax.buffer_donor` under
    shard_map — JAX silently un-donates on shape/dtype mismatch, which
    is exactly what this catches), and a small-shape COMPILE must show
    ``input_output_alias`` covering every argument.  This is the
    static answer to the ROADMAP's donation-under-vmap soak follow-up,
    fleet program included.

All checks are text-level over the same location-stripped StableHLO the
pins hash (plus optimized-HLO text for the compile-level donation
proof), so the audit is `eval_shape`-cheap and runs in tier-1
(tests/test_analysis.py) and via `python -m go_avalanche_tpu.analysis`.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

# ------------------------------------------------------------ text parsing

_CALLBACK_TARGET_RE = re.compile(r"^xla(?:_ffi)?_python_[a-z_]*callback$")
_CUSTOM_TARGET_RE = re.compile(r'custom_call\s*@([\w.$]+)')
COLLECTIVE_KINDS = ("all_reduce", "all_gather", "all_to_all",
                    "collective_permute", "reduce_scatter",
                    "collective_broadcast")
_COLLECTIVE_RE = re.compile(
    r'"?stablehlo\.(' + "|".join(COLLECTIVE_KINDS) + r')"?[ (]')
_REPLICA_GROUPS_RE = re.compile(r'replica_groups\s*=\s*dense<([^>]*)>')
_TENSOR_TYPE_RE = re.compile(r'tensor<([^>]*)>')
_MAIN_SIG_RE = re.compile(
    r'func\.func public @main\((.*?)\)\s*->', re.DOTALL)
_RESULT_TYPE_RE = re.compile(r'->\s*tensor<([^>]*)>')

# Custom-call targets that are lowering plumbing, not program semantics
# (sharding annotations, SPMD shape bridges, platform PRNG FFI).
BENIGN_CUSTOM_CALLS = frozenset({
    "Sharding", "SPMDShardToFullShape", "SPMDFullToShardShape",
    "cu_threefry2x32", "cu_threefry2x32_ffi",
})


def custom_call_targets(text: str) -> Counter:
    """All custom-call targets in the program, with counts."""
    return Counter(_CUSTOM_TARGET_RE.findall(text))


def callback_calls(text: str) -> int:
    """Number of host-callback custom calls (io_callback / pure_callback
    / debug prints all lower to `xla*_python_*callback` targets)."""
    return sum(n for t, n in custom_call_targets(text).items()
               if _CALLBACK_TARGET_RE.match(t))


def unknown_custom_calls(text: str) -> List[str]:
    """Custom-call targets that are neither benign plumbing nor python
    callbacks — anything here is a new dependency the contract table
    must name explicitly before it ships."""
    return sorted(t for t in custom_call_targets(text)
                  if t not in BENIGN_CUSTOM_CALLS
                  and not _CALLBACK_TARGET_RE.match(t))


# Structural attributes whose payload types are metadata, not program
# values (replica group tables, layouts) — their i64 spelling is MLIR's,
# not the program's.
_ATTR_CONTEXT = ("replica_groups", "source_target_pairs",
                 "operand_layouts", "result_layouts", "layout =",
                 "dimension_numbers", "scatter_dimension_numbers",
                 "gather_dimension_numbers")


def dtype_violations(text: str, scalar_i64_ok: bool = False) -> List[str]:
    """Every f64 / shaped-i64 / shaped-ui64 tensor TYPE in the program.

    `scalar_i64_ok` permits the bare ``tensor<i64>`` scalar (the python
    callback's process pointer constant) — only meaningful for
    programs whose contract allows callbacks."""
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        for m in _TENSOR_TYPE_RE.finditer(line):
            ty = m.group(1)
            if not ("f64" in ty or "i64" in ty):
                continue
            prefix = line[:m.start()]
            if any(a in prefix for a in _ATTR_CONTEXT):
                continue
            # A `dense<...> : tensor<...>` payload that is NOT a
            # stablehlo.constant is op metadata (reduce_window padding,
            # replica group tables, ...), spelled i64 by MLIR itself —
            # only constants carry program values through dense<>.
            if "dense<" in prefix and "stablehlo.constant" not in line:
                continue
            if ty == "i64" and scalar_i64_ok:
                continue
            out.append(f"line {lineno}: tensor<{ty}> — the dtype budget "
                       f"forbids f64/s64 (u8/u16/i32/f32 engines; x64 "
                       f"promotion doubles HBM traffic)")
    return out


def parse_replica_groups(line: str) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """The replica_groups attribute on a collective's op line, as a
    tuple of device-id groups (None when the op carries none)."""
    m = _REPLICA_GROUPS_RE.search(line)
    if not m:
        return None
    body = m.group(1).strip()
    if not body.startswith("["):
        body = f"[[{body}]]"
    elif not body.startswith("[["):
        body = f"[{body}]"
    import json

    groups = json.loads(body.replace(" ", "").replace("],[", "], ["))
    return tuple(tuple(int(d) for d in g) for g in groups)


def collective_instances(text: str) -> List[Dict]:
    """Every collective op instance: kind, replica groups (if printed on
    the op line) and — for single-line ops like all_gather — the result
    tensor's element count."""
    out = []
    for lineno, line in enumerate(text.splitlines(), 1):
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        groups = parse_replica_groups(line)
        elems = None
        rm = _RESULT_TYPE_RE.search(line)
        if rm:
            # `16x16xui8` -> dims [16, 16] (the last x-component is the
            # element type; a bare `tensor<ui8>` scalar has no dims).
            parts = rm.group(1).split("x")
            dims = parts[:-1]
            if all(d.isdigit() for d in dims):
                elems = 1
                for d in dims:
                    elems *= int(d)
        out.append({"kind": kind, "groups": groups, "elems": elems,
                    "line": lineno})
    return out


def axis_groupings(mesh_axes: Sequence[Tuple[str, int]]
                   ) -> Dict[FrozenSet[FrozenSet[int]], Tuple[str, ...]]:
    """Map every possible replica-group partition of a row-major device
    grid to the mesh-axis subset it reduces over.

    `mesh_axes` is the ordered ``[(axis_name, size), ...]`` of the
    audit mesh; device ids are row-major over that order (how
    `parallel/mesh.make_mesh` lays its grid out).  Covers every
    non-empty axis subset, so an observed grouping that matches nothing
    is by construction NOT a reduction over declared mesh axes.

    On a mesh with a size-1 axis, distinct subsets collapse to the
    SAME partition (reducing over a trivial axis is a no-op); the
    iteration goes largest-subset first so the SMALLEST subset wins —
    a collective on a degenerate mesh attributes to the minimal axis
    set, never to a phantom extra axis.
    """
    import itertools

    names = [n for n, _ in mesh_axes]
    table: Dict[FrozenSet[FrozenSet[int]], Tuple[str, ...]] = {}
    for r in range(len(names), 0, -1):
        for subset in itertools.combinations(names, r):
            table[_partition_for_axes(mesh_axes, subset)] = subset
    return table


def _partition_for_axes(mesh_axes: Sequence[Tuple[str, int]],
                        axes: Tuple[str, ...]
                        ) -> FrozenSet[FrozenSet[int]]:
    """The replica-group partition a reduction over `axes` produces on
    a row-major device grid — the ONE spelling of the grid layout,
    shared by `axis_groupings` and `declared_partitions`."""
    import itertools

    names = [n for n, _ in mesh_axes]
    sizes = [s for _, s in mesh_axes]
    idx = {names.index(a) for a in axes if a in names}
    groups: Dict[Tuple, List[int]] = {}
    for coord in itertools.product(*[range(s) for s in sizes]):
        dev = 0
        for c, s in zip(coord, sizes):
            dev = dev * s + c
        key = tuple(c for i, c in enumerate(coord) if i not in idx)
        groups.setdefault(key, []).append(dev)
    return frozenset(frozenset(g) for g in groups.values())


def declared_partitions(declared: FrozenSet,
                        mesh_axes: Sequence[Tuple[str, int]]
                        ) -> Dict[str, set]:
    """kind -> the replica-group partitions the declared (kind, axes)
    pairs produce ON THIS MESH.

    The mesh-robust form of the allowlist: on a degenerate mesh two
    declared axis sets can yield the same partition — coverage compares
    partitions directly, so `run_sim --audit --mesh 4,1` never
    false-fails on axis-attribution ambiguity."""
    out: Dict[str, set] = {}
    for kind, axes in declared:
        out.setdefault(kind, set()).add(
            _partition_for_axes(mesh_axes, axes))
    return out


def collective_coverage_failures(text: str, declared: FrozenSet,
                                 mesh_axes: Sequence[Tuple[str, int]],
                                 what: str) -> List[str]:
    """Partition-based allowlist check for an ARBITRARY mesh: every
    collective instance's replica grouping must equal some declared
    (kind, axes) pair's grouping on this mesh."""
    allowed = declared_partitions(declared, mesh_axes)
    failures = []
    for inst in collective_instances(text):
        if inst["groups"] is None:
            failures.append(
                f"{what}: line {inst['line']}: {inst['kind']} without "
                f"parseable replica_groups — the collective allowlist "
                f"cannot attribute it to a mesh axis")
            continue
        norm = frozenset(frozenset(g) for g in inst["groups"])
        if norm not in allowed.get(inst["kind"], ()):
            failures.append(
                f"{what}: line {inst['line']}: UNDECLARED collective "
                f"{inst['kind']} over device groups {inst['groups']} — "
                f"no DECLARED_COLLECTIVES entry produces this grouping "
                f"on the audited mesh")
    return failures


def observed_collectives(text: str, mesh_axes: Sequence[Tuple[str, int]]
                         ) -> Tuple[FrozenSet[Tuple[str, Tuple[str, ...]]],
                                    List[str]]:
    """The set of (collective kind, mesh axes) pairs a lowered sharded
    program contains, plus failures for any instance whose replica
    grouping matches no mesh-axis subset."""
    table = axis_groupings(mesh_axes)
    observed = set()
    failures = []
    for inst in collective_instances(text):
        if inst["groups"] is None:
            failures.append(
                f"line {inst['line']}: {inst['kind']} without parseable "
                f"replica_groups — the collective allowlist cannot "
                f"attribute it to a mesh axis")
            continue
        norm = frozenset(frozenset(g) for g in inst["groups"])
        axes = table.get(norm)
        if axes is None:
            failures.append(
                f"line {inst['line']}: {inst['kind']} over device groups "
                f"{inst['groups']} matches no mesh-axis subset — not a "
                f"reduction over declared axes")
            continue
        observed.add((inst["kind"], axes))
    return frozenset(observed), failures


def main_signature(text: str) -> Tuple[int, int, int]:
    """(n_args, n_aliased, n_buffer_donor) of the entry @main function.

    `tf.aliasing_output` is plain jit's donated-and-matched spelling;
    `jax.buffer_donor` is the shard_map/deferred spelling.  A donated
    leaf that JAX silently un-donated (shape/dtype mismatch against
    every output) carries NEITHER — which is the bug this counts."""
    m = _MAIN_SIG_RE.search(text)
    if not m:
        raise ValueError("no `func.func public @main(...)` entry "
                         "signature in the lowered text")
    sig = m.group(1)
    return (len(re.findall(r"%arg\d+\s*:", sig)),
            sig.count("tf.aliasing_output"),
            sig.count("jax.buffer_donor"))


def compiled_alias_count(compiled_text: str) -> int:
    """Number of aliased parameters in an optimized HLO module's
    ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` header.

    The table nests braces (`{0}` output indices, `{}` parameter
    index paths), so the close brace is found by depth counting, not
    regex."""
    idx = compiled_text.find("input_output_alias={")
    if idx < 0:
        return 0
    start = compiled_text.index("{", idx)
    depth = 0
    for j in range(start, len(compiled_text)):
        c = compiled_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return len(re.findall(r"alias\)",
                                      compiled_text[start:j]))
    return 0


# --------------------------------------------------------- shared checkers


def audit_text(text: str, what: str, *, callbacks: int = 0,
               donated_leaves: Optional[int] = None,
               collectives: FrozenSet = frozenset(),
               mesh_axes: Optional[Sequence[Tuple[str, int]]] = None,
               plane_elems: Optional[int] = None,
               kernel_calls: Optional[Dict[str, int]] = None) -> List[str]:
    """Run every text-level contract over one lowered program.

    `callbacks` — exact python-callback budget; `donated_leaves` — flat
    donated-state leaf count (None: program is not donated; the audit
    then asserts zero donation attrs, pinning the spelling);
    `collectives`/`mesh_axes` — the declared (kind, axes) allowlist and
    the audit mesh (None mesh: single-chip, zero collectives);
    `plane_elems` — the unpacked [N, T] element count for the
    all-gather plane guard; `kernel_calls` — the program's declared
    accelerator-kernel custom-call budget (target -> MAX count, e.g.
    the megakernel's Mosaic `tpu_custom_call`): listed targets are
    allowed up to their cap instead of reported as undeclared, and an
    over-budget count fails — a second kernel appearing in a
    one-kernel program is a program change, not plumbing.  Interpreter
    -mode lowerings (CPU) legitimately contain ZERO of them, so the
    budget is a ceiling, not an exact count."""
    failures = []
    kernel_calls = kernel_calls or {}

    got_cb = callback_calls(text)
    if got_cb != callbacks:
        failures.append(
            f"{what}: {got_cb} host-callback custom call(s), contract "
            f"says exactly {callbacks} — "
            + ("an io_callback/debug print leaked into an off-path "
               "program" if got_cb > callbacks else
               "the declared tap vanished (stale contract?)"))
    targets = custom_call_targets(text)
    for target, cap in sorted(kernel_calls.items()):
        if targets.get(target, 0) > cap:
            failures.append(
                f"{what}: {targets[target]} {target} custom call(s), "
                f"kernel budget allows at most {cap} — an extra "
                f"accelerator kernel entered the program")
    unknown = [t for t in unknown_custom_calls(text)
               if t not in kernel_calls]
    if unknown:
        failures.append(
            f"{what}: undeclared custom-call target(s) "
            f"{', '.join(unknown)} — extend BENIGN_CUSTOM_CALLS (or the "
            f"program contract) only with a reviewed reason")

    for v in dtype_violations(text, scalar_i64_ok=callbacks > 0):
        failures.append(f"{what}: {v}")

    if mesh_axes is None:
        insts = collective_instances(text)
        if insts:
            kinds = Counter(i["kind"] for i in insts)
            failures.append(
                f"{what}: single-chip program contains collectives "
                f"{dict(kinds)} — nothing may communicate here")
    else:
        observed, group_failures = observed_collectives(text, mesh_axes)
        failures.extend(f"{what}: {g}" for g in group_failures)
        # Subset check only here: one config's program legitimately
        # lowers a subset of the manifest (async-only psums etc.);
        # manifest STALENESS is `audit_sharded`'s union-equality job.
        for kind, axes in sorted(observed - collectives):
            failures.append(
                f"{what}: UNDECLARED collective {kind} over axes "
                f"{'/'.join(axes)} — the driver's DECLARED_COLLECTIVES "
                f"manifest does not allow it")
        if plane_elems is not None:
            for inst in collective_instances(text):
                if (inst["kind"] == "all_gather"
                        and inst["elems"] is not None
                        and inst["elems"] >= plane_elems):
                    failures.append(
                        f"{what}: line {inst['line']}: all_gather result "
                        f"of {inst['elems']} elements >= the unpacked "
                        f"[N, T] plane ({plane_elems}) — gathering a "
                        f"full plane is the exact ICI blow-up the "
                        f"packed-plane design exists to avoid")

    n_args, aliased, donors = main_signature(text)
    if donated_leaves is not None:
        if n_args != donated_leaves:
            failures.append(
                f"{what}: entry signature has {n_args} args but the "
                f"donated state pytree has {donated_leaves} leaves — "
                f"the audit is looking at a different program")
        if aliased + donors != n_args:
            failures.append(
                f"{what}: donation NOT honored — only {aliased + donors} "
                f"of {n_args} donated args carry "
                f"tf.aliasing_output/jax.buffer_donor (JAX silently "
                f"un-donates on shape/dtype mismatch; the state "
                f"double-buffers in HBM)")
    elif aliased or donors:
        failures.append(
            f"{what}: {aliased + donors} arg(s) carry donation attrs "
            f"but the contract says this program is NOT donated — "
            f"update the contract if donation was added on purpose")
    return failures


# ------------------------------------------------------- pinned programs

# Exact python-callback budget per pinned program (absent: 0).  The
# metrics tap is ONE unordered io_callback under a round-mod cond.
PINNED_CALLBACK_BUDGET: Dict[str, int] = {"flagship_metrics": 1}

# Accelerator-kernel custom-call budget per pinned program (absent:
# none allowed).  `flagship_megakernel` embeds exactly ONE Pallas
# program per round (ops/megakernel.py) — `tpu_custom_call` is
# Mosaic's lowering target on TPU, and the scan body spells it once;
# the CPU interpreter lowering contains zero (pure HLO emulation), so
# the budget is a ceiling (audit_text docstring).  A second kernel in
# a one-kernel program fails the audit.
PINNED_KERNEL_BUDGET: Dict[str, Dict[str, int]] = {
    "flagship_megakernel": {"tpu_custom_call": 1},
}

# Programs whose timed jit donates its state (everything except the
# bare streaming step, which is lowered un-donated by design).
PINNED_UNDONATED = frozenset({"streaming_step"})

# Small-shape overrides for the compile-level donation proof: same
# builder, same knobs, toy dims — compiling the 16384^2 program on a
# gate box would dominate tier-1 for no extra information.
_SMALL_DIMS = dict(nodes=64, txs=64, rounds=2)
_SMALL_FLEET = dict(fleet=4, nodes=32, txs=32, rounds=2)
_SMALL_TRAFFIC = dict(nodes=64, txs=256, window=64, rounds=4, rate=4.0)
_SMALL_STREAMING = dict(nodes=64, backlog_sets=256, set_cap=2,
                        window_sets=32)


def small_workload(name: str) -> Dict:
    """The pinned program's workload with dimensions shrunk to compile
    shape (engine knobs untouched — the audit must compile the same
    program FAMILY the pin hashes)."""
    from benchmarks import hlo_pin

    workload = dict(hlo_pin.PROGRAMS[name][0])
    if name in ("fleet_small", "fleet_sharded"):
        workload.update(_SMALL_FLEET)   # fleet_sharded keeps its mesh
    elif name == "flagship_traffic":
        workload.update(_SMALL_TRAFFIC)
    elif name == "streaming_step":
        workload.update(_SMALL_STREAMING)
    else:
        workload.update(_SMALL_DIMS)
    return workload


def pinned_donated_leaves(name: str, workload: Dict) -> int:
    """Flat leaf count of the state pytree the pinned program donates
    (eval_shape through the same `benchmarks/workload` builders the
    lowering uses — the PROGRAM_BUILDERS seam)."""
    import jax

    from benchmarks import workload as wl

    if name in ("fleet_small", "fleet_sharded"):
        state = jax.eval_shape(lambda: wl.fleet_flagship_state(
            workload["fleet"], workload["nodes"], workload["txs"],
            workload["k"])[0])
    elif name == "flagship_traffic":
        state = jax.eval_shape(lambda: wl.traffic_backlog_state(
            workload["nodes"], workload["txs"], workload["window"],
            workload["k"], workload["rate"])[0])
    else:
        state = jax.eval_shape(lambda: wl.flagship_state(
            workload["nodes"], workload["txs"], workload["k"],
            workload.get("latency", 0),
            inflight_engine=workload.get("inflight", "walk"),
            trace_every=workload.get("trace_every", 0),
            trace_rounds=workload["rounds"],
            stake=workload.get("stake", "off"),
            clusters=workload.get("clusters", 1))[0])
    return len(jax.tree.leaves(state))


def audit_pinned(name: str, workload: Optional[Dict] = None) -> List[str]:
    """Text-level contract audit of one pinned program at its archived
    workload (lowering shared with the drift test via
    `hlo_pin.program_text`'s cache — the audit costs no extra
    lowering)."""
    from benchmarks import hlo_pin

    workload = dict(workload or hlo_pin.PROGRAMS[name][0])
    text = hlo_pin.program_text(name, workload)
    donated = (None if name in PINNED_UNDONATED
               else pinned_donated_leaves(name, workload))
    return audit_text(
        text, f"{name}",
        callbacks=PINNED_CALLBACK_BUDGET.get(name, 0),
        donated_leaves=donated,
        kernel_calls=PINNED_KERNEL_BUDGET.get(name))


def audit_all_pinned(archive: Optional[Dict] = None) -> List[str]:
    """Audit every archived pin (archived workload when present)."""
    from benchmarks import hlo_pin

    archive = archive or hlo_pin._load_archive()
    failures = []
    for name, entry in sorted(archive.get("programs", {}).items()):
        if name not in hlo_pin.PROGRAMS:
            continue  # --stale owns that failure
        failures.extend(audit_pinned(name, entry.get("workload")))
    return failures


def audit_donation_compiled(name: str) -> List[str]:
    """Compile the pinned program at audit shape and prove the
    executable's ``input_output_alias`` covers every donated leaf —
    the compile-level half of the donation audit (lowered attrs can in
    principle be dropped by XLA; the alias table is what the runtime
    acts on)."""
    from benchmarks import hlo_pin

    if name in PINNED_UNDONATED:
        return []
    workload = small_workload(name)
    _, builder = hlo_pin.PROGRAMS[name]
    text = builder(workload)
    leaves = pinned_donated_leaves(name, workload)
    failures = audit_text(hlo_pin.strip_locations(text),
                          f"{name}@audit-shape",
                          callbacks=PINNED_CALLBACK_BUDGET.get(name, 0),
                          donated_leaves=leaves,
                          kernel_calls=PINNED_KERNEL_BUDGET.get(name))
    compiled = _compile_pinned(name, workload)
    aliased = compiled_alias_count(compiled)
    if aliased != leaves:
        failures.append(
            f"{name}@audit-shape: compiled input_output_alias covers "
            f"{aliased} of {leaves} donated leaves — the executable "
            f"double-buffers the rest (ROADMAP donation-soak contract)")
    return failures


# The memory-budget acceptance set (the resource plane,
# obs/resources.py): one program per timed lane whose analytic state
# footprint must account for the compiled buffer interface.  The five
# sharded drivers get the same check per-device through
# `obs.resources.sharded_driver_records` (benchmarks/mem_pin.py).
MEMORY_BUDGET_PROGRAMS = ("flagship", "fleet_small", "flagship_traffic",
                          "streaming_step")


def audit_memory_budget(name: str) -> List[str]:
    """Compile the pinned program at audit shape and assert the
    ANALYTIC footprint model (`obs.resources.footprint` — state pytree
    bytes from config shapes) accounts for the compiled
    `memory_analysis()` numbers: argument == state, output == state,
    and (donated programs) aliased bytes covering the state.  The
    byte-level twin of the `input_output_alias` leaf count above — an
    undonated COPY of one plane passes the leaf count (every leaf still
    aliased) but shows up here as surplus output or short alias."""
    from go_avalanche_tpu.obs import resources

    workload = small_workload(name)
    lowered, state_abs = lower_pinned(name, workload)
    record = resources.memory_record(lowered.compile())
    analytic = resources.footprint(state_abs)["total_bytes"]
    return resources.check_memory(
        record, analytic, donated=name not in PINNED_UNDONATED,
        rel_tol=0.02, abs_tol=2048, what=f"{name}@audit-shape")


def _compile_pinned(name: str, workload: Dict) -> str:
    """Optimized-HLO text of the pinned program compiled at `workload`
    shape (see `lower_pinned`)."""
    return lower_pinned(name, workload)[0].compile().as_text()


def lower_pinned(name: str, workload: Dict):
    """``(Lowered, abstract state)`` of a pinned program at `workload`
    shape — mirrors the lowering spelling in benchmarks/hlo_pin.py but
    keeps the Lowered object so ``.compile()`` (the donation proof, the
    resource plane's `memory_analysis`) and the state (the analytic
    footprint model) are both available from ONE lowering."""
    import dataclasses as _dc

    import jax

    import bench
    from benchmarks.workload import (
        flagship_config,
        flagship_state,
        fleet_flagship_state,
        northstar_config,
        northstar_state,
        traffic_backlog_state,
        traffic_config,
    )

    if name == "streaming_step":
        from go_avalanche_tpu.models import streaming_dag as sdg

        cfg = northstar_config(workload["window_sets"],
                               workload["set_cap"])
        state_abs = jax.eval_shape(lambda: northstar_state(
            nodes=workload["nodes"],
            backlog_sets=workload["backlog_sets"],
            set_cap=workload["set_cap"],
            window_sets=workload["window_sets"],
            track_finality=False)[0])
        return (jax.jit(lambda s: sdg.step(s, cfg)[0]).lower(state_abs),
                state_abs)
    if name in ("fleet_small", "fleet_sharded"):
        cfg = flagship_config(workload["txs"], workload["k"])
        state_abs = jax.eval_shape(lambda: fleet_flagship_state(
            workload["fleet"], workload["nodes"], workload["txs"],
            workload["k"])[0])
        mesh = None
        if name == "fleet_sharded":
            from go_avalanche_tpu.parallel import sharded_fleet

            a, b = (int(x) for x in workload["mesh"])
            mesh = sharded_fleet.make_fleet_mesh(a, b)
        lowered = bench.fleet_program(cfg, workload["rounds"],
                                      workload["fleet"],
                                      mesh=mesh).lower(state_abs)
        return lowered, state_abs
    elif name == "flagship_traffic":
        cfg = traffic_config(workload["window"], workload["k"],
                             workload["rate"])
        state_abs = jax.eval_shape(lambda: traffic_backlog_state(
            workload["nodes"], workload["txs"], workload["window"],
            workload["k"], workload["rate"])[0])
        lowered = bench.traffic_program(cfg,
                                        workload["rounds"]).lower(state_abs)
        return lowered, state_abs
    else:
        cfg = flagship_config(workload["txs"], workload["k"],
                              workload.get("latency", 0),
                              inflight_engine=workload.get("inflight",
                                                           "walk"),
                              metrics_every=workload.get("metrics_every",
                                                         0),
                              trace_every=workload.get("trace_every", 0),
                              stake=workload.get("stake", "off"),
                              clusters=workload.get("clusters", 1),
                              adversary=workload.get("adversary", "off"),
                              byzantine=workload.get("byzantine", 0.0),
                              round_engine=workload.get("round_engine",
                                                        "phased"))
        if workload.get("exchange", "fused") != "fused":
            cfg = _dc.replace(cfg, fused_exchange=False)
        if workload.get("ingest", "u8") != "u8":
            cfg = _dc.replace(cfg, ingest_engine=workload["ingest"])
        if workload.get("faults") is not None:
            from go_avalanche_tpu.config import fault_script_from_json

            cfg = _dc.replace(cfg, fault_script=fault_script_from_json(
                workload["faults"]))
        state_abs = jax.eval_shape(lambda: flagship_state(
            workload["nodes"], workload["txs"], workload["k"],
            workload.get("latency", 0),
            inflight_engine=workload.get("inflight", "walk"),
            trace_every=workload.get("trace_every", 0),
            trace_rounds=workload["rounds"])[0])
        lowered = bench.flagship_program(cfg,
                                         workload["rounds"]).lower(state_abs)
    return lowered, state_abs


def audit_off_path(platform: str, archive: Optional[Dict] = None
                   ) -> List[str]:
    """The semantic half of `hlo_pin --verify-off-path`: each off-path
    flagship program, re-lowered with every tap/script/stake knob
    forced off, must contain ZERO host callbacks, zero collectives, a
    clean dtype budget and full donation coverage — not merely the
    archived hash.  (Hash equality already proves byte-identity; this
    proves the byte-identical program IS callback-free, so a future
    re-pin cannot silently bless a leaked tap.)"""
    from benchmarks import hlo_pin

    archive = archive or hlo_pin._load_archive()
    failures = []
    for name in hlo_pin.OFF_PATH_PROGRAMS:
        entry = archive.get("programs", {}).get(name)
        if not entry or entry.get("hashes", {}).get(platform) is None:
            continue
        workload = dict(entry.get("workload")
                        or hlo_pin.PROGRAMS[name][0])
        workload.update(metrics_every=0, trace_every=0, faults=[],
                        stake="off", adversary="off", byzantine=0.0)
        failures.extend(audit_pinned(name, workload))
    return failures


# ------------------------------------------------------- sharded drivers

SHARDED_DRIVERS = ("avalanche", "dag", "backlog", "streaming_dag",
                   "node_stream")


class AuditUnavailable(RuntimeError):
    """The audit cannot run in this environment (e.g. fewer than 4
    devices for the 2x2 collective-attribution mesh)."""


def _audit_mesh():
    """A 2x2 (nodes, txs) mesh over the first 4 devices: small, and
    every axis subset produces a DISTINCT replica grouping, so
    collective attribution is unambiguous."""
    import jax

    from go_avalanche_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) < 4:
        raise AuditUnavailable(
            f"the sharded-driver audit needs >= 4 devices for its 2x2 "
            f"mesh, found {len(devices)} — run under the tier-1 "
            f"harness (8 virtual CPU devices) or on hardware")
    return make_mesh(n_node_shards=2, n_tx_shards=2,
                     devices=devices[:4])


# The async audit knobs: a 1-round fixed latency with a 4-round timeout
# turns the in-flight ring on, whose counters are the node-axis psums
# several manifests declare — the async VARIANT below proves those
# entries are live, not stale.
_ASYNC_KW = dict(latency_mode="fixed", latency_rounds=1, time_step_s=1.0,
                 request_timeout_s=3.0)


def _sharded_case(driver: str):
    """(variants, declared manifest, [N, T] plane elements) for one
    sharded driver at audit shape — variants are ``(label,
    program_builder(mesh), abstract state)`` triples; the base variant
    comes first (the compile-donation one), an async variant follows
    where the manifest declares async-only collectives.  States come
    from `jax.eval_shape` over the dense inits — nothing allocates."""
    import jax
    import jax.numpy as jnp

    from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig

    key = jax.random.key(0)
    if driver == "avalanche":
        from go_avalanche_tpu.models import avalanche as av
        from go_avalanche_tpu.parallel import sharded as drv

        def variant(label, cfg):
            state = jax.eval_shape(lambda: av.init(key, 16, 8, cfg))
            return (label,
                    lambda mesh: drv.scan_program(mesh, state, cfg,
                                                  n_rounds=2,
                                                  donate=True),
                    state)

        # The async+adversary variant exercises the ring counters AND
        # the minority-plane psum — the manifest's nodes-axis
        # all_reduce entries.
        variants = [
            variant("base", AvalancheConfig()),
            variant("async", AvalancheConfig(
                byzantine_fraction=0.25,
                adversary_strategy=AdversaryStrategy.OPPOSE_MAJORITY,
                **_ASYNC_KW)),
        ]
        return variants, drv.DECLARED_COLLECTIVES, 16 * 8
    if driver == "dag":
        from go_avalanche_tpu.models import dag as dag_model
        from go_avalanche_tpu.parallel import sharded_dag as drv

        cs = jnp.arange(8, dtype=jnp.int32) // 2

        def variant(label, cfg):
            # n_sets/set_size passed explicitly (the fleet's spelling)
            # so init stays abstract under eval_shape.
            state = jax.eval_shape(lambda: dag_model.init(
                key, 16, cs, cfg, n_sets=4, set_size=2))
            return (label,
                    lambda mesh: drv.settle_program(mesh, state, cfg,
                                                    max_rounds=8,
                                                    donate=True),
                    state)

        variants = [variant("base", AvalancheConfig()),
                    variant("async", AvalancheConfig(**_ASYNC_KW))]
        return variants, drv.DECLARED_COLLECTIVES, 16 * 8
    if driver == "backlog":
        from go_avalanche_tpu.models import backlog as bl
        from go_avalanche_tpu.parallel import sharded_backlog as drv

        cfg = AvalancheConfig()
        state = jax.eval_shape(lambda: bl.init(
            key, 16, 8, bl.make_backlog(jnp.arange(32, dtype=jnp.int32)),
            cfg))
        variants = [("base",
                     lambda mesh: drv.scan_program(mesh, state, cfg,
                                                   n_rounds=2,
                                                   donate=True),
                     state)]
        return variants, drv.DECLARED_COLLECTIVES, 16 * 8
    if driver == "streaming_dag":
        from go_avalanche_tpu.models import streaming_dag as sdg
        from go_avalanche_tpu.parallel import sharded_streaming_dag as drv

        cfg = AvalancheConfig()
        backlog = sdg.make_set_backlog(
            jnp.arange(32, dtype=jnp.int32).reshape(16, 2))
        state = jax.eval_shape(lambda: sdg.init(key, 16, 8, backlog, cfg))
        variants = [("base",
                     lambda mesh: drv.scan_program(mesh, state, cfg,
                                                   n_rounds=2,
                                                   donate=True),
                     state)]
        return variants, drv.DECLARED_COLLECTIVES, 16 * 16
    if driver == "node_stream":
        from go_avalanche_tpu.models import node_stream as ns
        from go_avalanche_tpu.parallel import sharded_node_stream as drv

        def variant(label, cfg):
            state = jax.eval_shape(lambda: ns.init(key, 8, cfg))
            return (label,
                    lambda mesh: drv.scan_program(mesh, state, cfg,
                                                  n_rounds=2,
                                                  donate=True),
                    state)

        ns_kw = dict(stake_mode="zipf", registry_nodes=32,
                     active_nodes=16, node_churn_rate=0.25)
        variants = [
            variant("base", AvalancheConfig(**ns_kw)),
            variant("async", AvalancheConfig(**ns_kw, **_ASYNC_KW)),
        ]
        return variants, drv.DECLARED_COLLECTIVES, 16 * 8
    raise ValueError(f"unknown sharded driver {driver!r}; drivers: "
                     f"{', '.join(SHARDED_DRIVERS)}")


def audit_sharded(driver: str, compile_donation: bool = False
                  ) -> List[str]:
    """Full contract audit of one sharded driver on the 2x2 audit mesh.

    Per variant (base + async where the manifest declares async-only
    collectives): observed collectives ⊆ `DECLARED_COLLECTIVES`, the
    all-gather plane guard, dtype budget, zero callbacks, donated-leaf
    coverage.  Across ALL variants: the union of observed collectives
    must EQUAL the manifest — a declared pair no audit variant lowers
    is a stale entry and fails.  `compile_donation=True` additionally
    compiles the base variant and proves the executable's
    ``input_output_alias`` coverage."""
    import jax

    from benchmarks.hlo_pin import strip_locations
    from go_avalanche_tpu.parallel.mesh import NODES_AXIS, TXS_AXIS

    mesh = _audit_mesh()
    variants, declared, plane_elems = _sharded_case(driver)
    mesh_axes = [(NODES_AXIS, mesh.shape[NODES_AXIS]),
                 (TXS_AXIS, mesh.shape[TXS_AXIS])]
    failures: List[str] = []
    union: set = set()
    for i, (label, program, state) in enumerate(variants):
        what = f"sharded:{driver}[{label}]"
        lowered = program(mesh).lower(state)
        text = strip_locations(lowered.as_text())
        leaves = len(jax.tree.leaves(state))
        # The shared checker owns the contracts (subset allowlist,
        # plane guard, dtype, callbacks, donation); only the
        # cross-variant union below is this function's own.
        failures.extend(audit_text(
            text, what, callbacks=0, donated_leaves=leaves,
            collectives=declared, mesh_axes=mesh_axes,
            plane_elems=plane_elems))
        observed, _ = observed_collectives(text, mesh_axes)
        union |= observed
        if compile_donation and i == 0:
            c_aliased = compiled_alias_count(lowered.compile().as_text())
            if c_aliased != leaves:
                failures.append(
                    f"{what}: compiled input_output_alias covers "
                    f"{c_aliased} of {leaves} donated leaves — the "
                    f"sharded state double-buffers the rest (the "
                    f"donation-under-shard_map soak, statically)")
    for kind, axes in sorted(declared - union):
        failures.append(
            f"sharded:{driver}: declared collective {kind} over axes "
            f"{'/'.join(axes)} never lowered in any audit variant — "
            f"stale manifest entry")
    return failures


def audit_all_sharded(compile_donation: bool = False) -> List[str]:
    failures = []
    for driver in SHARDED_DRIVERS:
        failures.extend(audit_sharded(driver, compile_donation))
    return failures


def _fleet_audit_mesh():
    """A 2x2 ``(trials, nodes)`` fleet mesh over the first 4 devices —
    the sharded-fleet twin of `_audit_mesh` (distinct replica grouping
    per axis subset, so collective attribution is unambiguous)."""
    import jax

    from go_avalanche_tpu.parallel import sharded_fleet

    devices = jax.devices()
    if len(devices) < 4:
        raise AuditUnavailable(
            f"the sharded-fleet audit needs >= 4 devices for its 2x2 "
            f"fleet mesh, found {len(devices)} — run under the tier-1 "
            f"harness (8 virtual CPU devices) or on hardware")
    return sharded_fleet.make_fleet_mesh(2, 2, devices=devices[:4])


def audit_sharded_fleet(compile_donation: bool = False) -> List[str]:
    """Contract audit of BOTH fleet-of-sharded-sims programs on the
    2x2 fleet mesh (`parallel/sharded_fleet.py`):

      * the DRIVER (`fleet_driver_program`, the `run_fleet(mesh=...)`
        seam, lowered through `fleet._compiled_sharded_fleet` — the
        exact lru-cached jit the runner executes): per-trial gathers
        and count psums over the declared trial axes and NOTHING else
        (a collective touching an [N, T] plane means one trial leaked
        into another's stream), zero callbacks, clean dtypes, and —
        union equality — every `DECLARED_COLLECTIVES` entry actually
        lowered (stale-manifest check, like `audit_sharded`);
      * the BENCH scan (`fleet_scan_program`, the `fleet_sharded`
        pin): ZERO collectives (trials never communicate — the
        embarrassing parallelism IS the contract) and full donation
        coverage, `compile_donation=True` additionally proving the
        executable's ``input_output_alias`` covers every fleet-stacked
        leaf (the donation-under-vmap contract's static half at mesh
        scale; the RUNTIME soak lives in tests/test_sharded_fleet.py).
    """
    import jax

    from benchmarks.hlo_pin import strip_locations
    from benchmarks.workload import flagship_config, fleet_flagship_state
    from go_avalanche_tpu import fleet as fl
    from go_avalanche_tpu.parallel import sharded_fleet
    from go_avalanche_tpu.parallel.mesh import NODES_AXIS

    mesh = _fleet_audit_mesh()
    mesh_axes = [(sharded_fleet.TRIALS_AXIS, 2), (NODES_AXIS, 2)]
    failures: List[str] = []

    # --- the driver program (keys -> gathered outcomes + counts).
    from go_avalanche_tpu.config import AvalancheConfig

    cfg = AvalancheConfig(finalization_score=16)
    driver = fl.compiled_fleet_program("avalanche", cfg, 16, 8, 2, 2,
                                       0.5, True, 64, mesh=mesh)
    keys_abs = jax.eval_shape(
        lambda: jax.random.split(jax.random.key(0), 8))
    text = strip_locations(driver.lower(keys_abs).as_text())
    failures.extend(audit_text(
        text, "sharded_fleet[driver]", callbacks=0, donated_leaves=None,
        collectives=sharded_fleet.DECLARED_COLLECTIVES,
        mesh_axes=mesh_axes, plane_elems=16 * 8))
    observed, _ = observed_collectives(text, mesh_axes)
    for kind, axes in sorted(sharded_fleet.DECLARED_COLLECTIVES
                             - observed):
        failures.append(
            f"sharded_fleet: declared collective {kind} over axes "
            f"{'/'.join(axes)} never lowered in the driver program — "
            f"stale manifest entry")

    # --- the bench scan program (the fleet_sharded pin's family).
    import bench

    bcfg = flagship_config(32, 8)
    state_abs = jax.eval_shape(
        lambda: fleet_flagship_state(8, 32, 32, 8)[0])
    scan = bench.fleet_program(bcfg, 2, 8, mesh=mesh)
    lowered = scan.lower(state_abs)
    stext = strip_locations(lowered.as_text())
    leaves = len(jax.tree.leaves(state_abs))
    failures.extend(audit_text(
        stext, "sharded_fleet[bench-scan]", callbacks=0,
        donated_leaves=leaves, collectives=frozenset(),
        mesh_axes=mesh_axes, plane_elems=32 * 32))
    if compile_donation:
        c_aliased = compiled_alias_count(lowered.compile().as_text())
        if c_aliased != leaves:
            failures.append(
                f"sharded_fleet[bench-scan]: compiled "
                f"input_output_alias covers {c_aliased} of {leaves} "
                f"donated fleet-stacked leaves — the trial planes "
                f"double-buffer (the donation-under-vmap contract at "
                f"mesh scale)")
    return failures


# --------------------------------------------------------- run_sim audit


def audit_run_sim(args, cfg) -> List[str]:
    """`run_sim --audit`: lower the EXACT program the parsed flags
    select — same model entry point, same statics, same donation — and
    run the text-level contracts before the runner executes it.

    Fleet audits lower through `fleet._compiled_fleet`'s lru-cached jit,
    so the subsequent execution compiles the audited program exactly
    once (lowering never compiles).  The parser has already rejected
    the combinations with no single-program meaning (--phase-grid,
    --check-invariants, --chunk)."""
    import jax

    from benchmarks.hlo_pin import strip_locations

    callbacks = 1 if cfg.metrics_every > 0 else 0
    what = f"run_sim:{args.model}"

    if args.fleet is not None:
        from go_avalanche_tpu import fleet as fl

        fleet_mesh = getattr(args, "fleet_mesh", None)
        keys_abs = jax.eval_shape(
            lambda: jax.random.split(jax.random.key(args.seed),
                                     args.fleet))
        jitted = fl.compiled_fleet_program(
            args.model, cfg, args.nodes, args.txs, args.max_rounds,
            args.conflict_size, args.yes_fraction, args.contested,
            args.slots, mesh=fleet_mesh)
        text = strip_locations(jitted.lower(keys_abs).as_text())
        if fleet_mesh is not None and fleet_mesh.devices.size > 1:
            # The trial-sharded driver: collectives on the declared
            # trial axes only (partition-based, so degenerate meshes
            # like 4,1 attribute correctly), plane guard included.
            from go_avalanche_tpu.parallel import sharded_fleet
            from go_avalanche_tpu.parallel.mesh import NODES_AXIS

            mesh_axes = [
                (sharded_fleet.TRIALS_AXIS,
                 fleet_mesh.shape[sharded_fleet.TRIALS_AXIS]),
                (NODES_AXIS, fleet_mesh.shape[NODES_AXIS])]
            failures = collective_coverage_failures(
                text, sharded_fleet.DECLARED_COLLECTIVES, mesh_axes,
                f"{what}@fleet{args.fleet}-mesh")
            failures.extend(audit_text(
                text, f"{what}@fleet{args.fleet}-mesh", callbacks=0,
                donated_leaves=None,
                collectives=sharded_fleet.DECLARED_COLLECTIVES,
                mesh_axes=mesh_axes,
                plane_elems=args.nodes * args.txs))
            return failures
        return audit_text(text, f"{what}@fleet{args.fleet}",
                          callbacks=0, donated_leaves=None)

    if args.mesh:
        from go_avalanche_tpu.parallel.mesh import (
            NODES_AXIS,
            TXS_AXIS,
        )

        mesh, program, state = _run_sim_mesh_program(args, cfg)
        text = strip_locations(program.lower(state).as_text())
        declared = _driver_manifest(args.model)
        mesh_axes = [(NODES_AXIS, mesh.shape[NODES_AXIS]),
                     (TXS_AXIS, mesh.shape[TXS_AXIS])]
        # Partition-based coverage: the user's mesh can be degenerate
        # (a size-1 axis makes axis subsets indistinguishable), so the
        # allowlist compares replica groupings, never axis names.
        failures = collective_coverage_failures(text, declared,
                                                mesh_axes, what)
        failures.extend(
            f"{what}: {v}"
            for v in dtype_violations(text, scalar_i64_ok=False))
        got_cb = callback_calls(text)
        if got_cb:
            failures.append(
                f"{what}: {got_cb} host-callback custom call(s) inside "
                f"a sharded program — io_callback is illegal under "
                f"shard_map here")
        if args.donate:
            leaves = len(jax.tree.leaves(state))
            n_args, aliased, donors = main_signature(text)
            if aliased + donors != n_args or n_args != leaves:
                failures.append(
                    f"{what}: --donate requested but only "
                    f"{aliased + donors} of {n_args} args (for {leaves} "
                    f"leaves) carry donation attrs")
        return failures

    program, state = _run_sim_dense_program(args, cfg)
    text = strip_locations(program.lower(state).as_text())
    donated = (len(jax.tree.leaves(state))
               if args.model == "avalanche" else None)
    return audit_text(text, what, callbacks=callbacks,
                      donated_leaves=donated)


def _run_sim_dense_program(args, cfg):
    """(jitted program, abstract state) for a dense run_sim selection —
    the same entry point + statics each runner calls."""
    import jax
    import jax.numpy as jnp

    key = jax.random.key(args.seed)
    model = args.model
    if model in ("slush", "snowflake"):
        from go_avalanche_tpu.models import family as fam

        if model == "slush":
            state = jax.eval_shape(lambda: fam.slush_init(
                key, args.nodes, cfg, yes_fraction=args.yes_fraction))
            program = jax.jit(fam.slush_run,
                              static_argnames=("cfg", "m_rounds"))
            return _bind(program, cfg, m_rounds=args.max_rounds), state
        state = jax.eval_shape(lambda: fam.snowflake_init(
            key, args.nodes, cfg, yes_fraction=args.yes_fraction))
        program = jax.jit(fam.snowflake_run,
                          static_argnames=("cfg", "max_rounds"))
        return _bind(program, cfg, max_rounds=args.max_rounds), state
    if model == "snowball":
        from go_avalanche_tpu.models import snowball as sb

        state = jax.eval_shape(lambda: sb.with_trace(
            sb.init(key, args.nodes, cfg,
                    yes_fraction=args.yes_fraction), cfg,
            args.max_rounds))
        program = jax.jit(sb.run, static_argnames=("cfg", "max_rounds"))
        return _bind(program, cfg, max_rounds=args.max_rounds), state
    if model == "avalanche":
        from go_avalanche_tpu.models import avalanche as av

        init_pref = (av.contested_init_pref(args.seed, args.nodes,
                                            args.txs)
                     if args.contested else None)
        state = jax.eval_shape(lambda: av.with_trace(
            av.init(key, args.nodes, args.txs, cfg,
                    init_pref=init_pref), cfg, args.max_rounds))
        # THE lru-cached jit `av.run(donate=True)` executes.
        return av._compiled_run(cfg, int(args.max_rounds), True), state
    if model == "dag":
        from go_avalanche_tpu.models import dag as dag_model

        cs = jnp.arange(args.txs, dtype=jnp.int32) // args.conflict_size
        state = jax.eval_shape(lambda: dag_model.with_trace(
            dag_model.init(key, args.nodes, cs, cfg), cfg,
            args.max_rounds))
        program = jax.jit(dag_model.run,
                          static_argnames=("cfg", "max_rounds"))
        return _bind(program, cfg, max_rounds=args.max_rounds), state
    if model == "backlog":
        from go_avalanche_tpu.models import backlog as bl

        state = jax.eval_shape(lambda: bl.with_trace(
            bl.init(key, args.nodes, args.slots,
                    bl.make_backlog(jnp.arange(args.txs,
                                               dtype=jnp.int32)), cfg),
            cfg, args.max_rounds))
        program = jax.jit(bl.run, static_argnames=("cfg", "max_rounds"))
        return _bind(program, cfg, max_rounds=args.max_rounds), state
    if model == "streaming_dag":
        from go_avalanche_tpu.models import streaming_dag as sdg

        c = args.conflict_size
        n_sets = args.txs // c
        backlog = sdg.make_set_backlog(
            jnp.arange(args.txs, dtype=jnp.int32).reshape(n_sets, c))
        state = jax.eval_shape(lambda: sdg.with_trace(
            sdg.init(key, args.nodes, args.slots, backlog, cfg), cfg,
            args.max_rounds))
        program = jax.jit(sdg.run, static_argnames=("cfg", "max_rounds"))
        return _bind(program, cfg, max_rounds=args.max_rounds), state
    if model == "node_stream":
        from go_avalanche_tpu.models import node_stream as ns

        state = jax.eval_shape(lambda: ns.with_trace(
            ns.init(key, args.txs, cfg), cfg, args.max_rounds))
        program = jax.jit(ns.run_scan,
                          static_argnames=("cfg", "n_rounds"))
        return _bind(program, cfg, n_rounds=args.max_rounds), state
    raise ValueError(f"no audit program for model {args.model!r}")


class _Bound:
    """A jitted (state, **statics) program partially applied to its
    statics so the audit's `.lower(state)` spelling is uniform."""

    def __init__(self, jitted, cfg, **statics):
        self._jitted, self._cfg, self._statics = jitted, cfg, statics

    def lower(self, state):
        return self._jitted.lower(state, self._cfg, **self._statics)


def _bind(jitted, cfg, **statics) -> _Bound:
    return _Bound(jitted, cfg, **statics)


def _driver_manifest(model: str) -> FrozenSet:
    from go_avalanche_tpu.parallel import (
        sharded,
        sharded_backlog,
        sharded_dag,
        sharded_node_stream,
        sharded_streaming_dag,
    )

    return {
        "avalanche": sharded.DECLARED_COLLECTIVES,
        "dag": sharded_dag.DECLARED_COLLECTIVES,
        "backlog": sharded_backlog.DECLARED_COLLECTIVES,
        "streaming_dag": sharded_streaming_dag.DECLARED_COLLECTIVES,
        "node_stream": sharded_node_stream.DECLARED_COLLECTIVES,
    }[model]


def _run_sim_mesh_program(args, cfg):
    """(mesh, jitted program, abstract state) for a --mesh selection —
    the exact driver program seam each mesh runner executes."""
    import jax
    import jax.numpy as jnp

    from go_avalanche_tpu.parallel.mesh import make_mesh

    n_shards, t_shards = (int(x) for x in args.mesh.split(","))
    mesh = make_mesh(n_node_shards=n_shards, n_tx_shards=t_shards)
    key = jax.random.key(args.seed)
    model = args.model
    if model == "avalanche":
        from go_avalanche_tpu.models import avalanche as av
        from go_avalanche_tpu.parallel import sharded as drv

        init_pref = (av.contested_init_pref(args.seed, args.nodes,
                                            args.txs)
                     if args.contested else None)
        state = jax.eval_shape(lambda: av.with_trace(
            av.init(key, args.nodes, args.txs, cfg,
                    init_pref=init_pref), cfg, args.max_rounds))
        return mesh, drv.settle_program(
            mesh, state, cfg, max_rounds=args.max_rounds,
            donate=args.donate), state
    if model == "dag":
        from go_avalanche_tpu.models import dag as dag_model
        from go_avalanche_tpu.parallel import sharded_dag as drv

        cs = jnp.arange(args.txs, dtype=jnp.int32) // args.conflict_size
        state = jax.eval_shape(lambda: dag_model.with_trace(
            dag_model.init(key, args.nodes, cs, cfg), cfg,
            args.max_rounds))
        return mesh, drv.settle_program(
            mesh, state, cfg, max_rounds=args.max_rounds,
            donate=args.donate), state
    if model == "backlog":
        from go_avalanche_tpu.models import backlog as bl
        from go_avalanche_tpu.parallel import sharded_backlog as drv

        state = jax.eval_shape(lambda: bl.with_trace(
            bl.init(key, args.nodes, args.slots,
                    bl.make_backlog(jnp.arange(args.txs,
                                               dtype=jnp.int32)), cfg),
            cfg, args.max_rounds))
        return mesh, drv.settle_program(
            mesh, state, cfg, max_rounds=args.max_rounds,
            donate=args.donate), state
    if model == "streaming_dag":
        from go_avalanche_tpu.models import streaming_dag as sdg
        from go_avalanche_tpu.parallel import sharded_streaming_dag as drv

        c = args.conflict_size
        backlog = sdg.make_set_backlog(
            jnp.arange(args.txs, dtype=jnp.int32).reshape(
                args.txs // c, c))
        state = jax.eval_shape(lambda: sdg.with_trace(
            sdg.init(key, args.nodes, args.slots, backlog, cfg), cfg,
            args.max_rounds))
        return mesh, drv.settle_program(
            mesh, state, cfg, max_rounds=args.max_rounds,
            donate=args.donate), state
    if model == "node_stream":
        from go_avalanche_tpu.models import node_stream as ns
        from go_avalanche_tpu.parallel import sharded_node_stream as drv

        state = jax.eval_shape(lambda: ns.with_trace(
            ns.init(key, args.txs, cfg), cfg, args.max_rounds))
        return mesh, drv.scan_program(
            mesh, state, cfg, n_rounds=args.max_rounds,
            donate=args.donate), state
    raise ValueError(f"no sharded audit program for model {args.model!r}")
