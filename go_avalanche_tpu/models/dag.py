"""Conflict-set Avalanche: double-spend resolution over [nodes, txs].

The reference has no conflict DAG — its records are independent single
targets — but Avalanche-the-protocol (the paper linked from the reference
README, `README.md:15`) and BASELINE config 3 ("Avalanche DAG: 10k nodes,
10k-tx UTXO conflict graph") demand one (SURVEY.md section 2.4 item 4).

Model: transactions partition into **conflict sets** (the UTXO double-spend
model: txs spending the same output conflict; `conflict_set[t]` gives tx t's
set id).  Per node and per set, the *preferred* tx is the one with the
highest confidence counter (ties -> accepted bit, then lowest tx index — the
deterministic stand-in for first-seen).  A node answers a poll about tx t
with yes iff t is preferred in its set, so the per-tx sliding-window records
(`ops/voterecord`) accumulate chits only for set winners; losers bleed
confidence and flip to rejected.  A set settles for a node once any of its
txs finalizes accepted — remaining rivals stop being polled (the same
mask-freeze that models the reference's delete-on-finalize,
`processor.go:114-116`).

Everything is segment_max/min over the txs axis — no [T, T] conflict matrix
— so the state stays SoA and the step stays one fused pass; the txs axis
remains collective-free, which keeps this compatible with the
`parallel/sharded` mesh layout when conflict sets do not straddle tx shards.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.obs import sink as obs_sink
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import adversary, exchange, inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.ops.bitops import pack_bool_plane
from go_avalanche_tpu.ops.sampling import draw_peers


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class DagSimState:
    """Avalanche sim state plus the conflict partition.

    `n_sets` is static pytree aux data (segment ops need a concrete segment
    count under jit/scan), not a traced leaf.  `set_size` is the static
    fast-path witness: when the partition is the contiguous fixed-capacity
    ``arange(T) // c`` (detected in `init`; true by construction for the
    streaming window, `models/streaming_dag`), set reductions collapse to
    ``[N, S, c]`` reshapes — no ``[T, N]`` transposes, no segment ops, no
    index planes — which is what fits the DAG round in HBM at the
    north-star window shape (verified on a v5e chip: the 100k-node x
    2048-tx-window round executes and sustains thousands of rounds; the
    round-3 "worker crashed" failure was dispatch length through the
    tunnel, not memory — see `streaming_dag.run_chunked`).  ``None``
    means "arbitrary partition": the general segment path.
    """

    base: av.AvalancheSimState
    conflict_set: jax.Array   # int32 [T] — set id per tx
    n_sets: int               # static
    set_size: Optional[int] = None  # static; c when partition is arange//c

    def tree_flatten(self):
        return (self.base, self.conflict_set), (self.n_sets, self.set_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


def init(
    key: jax.Array,
    n_nodes: int,
    conflict_set: jax.Array,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    init_pref: Optional[jax.Array] = None,
    scores: Optional[jax.Array] = None,
    track_finality: bool = True,
    n_sets: Optional[int] = None,
    set_size: Optional[int] = None,
) -> DagSimState:
    """Fresh conflicted network.

    `conflict_set` is an int32 [T] partition.  `init_pref` defaults to
    "every node initially prefers the lowest-index tx of each set" (the
    deterministic first-seen stand-in); pass a bool [T] to model nodes
    seeing double-spends in a different global order.

    `n_sets` / `set_size` override the host-side partition inspection
    (a `device_get` of `conflict_set.max()` plus a numpy compare) with
    the caller's static knowledge — the vmap-clean path (PR 7 audit):
    inside a traced fleet trial those host syncs are only legal because
    `conflict_set` is a closed-over CONSTANT, and a traced partition
    must pass the statics explicitly.  `set_size` (with `n_sets`)
    asserts the ``arange(T) // set_size`` fast-path layout; pass
    `n_sets` alone for an arbitrary partition.
    """
    conflict_set = jnp.asarray(conflict_set, jnp.int32)
    n_txs = conflict_set.shape[0]
    if n_sets is None:
        if set_size is not None:
            raise ValueError(
                "set_size override requires n_sets (pass both, or "
                "neither for host-side detection)")
        n_sets = int(jax.device_get(conflict_set.max())) + 1
        # Fast-path detection: the standard fixed-capacity contiguous
        # partition.
        set_size = None
        if n_txs % n_sets == 0:
            c = n_txs // n_sets
            if (np.asarray(jax.device_get(conflict_set))
                    == np.arange(n_txs) // c).all():
                set_size = c
    elif set_size is not None:
        # The override claims the contiguous fast-path layout; check
        # the static arithmetic always, and the layout itself whenever
        # the partition is concrete (it is even under the fleet vmap —
        # conflict_set is a closed-over constant there; only a truly
        # traced partition is taken on faith).
        if n_txs % set_size or n_sets != n_txs // set_size:
            raise ValueError(
                f"set_size={set_size} with n_sets={n_sets} does not "
                f"tile {n_txs} txs")
        if not isinstance(conflict_set, jax.core.Tracer) and not (
                np.asarray(jax.device_get(conflict_set))
                == np.arange(n_txs) // set_size).all():
            raise ValueError(
                f"set_size={set_size} claims the contiguous "
                f"arange(T) // set_size layout, but conflict_set is "
                f"partitioned differently — pass n_sets alone for an "
                f"arbitrary partition")
    elif not isinstance(conflict_set, jax.core.Tracer):
        # n_sets alone: an undercount would make every segment op
        # (num_segments=n_sets) silently DROP txs in the high sets —
        # settled/double-commit stats would miss them.  Overcounting
        # (empty trailing segments) is harmless padding.
        max_set = int(jax.device_get(conflict_set.max()))
        if max_set >= n_sets:
            raise ValueError(
                f"n_sets={n_sets} undercounts conflict_set (max set "
                f"id {max_set}) — txs in sets >= {n_sets} would be "
                f"silently dropped by every segment reduction")
    if init_pref is None:
        first_of_set = jnp.zeros((n_sets,), jnp.int32).at[
            conflict_set[::-1]].set(jnp.arange(n_txs - 1, -1, -1,
                                               dtype=jnp.int32))
        init_pref = jnp.zeros((n_txs,), jnp.bool_).at[first_of_set].set(True)
    base = av.init(key, n_nodes, n_txs, cfg, init_pref=init_pref,
                   scores=scores, track_finality=track_finality)
    return DagSimState(base=base, conflict_set=conflict_set, n_sets=n_sets,
                       set_size=set_size)


def with_trace(state: DagSimState, cfg: AvalancheConfig,
               n_rounds: int) -> DagSimState:
    """Attach the on-device trace plane (obs/trace.py) for an
    `n_rounds`-horizon run — the DAG round emits `SimTelemetry`, so the
    buffer is the flagship manifest on the base state.  No-op when
    `cfg.trace_every == 0`."""
    return dataclasses.replace(state,
                               base=av.with_trace(state.base, cfg,
                                                  n_rounds))


def preferred_in_set(
    confidence: jax.Array,
    conflict_set: jax.Array,
    n_sets: int,
) -> jax.Array:
    """Bool [N, T]: is tx t this node's preferred member of its set?

    Preference order within a set: highest confidence counter, then the
    accepted bit, then lowest tx index.  Two segment passes, no [T,T] blow-up.
    """
    # Preference order is (counter, accepted-bit) lexicographic, i.e.
    # (counter << 1) | accepted — which is exactly the packed `confidence`
    # word itself (bit 0 = accepted, bits 1..15 = counter, vote.go:24-50).
    # Keeping it uint16 halves the [T, N]/[S, N] segment-op intermediates,
    # the DAG model's HBM high-water mark at 100k-node scale.
    strength = confidence                              # uint16 [N, T]

    best = jax.ops.segment_max(strength.T, conflict_set,
                               num_segments=n_sets)    # [S, N]
    is_best = strength == best.T[:, conflict_set]      # broadcast per tx
    # Tie-break to the lowest tx index among the maxima.  The index planes
    # are the other [N, T]-sized transients; narrow them when T allows
    # (int16 halves another high-water contributor at fleet node counts).
    t = confidence.shape[-1]
    idx_dt = jnp.int16 if t < 0x7FFF else jnp.int32
    idx = jnp.arange(t, dtype=idx_dt)
    idx_masked = jnp.where(is_best, idx, idx_dt(t))    # non-best -> sentinel
    first_best = jax.ops.segment_min(idx_masked.T, conflict_set,
                                     num_segments=n_sets)  # [S, N]
    return idx[None, :] == first_best.T[:, conflict_set]


def preferred_in_set_fixed(confidence: jax.Array, set_size: int) -> jax.Array:
    """`preferred_in_set` for the contiguous ``arange(T) // c`` partition.

    The packed `confidence` word already orders (counter, accepted-bit)
    lexicographically, and `argmax` returns the FIRST maximum — exactly the
    lowest-index tie-break — so one reshape+argmax replaces both segment
    passes.  No ``[T, N]`` transposes and no index planes: at 100k nodes
    this is the difference between the DAG round fitting in HBM or not.
    Parity with the segment path is pinned by
    `tests/test_dag.py::test_fixed_partition_fast_path_matches_segment`.
    """
    n, t = confidence.shape
    grouped = confidence.reshape(n, t // set_size, set_size)
    best_lane = jnp.argmax(grouped, axis=2).astype(jnp.int32)  # [N, S]
    lanes = jnp.arange(set_size, dtype=jnp.int32)
    return (lanes[None, None, :] == best_lane[:, :, None]).reshape(n, t)


def set_any_fixed(plane: jax.Array, set_size: int) -> jax.Array:
    """bool [N, T]: does tx t's set contain a True anywhere on this node?
    Reshape form of the `segment_max` set_done pass (fixed partition)."""
    n, t = plane.shape
    done = plane.reshape(n, t // set_size, set_size).any(axis=2)  # [N, S]
    return jnp.repeat(done, set_size, axis=1)


def round_step(
    state: DagSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> Tuple[DagSimState, av.SimTelemetry]:
    """One conflicted-network round.

    Like `avalanche.round_step` but responses vote conflict-set preference,
    and finalizing a set freezes its losers.
    """
    if cfg.round_engine != "phased":
        raise ValueError(
            "round_engine 'megakernel' is wired for the dense avalanche "
            "round only; the dag model keeps the phased path (fusing the "
            "conflict-set preference vote is a ROADMAP follow-up) — the "
            "knob would be inert here")
    base = state.base
    n, t = base.records.votes.shape
    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(base.key, 5)

    fin = vr.has_finalized(base.records.confidence, cfg)
    fin_acc = fin & vr.is_accepted(base.records.confidence)

    # A set is settled for a node once any member finalized accepted.
    if state.set_size is not None:
        rival_settled = (set_any_fixed(fin_acc, state.set_size)
                         & jnp.logical_not(fin_acc))
    else:
        set_done = jax.ops.segment_max(fin_acc.astype(jnp.uint8).T,
                                       state.conflict_set,
                                       num_segments=state.n_sets)  # [S, N]
        rival_settled = (set_done.T[:, state.conflict_set] > 0) \
            & jnp.logical_not(fin_acc)

    pollable = (base.added & base.alive[:, None] & base.valid[None, :]
                & jnp.logical_not(fin) & jnp.logical_not(rival_settled))
    polled = av.capped_poll_mask(pollable, base.score_rank,
                                 cfg.max_element_poll,
                                 base.poll_order, base.poll_order_inv)

    # Peer sampling + failure model: identical axes to the flat simulator
    # (`models/avalanche.py`) — the shared draw dispatch, byzantine lies,
    # dropped responses, churn.
    peers, self_draw = draw_peers(k_sample, cfg, base.latency_weight,
                                  base.alive, n)
    lie = adversary.lie_mask(k_byz, peers, base.byzantine, cfg)
    responded = base.alive[peers]
    if self_draw is not None:
        responded &= jnp.logical_not(self_draw)
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    # Responses: yes iff the tx is the peer's preferred member of its set.
    if state.set_size is not None:
        prefs = preferred_in_set_fixed(base.records.confidence,
                                       state.set_size)
    else:
        prefs = preferred_in_set(base.records.confidence, state.conflict_set,
                                 state.n_sets)
    # Bit-pack the preference plane BEFORE gathering, as in
    # `models/avalanche.round_step`: the gather then reads T/8 bytes per
    # (node, draw) instead of T (measured 23.0ms -> 10.6ms for the
    # gather+pack stage at 100k nodes x 2048 txs on v5e — the streaming
    # north-star shape).  The engine dispatch collects all k draws in one
    # flattened gather by default (`ops/exchange.gather_vote_packs`).
    minority_t = adversary.minority_plane(prefs)
    packed_prefs = pack_bool_plane(prefs)

    # --- adaptive adversary (cfg.adversary_policy, ops/adversary.py):
    # the split tally reads the PREFERRED-IN-SET response plane (what
    # responders would actually say), the near-quorum gate the window
    # vote counts; statically absent (None) with the policy off.
    pol = adversary.policy_ctx(cfg, base.records, base.byzantine,
                               base.latency_weight, prefs=prefs)
    lie, responded, withheld = adversary.apply_policy_issue(cfg, pol, lie,
                                                            responded)
    ring = base.inflight
    if inflight.enabled(cfg):
        # Async query lifecycle (ops/inflight.py): responses vote the
        # responder's preferred-in-set plane AS OF the delivery round's
        # start (the synchronous round's own observation convention).
        lat = inflight.draw_latency(k_sample, cfg, peers,
                                    base.latency_weight, n)
        lat = adversary.apply_policy_latency(cfg, lat, lie, withheld)
        lat = inflight.apply_faults(lat, cfg, base.round, 0, peers, n,
                                    base.fault_params)
        ring = inflight.enqueue(base.inflight, base.round, peers, lat,
                                responded, lie, polled)
        records, changed, votes_applied = inflight.deliver_multi_engine(
            ring, base.records, cfg, packed_prefs, minority_t, k_byz,
            base.round, t, live_rows=base.alive, ctx=pol)
    else:
        yes_pack, consider_pack = exchange.gather_vote_packs(
            packed_prefs, peers, responded, lie, k_byz, cfg, minority_t, t,
            pol)

        records, changed = vr.register_packed_votes_engine(
            base.records, yes_pack, consider_pack, cfg.k, cfg,
            update_mask=polled)
        votes_applied = (av.popcnt_plane(consider_pack) * polled).sum()

    fin_after = vr.has_finalized(records.confidence, cfg)
    newly_final = fin_after & jnp.logical_not(fin)
    finalized_at = av.stamp_finality(base.finalized_at, newly_final,
                                     base.round)

    alive = base.alive
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability, (n,))
        alive = jnp.logical_xor(alive, toggle)
    alive = inflight.apply_churn_bursts(alive, cfg, base.round, k_churn)

    # Async-era ring counters: same accounting as the flat simulator
    # (statically zero when the in-flight engine is off); the DAG round
    # has no gossip, so the gossip counters stay zero.
    rt = inflight.ring_telemetry(ring, cfg, base.round)
    cut = (inflight.partition_cut(cfg, base.round, 0, peers, n,
                                  base.fault_params)
           if inflight.enabled(cfg) else None)
    telemetry = av.SimTelemetry(
        polls=polled.sum().astype(jnp.int32),
        votes_applied=votes_applied.astype(jnp.int32),
        flips=(changed & jnp.logical_not(newly_final)).sum().astype(jnp.int32),
        finalizations=newly_final.sum().astype(jnp.int32),
        admissions=jnp.int32(0),
        deliveries=rt.deliveries,
        expiries=rt.expiries,
        ring_occupancy=rt.occupancy,
        partition_blocked=(jnp.int32(0) if cut is None
                           else cut.sum().astype(jnp.int32)),
        gossip_writes=jnp.int32(0),
    )
    obs_sink.emit_round(cfg, base.round, telemetry)
    new_base = av.AvalancheSimState(
        records=records,
        added=base.added,
        valid=base.valid,
        score_rank=base.score_rank,
        poll_order=base.poll_order,
        poll_order_inv=base.poll_order_inv,
        byzantine=base.byzantine,
        alive=alive,
        latency_weight=base.latency_weight,
        finalized_at=finalized_at,
        round=base.round + 1,
        key=k_next,
        inflight=ring,
        fault_params=base.fault_params,
        trace=obs_trace.write_round(base.trace, cfg, base.round,
                                    telemetry),
    )
    return DagSimState(new_base, state.conflict_set, state.n_sets,
                       state.set_size), telemetry


def winners_per_set(fin_acc, set_size: int):
    """Finalized-accepted member count per CONTIGUOUS set; ``[N, T//c]``.

    Host-side analysis counterpart of the on-device segment ops, for the
    standard ``idx // set_size`` partition: a (node, set) pair is resolved
    iff its count is exactly 1.  Accepts numpy or jnp planes; callers
    filter node rows (honest / alive) to taste.  Shared by the connector
    sim backend, the baseline suite, and the threshold sweep.
    """
    n, t = fin_acc.shape
    return fin_acc.reshape(n, t // set_size, set_size).sum(axis=2)


def settled(state: DagSimState,
            cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """True when every (live node, set) resolved: a member finalized accepted
    for every set on every live node."""
    fin_acc = (vr.has_finalized(state.base.records.confidence, cfg)
               & vr.is_accepted(state.base.records.confidence))
    if state.set_size is not None:
        n, t = fin_acc.shape
        done = fin_acc.reshape(n, t // state.set_size,
                               state.set_size).any(axis=2)      # [N, S]
        return jnp.where(state.base.alive[:, None], done, True).all()
    set_done = jax.ops.segment_max(fin_acc.astype(jnp.uint8).T,
                                   state.conflict_set,
                                   num_segments=state.n_sets)   # [S, N]
    return jnp.where(state.base.alive[None, :], set_done > 0, True).all()


def run(
    state: DagSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 2000,
) -> DagSimState:
    """Run until every conflict set resolved on every live node."""

    def cond(s: DagSimState) -> jax.Array:
        return jnp.logical_not(settled(s, cfg)) & (s.base.round < max_rounds)

    def body(s: DagSimState) -> DagSimState:
        new_s, _ = round_step(s, cfg)
        return new_s

    return lax.while_loop(cond, body, state)


def run_scan(
    state: DagSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 200,
) -> Tuple[DagSimState, av.SimTelemetry]:
    def step(s, _):
        return round_step(s, cfg)

    return lax.scan(step, state, None, length=n_rounds)
