"""Multi-target Avalanche network simulator: N nodes × T targets.

The batched re-design of the reference's whole stack (SURVEY.md sections 3.3,
7 phase 3): every node's `Processor` maps (`processor.go:16-19`) become rows
of dense ``[nodes, txs]`` arrays, and one `round_step` is the entire network
doing one poll/response/ingest cycle:

    poll-cap top-score targets  (GetInvsForNextPoll, `processor.go:144-170`)
    sample k peers per node     (replaces round-robin, `main.go:111`)
    gossip-on-poll admission    (`main.go:177`, as k scatter-ORs)
    gather peer preferences     (the synchronous `query`, `main.go:168-193`)
    adversary/drop transforms   (`main.go:184-187` hook; `vote.go:56` neutrals)
    fused window update         (RegisterVotes, `processor.go:92-117`)

Map insert/delete become masks: `added` replaces AddTargetToReconcile's map
insert (`processor.go:55-56`), freezing finalized records replaces the
delete (`processor.go:114-116`).

Memory discipline: the per-round peer gather never materializes a bool
``[nodes, k, txs]`` tensor in HBM — the fused engine (`ops/exchange.py`,
default) gathers all ``N*k`` rows of the BIT-PACKED preference plane in one
HLO and bit-transposes them element-wise into the two uint8 planes
`register_packed_votes` consumes; the legacy engine
(`cfg.fused_exchange=False`) runs the same exchange as k row-gathers.  Both
are bit-exact (tests/test_exchange.py).
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG, VoteMode
from go_avalanche_tpu.obs import sink as obs_sink
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import adversary, exchange, inflight
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.ops.bitops import pack_bool_plane, popcount8
from go_avalanche_tpu.ops.sampling import draw_peers
from go_avalanche_tpu.utils.tracing import annotate


def popcnt_plane(x: jax.Array) -> jax.Array:
    """Per-element popcount of a uint8 plane, as int32."""
    return popcount8(x).astype(jnp.int32)


class AvalancheSimState(NamedTuple):
    """Whole-network state: a pytree of ``[N, T]`` / ``[N]`` / ``[T]`` arrays.

    The structs-of-arrays batched state store (SURVEY.md section 2.4 item 1).
    """

    records: vr.VoteRecordState  # [N, T] uint8/uint8/uint16
    added: jax.Array             # bool [N, T] — node reconciles target
    valid: jax.Array             # bool [T]   — Target.IsValid
    score_rank: jax.Array        # int32 [T]  — 0 = highest score (poll order)
    poll_order: jax.Array        # int32 [T]  — argsort(score_rank): target
                                 # ids best-score-first.  Hoisted to init
                                 # so `capped_poll_mask` pays no per-round
                                 # argsort; immutable whenever score_rank
                                 # is (the streaming schedulers refresh
                                 # both together, `score_rank_with_orders`)
    poll_order_inv: jax.Array    # int32 [T]  — inverse permutation of
                                 # poll_order (numerically == score_rank,
                                 # kept as its own buffer so state
                                 # donation never aliases two leaves)
    byzantine: jax.Array         # bool [N]
    alive: jax.Array             # bool [N]
    latency_weight: jax.Array    # float32 [N] — peer sampling propensity
    finalized_at: Optional[jax.Array]  # int32 [N, T]; -1 until finalized.
                                 # None = tracking off (init
                                 # track_finality=False): the plane is pure
                                 # telemetry for per-(node,tx) latency
                                 # stats, and maintaining it costs an int32
                                 # [N, T] read+write every round — callers
                                 # that record latency elsewhere (the
                                 # streaming scheduler's per-set
                                 # `SetOutputs`) can drop it
    round: jax.Array             # int32 scalar
    key: jax.Array               # PRNG key
    inflight: Optional[inflight.InflightState] = None
                                 # pending-query ring buffer
                                 # (ops/inflight.py) — present iff
                                 # cfg.async_queries(): response
                                 # latency / timeout expiry / partition
                                 # faults.  None = the synchronous
                                 # ideal, statically absent from the
                                 # trace (flagship hlo_pin unchanged)
    fault_params: Optional[inflight.FaultParams] = None
                                 # realized stochastic fault-event
                                 # parameters (ops/inflight.
                                 # draw_fault_params), drawn once from
                                 # the init key — present iff the
                                 # script schedules stochastic events;
                                 # None = statically absent (every
                                 # archived hlo pin unchanged)
    trace: Optional[obs_trace.TraceBuffer] = None
                                 # on-device trace plane
                                 # (obs/trace.py): an [S, M] int32 row
                                 # buffer the round writes its
                                 # telemetry into at round % stride ==
                                 # 0 — attach with `with_trace` when
                                 # cfg.trace_every > 0; None = the
                                 # zero-trace path, statically absent
                                 # (every archived hlo pin unchanged)


class SimTelemetry(NamedTuple):
    """Per-round scalars accumulated on device; fetched infrequently.

    Two granularities (docs/observability.md glossary): the vote
    counters (`polls`, `votes_applied`, `flips`, `finalizations`,
    `admissions`, `gossip_writes`) count (node, target[, draw]) events;
    the async-era ring counters (`deliveries`, `expiries`,
    `ring_occupancy`, `partition_blocked` — PR 5) count (querier, draw)
    in-flight ENTRIES and are statically zero when the in-flight engine
    is off.  Every field is computed from planes the round already
    materializes (popcount/compare reductions, zero extra gathers), so
    a driver that discards telemetry — every pinned hlo program does —
    pays nothing: jax DCEs the dead reductions before lowering.
    """

    polls: jax.Array           # int32 — (node, target) pairs polled
    votes_applied: jax.Array   # int32 — non-neutral votes ingested
    flips: jax.Array           # int32 — preference flips
    finalizations: jax.Array   # int32 — records finalized this round
    admissions: jax.Array      # int32 — gossip admissions this round
    deliveries: jax.Array      # int32 — ring entries delivered this round
    expiries: jax.Array        # int32 — ring entries expired unanswered
    ring_occupancy: jax.Array  # int32 — entries in flight after the round
    partition_blocked: jax.Array  # int32 — this round's draws cut by the
                               # active partition (they will expire)
    gossip_writes: jax.Array   # int32 — (node, target) pairs the gossip
                               # scatter marked heard this round


# The flagship round's trace-plane column manifest: exactly the
# SimTelemetry fields, in JSONL flattening order (all int32 counters).
TRACE_COLUMNS = obs_trace.columns_from_fields(SimTelemetry._fields)


def with_trace(state: AvalancheSimState, cfg: AvalancheConfig,
               n_rounds: int) -> AvalancheSimState:
    """Attach the on-device trace plane for an `n_rounds`-horizon run
    (no-op when `cfg.trace_every == 0`); also the DAG round's buffer —
    it emits the same `SimTelemetry` columns."""
    return state._replace(trace=obs_trace.alloc(cfg, n_rounds,
                                                TRACE_COLUMNS))


def contested_init_pref(seed: int, n_nodes: int, n_txs: int) -> jax.Array:
    """Per-NODE 50/50 initial preferences; bool ``[N, T]``.

    The contested-prior convention shared by `run_sim --contested` and
    `examples/finality_curves.py --contested`: nodes first saw different
    spends, so the network must genuinely converge per tx (unanimous
    priors finalize in ceil((6 + finalization)/k) rounds at every size).
    The key offsets the sim seed so priors and round draws decorrelate.
    """
    return jax.random.bernoulli(jax.random.key(seed + 1), 0.5,
                                (n_nodes, n_txs))


def contested_init_pref_from_key(key: jax.Array, n_nodes: int,
                                 n_txs: int) -> jax.Array:
    """`contested_init_pref` from a PRNG KEY instead of a host seed —
    the vmap-clean spelling the Monte-Carlo fleet driver needs (the
    per-trial key is a tracer inside the vmapped init, so
    `jax.random.key(seed + 1)` is unreachable there).  A distinct
    stream from the seed spelling by design: fleet trials are their own
    population, not replays of the seed-based studies."""
    return jax.random.bernoulli(jax.random.fold_in(key, 0xC0), 0.5,
                                (n_nodes, n_txs))


def stamp_finality(finalized_at, newly_final, round_):
    """Record first-finalization rounds; None (tracking off) passes through.

    The shared telemetry stamp for every round implementation (dense and
    sharded) — semantics changes belong here, not per-model.
    """
    if finalized_at is None:   # static: tracking disabled at init
        return None
    return jnp.where(newly_final & (finalized_at < 0), round_, finalized_at)


def reset_finality(finalized_at, take_cols):
    """Clear stamps for window columns being re-admitted (streaming
    schedulers); None (tracking off) passes through."""
    if finalized_at is None:
        return None
    return jnp.where(take_cols[None, :], -1, finalized_at)


def score_ranks(scores: jax.Array) -> jax.Array:
    """Rank targets by descending score; int32 [T], 0 = best.

    Implements the *intended* work-descending poll order
    (`avalanche.go:162-174`, disabled call at `processor.go:163`).  Ties
    break by index for determinism.
    """
    return score_rank_with_orders(scores)[0]


def score_rank_with_orders(scores: jax.Array) -> Tuple[jax.Array, jax.Array,
                                                       jax.Array]:
    """``(score_rank, poll_order, poll_order_inv)`` from raw scores — ONE
    argsort for all three.

    `poll_order` is the best-score-first target permutation (exactly what
    `capped_poll_mask` used to recompute every round as
    ``argsort(score_rank)``: ranks are a permutation, so their stable
    argsort reproduces the score argsort bit-for-bit) and `poll_order_inv`
    its inverse — which IS `score_rank`, built here as a second scatter so
    the two state leaves never share a device buffer (donated states must
    not alias inputs).  Used by `init` and by every scheduler that refreshes
    scores mid-run (`models/backlog`, `models/streaming_dag`, their sharded
    twins).
    """
    scores = jnp.asarray(scores)
    t = scores.shape[0]
    order = jnp.argsort(-scores, stable=True).astype(jnp.int32)
    ar = jnp.arange(t, dtype=jnp.int32)
    rank = jnp.zeros((t,), jnp.int32).at[order].set(ar)
    inv = jnp.zeros((t,), jnp.int32).at[order].set(ar)
    return rank, order, inv


def init(
    key: jax.Array,
    n_nodes: int,
    n_txs: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    init_pref: Optional[jax.Array] = None,   # bool [T] or [N, T]; default all-
                                             #   accepted.  A 2-D plane gives
                                             #   per-NODE priors — contested
                                             #   networks (nodes first saw
                                             #   different spends) rather than
                                             #   unanimous ones
    scores: Optional[jax.Array] = None,      # [T]; default uniform (tx-like)
    added: Optional[jax.Array] = None,       # bool [N, T]; default all
    valid: Optional[jax.Array] = None,       # bool [T]; default all
    latency_weights: Optional[jax.Array] = None,  # f32 [N]; default uniform
    track_finality: bool = True,             # False: skip the finalized_at
                                             #   plane (see AvalancheSimState)
) -> AvalancheSimState:
    """Fresh network.

    Defaults mirror the reference example: every node is fed every tx up
    front (`main.go:49-53`), every tx starts accepted (`main.go:51`:
    isAccepted=true) with score 1 (`main.go:209`).  Records for not-yet-added
    pairs are pre-seeded with the target prior and stay inert until gossip
    admission flips `added` — at which point they start from exactly the
    state `NewVoteRecord(t.IsAccepted())` would give (`processor.go:56`).
    """
    if init_pref is None:
        init_pref = jnp.ones((n_txs,), jnp.bool_)
    init_pref = jnp.asarray(init_pref, jnp.bool_)
    if init_pref.ndim == 1:
        init_pref = jnp.broadcast_to(init_pref[None, :], (n_nodes, n_txs))
    if scores is None:
        scores = jnp.ones((n_txs,), jnp.int32)
    if added is None:
        added = jnp.ones((n_nodes, n_txs), jnp.bool_)
    if valid is None:
        valid = jnp.ones((n_txs,), jnp.bool_)
    if latency_weights is None:
        latency_weights = jnp.ones((n_nodes,), jnp.float32)
    latency_weights = jnp.asarray(latency_weights, jnp.float32)
    if cfg.stake_mode != "off" and not cfg.registry_nodes:
        # Stake subsystem (go_avalanche_tpu/stake.py): the jit-static
        # per-node stake vector folds into the sampling-propensity
        # plane, turning every peer draw into a stake-weighted
        # committee draw (`ops/sampling.draw_peers` stake dispatch).
        # Off = plane untouched (every archived hlo pin byte-identical).
        # With the node registry on, row index != node id — the
        # node-stream scheduler owns the plane and overwrites it with
        # the residents' registry stakes (`models/node_stream.init`).
        from go_avalanche_tpu import stake as stake_mod

        latency_weights = latency_weights * stake_mod.node_stake(
            cfg, n_nodes)

    n_byz = int(round(cfg.byzantine_fraction * n_nodes))
    score_rank, poll_order, poll_order_inv = score_rank_with_orders(scores)
    return AvalancheSimState(
        records=vr.init_state(init_pref),
        added=jnp.asarray(added, jnp.bool_),
        valid=jnp.asarray(valid, jnp.bool_),
        score_rank=score_rank,
        poll_order=poll_order,
        poll_order_inv=poll_order_inv,
        byzantine=jnp.arange(n_nodes) < n_byz,
        alive=jnp.ones((n_nodes,), jnp.bool_),
        latency_weight=jnp.asarray(latency_weights, jnp.float32),
        finalized_at=(jnp.full((n_nodes, n_txs), -1, jnp.int32)
                      if track_finality else None),
        round=jnp.int32(0),
        key=key,
        inflight=(inflight.init_ring(cfg, n_nodes, n_txs)
                  if inflight.enabled(cfg) else None),
        fault_params=inflight.draw_fault_params(cfg, key, n_nodes),
    )


def capped_poll_mask(
    pollable: jax.Array,
    score_rank: jax.Array,
    cap: int,
    poll_order: Optional[jax.Array] = None,
    poll_order_inv: Optional[jax.Array] = None,
) -> jax.Array:
    """Keep at most `cap` pollable targets per node, best score first.

    The truncation at `processor.go:165-167` — but by the intended score
    order rather than whatever the map iterator yielded.  No-op (statically)
    when T <= cap.

    `poll_order`/`poll_order_inv` are the init-time-hoisted argsort pair
    (`AvalancheSimState.poll_order`); when omitted they are recomputed here
    from `score_rank` — identical bits either way (ranks are a permutation),
    the hoisted form just skips two argsorts per round.
    """
    t = pollable.shape[-1]
    if t <= cap:
        return pollable
    order = jnp.argsort(score_rank) if poll_order is None else poll_order
    in_order = pollable[:, order]
    keep = (jnp.cumsum(in_order.astype(jnp.int32), axis=1) <= cap) & in_order
    inv = jnp.argsort(order) if poll_order_inv is None else poll_order_inv
    return keep[:, inv]


def round_step(
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> Tuple[AvalancheSimState, SimTelemetry]:
    """One network-wide poll/response/ingest round.  Pure; jit/scan-able."""
    n, t = state.records.votes.shape
    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(state.key, 5)

    fin = vr.has_finalized(state.records.confidence, cfg)

    # --- GetInvsForNextPoll: live, valid, non-finalized, score-capped.
    with annotate("poll_mask"):
        pollable = (state.added & state.alive[:, None] & state.valid[None, :]
                    & jnp.logical_not(fin))
        polled = capped_poll_mask(pollable, state.score_rank,
                                  cfg.max_element_poll,
                                  state.poll_order, state.poll_order_inv)

    # --- peer sampling: uniform (with/without replacement),
    # latency-weighted (BASELINE config 5), or clustered topology — the
    # shared `ops/sampling.draw_peers` dispatch.  In the weighted/clustered
    # families self-draws (which per-row exclusion can't cheaply rule out)
    # become abstentions.
    with annotate("sample_peers"):
        peers, self_draw = draw_peers(k_sample, cfg, state.latency_weight,
                                      state.alive, n)

    # --- response model: byzantine lies and dropped responses, decided
    # per (poller, draw) — a lying peer's whole response is transformed per
    # `cfg.adversary_strategy` (ops/adversary.py).
    lie = adversary.lie_mask(k_byz, peers, state.byzantine, cfg)
    responded = state.alive[peers]
    if self_draw is not None:
        responded &= jnp.logical_not(self_draw)
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    # --- adaptive adversary (cfg.adversary_policy, ops/adversary.py):
    # one per-round context read from the PRE-round state turns the
    # state-blind lie transforms into state-dependent attacks — who
    # lies (stake_eclipse), whether a lie is silence instead
    # (withhold_near_quorum), what it says (split_vote), when it lands
    # (timing, via the latency plane below).  Statically absent (None)
    # with the policy off: every archived hlo pin byte-identical.
    pol = adversary.policy_ctx(cfg, state.records, state.byzantine,
                               state.latency_weight)
    lie, responded, withheld = adversary.apply_policy_issue(cfg, pol, lie,
                                                            responded)

    # --- gossip-on-poll: each polled peer admits targets it hasn't seen
    # (`main.go:177`) — one scatter over the flattened (peer, polled-plane)
    # pairs (fused engine, default) or k scatter-ORs (legacy); identical
    # bits either way (`ops/exchange.gossip_heard`).
    added = state.added
    admissions = jnp.int32(0)
    gossip_writes = jnp.int32(0)
    if cfg.gossip:
        with annotate("gossip_admission"):
            heard = exchange.gossip_heard(peers, polled.astype(jnp.uint8),
                                          cfg)
            new_adds = ((heard > 0) & jnp.logical_not(added)
                        & state.alive[:, None] & state.valid[None, :])
            admissions = new_adds.sum().astype(jnp.int32)
            gossip_writes = (heard > 0).sum().astype(jnp.int32)
            added = added | new_adds

    # --- gather peer preferences and pack the k votes into bit planes.
    # The preference plane is bit-packed along txs BEFORE gathering, so the
    # gather reads T/8 bytes per (node, draw) instead of T (measured ~13%
    # faster end-to-end at 8192x8192; it is also the sharded path's wire
    # format, `parallel/sharded.py`).  The engine dispatch
    # (`ops/exchange.gather_vote_packs`) collects all k draws in ONE
    # flattened gather by default, or k row-gathers with
    # `cfg.fused_exchange=False`.
    with annotate("gather_prefs"):
        prefs = vr.is_accepted(state.records.confidence)   # [N, T]
        packed_prefs = pack_bool_plane(prefs)              # [N, ceil(T/8)]
        minority_t = adversary.minority_plane(prefs)       # [T]
        if not inflight.enabled(cfg) and cfg.round_engine != "megakernel":
            yes_pack, consider_pack = exchange.gather_vote_packs(
                packed_prefs, peers, responded, lie, k_byz, cfg,
                minority_t, t, pol)

    # --- ingest: k fused window updates on polled records only
    # (RegisterVotes, `processor.go:92-117`); finalized records freeze.
    # `cfg.ingest_engine` selects the u8 reference or the SWAR
    # lane-packed engine (ops/swar.py) — identical bits either way.
    ring = state.inflight
    if cfg.round_engine == "megakernel":
        # --- whole-round megakernel (ops/megakernel.py): the gather,
        # the SWAR window ingest, and the closed-form confidence fold
        # run as ONE Pallas program on VMEM-resident record tiles — the
        # [N, k] vote packs and intermediate [N, T] planes above never
        # reach HBM.  Bit-identical to the phased chain (pinned by
        # tests/test_megakernel.py); sync round only (config-validated:
        # no in-flight ring, SEQUENTIAL votes).  Imported lazily so the
        # phased path never touches pallas at import time.
        from go_avalanche_tpu.ops import megakernel
        with annotate("fused_round"):
            records, changed = megakernel.fused_round(
                state.records, packed_prefs, peers, responded, lie,
                minority_t, polled, cfg)
            # consider_pack is the per-row responded popcount broadcast
            # over txs, so the phased count folds to this closed form.
            votes_applied = (responded.sum(axis=1).astype(jnp.int32)[:, None]
                             * polled).sum()
    else:
        with annotate("ingest_votes"):
            if inflight.enabled(cfg):
                # Async query lifecycle (ops/inflight.py): stamp this
                # round's polls with per-draw latencies (+ the fault
                # script's spikes and cuts), enqueue them, then run the
                # delivery/expiry pass over the whole ring.
                # SEQUENTIAL-only (config-validated).
                lat = inflight.draw_latency(k_sample, cfg, peers,
                                            state.latency_weight, n)
                lat = adversary.apply_policy_latency(cfg, lat, lie,
                                                     withheld)
                lat = inflight.apply_faults(lat, cfg, state.round, 0,
                                            peers, n, state.fault_params)
                ring = inflight.enqueue(state.inflight, state.round, peers,
                                        lat, responded, lie, polled)
                records, changed, votes_applied = (
                    inflight.deliver_multi_engine(
                        ring, state.records, cfg, packed_prefs, minority_t,
                        k_byz, state.round, t, live_rows=state.alive,
                        ctx=pol))
            elif cfg.vote_mode is VoteMode.SEQUENTIAL:
                records, changed = vr.register_packed_votes_engine(
                    state.records, yes_pack, consider_pack, cfg.k, cfg,
                    update_mask=polled)
                votes_applied = (popcnt_plane(consider_pack)
                                 * polled).sum()
            else:
                thresh = math.ceil(cfg.alpha * cfg.k)
                yes_cnt = popcnt_plane(yes_pack & consider_pack)
                no_cnt = popcnt_plane(~yes_pack & consider_pack)
                err = jnp.where(yes_cnt >= thresh, jnp.int32(0),
                                jnp.where(no_cnt >= thresh, jnp.int32(1),
                                          jnp.int32(-1)))
                records, changed = vr.register_vote(state.records, err,
                                                    cfg,
                                                    update_mask=polled)
                votes_applied = ((err >= 0) & polled).sum()

    # --- lifecycle + telemetry.
    fin_after = vr.has_finalized(records.confidence, cfg)
    newly_final = fin_after & jnp.logical_not(fin)
    finalized_at = stamp_finality(state.finalized_at, newly_final,
                                  state.round)

    alive = state.alive
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability, (n,))
        alive = jnp.logical_xor(alive, toggle)
    alive = inflight.apply_churn_bursts(alive, cfg, state.round, k_churn)

    # Async-era counters (PR 5): ring-entry accounting from the no-T
    # latency planes plus the issue-time fault cut — all statically
    # zero when the in-flight engine / fault script is off.
    rt = inflight.ring_telemetry(ring, cfg, state.round)
    cut = (inflight.partition_cut(cfg, state.round, 0, peers, n,
                                  state.fault_params)
           if inflight.enabled(cfg) else None)
    telemetry = SimTelemetry(
        polls=polled.sum().astype(jnp.int32),
        votes_applied=votes_applied.astype(jnp.int32),
        flips=(changed & jnp.logical_not(newly_final)).sum().astype(jnp.int32),
        finalizations=newly_final.sum().astype(jnp.int32),
        admissions=admissions,
        deliveries=rt.deliveries,
        expiries=rt.expiries,
        ring_occupancy=rt.occupancy,
        partition_blocked=(jnp.int32(0) if cut is None
                           else cut.sum().astype(jnp.int32)),
        gossip_writes=gossip_writes,
    )
    obs_sink.emit_round(cfg, state.round, telemetry)
    new_state = AvalancheSimState(
        records=records,
        added=added,
        valid=state.valid,
        score_rank=state.score_rank,
        poll_order=state.poll_order,
        poll_order_inv=state.poll_order_inv,
        byzantine=state.byzantine,
        alive=alive,
        latency_weight=state.latency_weight,
        finalized_at=finalized_at,
        round=state.round + 1,
        key=k_next,
        inflight=ring,
        fault_params=state.fault_params,
        trace=obs_trace.write_round(state.trace, cfg, state.round,
                                    telemetry),
    )
    return new_state, telemetry


def all_settled(state: AvalancheSimState,
                cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """True when no (live node, valid target) pair still needs polling —
    the batched "out of invs" condition (`main.go:127-130`)."""
    fin = vr.has_finalized(state.records.confidence, cfg)
    pollable = (state.added & state.alive[:, None] & state.valid[None, :]
                & jnp.logical_not(fin))
    return jnp.logical_not(pollable.any())


# Bounded: a config sweep (examples/churn_tolerance.py builds dozens of
# distinct cfgs) must not pin every compiled executable for process
# lifetime — evicting the jitted wrapper lets jax's per-function compile
# cache go with it.
@functools.lru_cache(maxsize=32)
def _compiled_run(cfg: AvalancheConfig, max_rounds: int, donate: bool):
    def go(state: AvalancheSimState) -> AvalancheSimState:
        def cond(s: AvalancheSimState) -> jax.Array:
            return (jnp.logical_not(all_settled(s, cfg))
                    & (s.round < max_rounds))

        def body(s: AvalancheSimState) -> AvalancheSimState:
            return round_step(s, cfg)[0]

        return lax.while_loop(cond, body, state)

    return jax.jit(go, donate_argnums=(0,) if donate else ())


def run(
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 2000,
    donate: bool = False,
) -> AvalancheSimState:
    """Run until the network settles (or `max_rounds`); single compile.

    Jits itself (keyed on the static cfg/max_rounds/donate) — callers no
    longer wrap it in `jax.jit`.  `donate=True` threads `donate_argnums`
    through so the ``[N, T]`` planes update IN PLACE instead of
    double-buffering in HBM: the input state's buffers are consumed and
    must not be reused afterwards (on backends without donation support,
    e.g. CPU, jax falls back to copies with a warning).
    """
    return _compiled_run(cfg, int(max_rounds), bool(donate))(state)


@functools.lru_cache(maxsize=32)  # bounded — see _compiled_run
def _compiled_run_scan(cfg: AvalancheConfig, n_rounds: int, donate: bool):
    def go(state: AvalancheSimState):
        def step(s: AvalancheSimState, _):
            return round_step(s, cfg)

        return lax.scan(step, state, None, length=n_rounds)

    return jax.jit(go, donate_argnums=(0,) if donate else ())


def run_scan(
    state: AvalancheSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 200,
    donate: bool = False,
) -> Tuple[AvalancheSimState, SimTelemetry]:
    """Fixed-round run with stacked per-round telemetry (bench/curves).

    Self-jitting, with the same `donate` contract as `run`.
    """
    return _compiled_run_scan(cfg, int(n_rounds), bool(donate))(state)
