"""Slush and Snowflake: the rest of the Avalanche protocol family.

The reference implements only the Snowball vote-record machine
(`vote.go:24-98`, transcribed from Bitcoin ABC) but its stated purpose is
"creation of Avalanche-based consensus systems" (`README.md:11`) and it
links the Avalanche paper (`README.md:15`), whose protocol family is

    Slush      — memoryless: adopt any alpha-majority color seen in a poll;
                 run a fixed number of rounds.
    Snowflake  — Slush + a conviction counter: accept a color after beta
                 consecutive alpha-majority polls for it; any flip resets.
    Snowball   — Snowflake + per-color confidence (the reference's windowed
                 variant lives in `models/snowball.py` / `ops/voterecord`).
    Avalanche  — Snowball over a DAG of conflict sets (`models/dag.py`).

These two single-decree models complete the family for protocol-comparison
sweeps (rounds-to-finality and safety-failure curves across the family are
the paper's fig. 2-4). Both reuse the simulator's peer-sampling and fault
model; parameters map as: k = cfg.k, alpha = cfg.alpha, beta =
cfg.finalization_score, m (slush rounds) = caller's round budget.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.ops import adversary
from go_avalanche_tpu.ops.sampling import sample_peers_uniform


class SlushState(NamedTuple):
    """``[N]`` color plane + fault masks; no per-node memory beyond color."""

    color: jax.Array      # bool [N] — current color (True = yes)
    byzantine: jax.Array  # bool [N]
    alive: jax.Array      # bool [N]
    round: jax.Array      # int32 scalar
    key: jax.Array        # PRNG key


class SnowflakeState(NamedTuple):
    """Slush plus a conviction counter and acceptance stamp."""

    color: jax.Array        # bool [N]
    count: jax.Array        # int32 [N] — consecutive successes for color
    accepted_at: jax.Array  # int32 [N] — round of acceptance; -1 before
    byzantine: jax.Array    # bool [N]
    alive: jax.Array        # bool [N]
    round: jax.Array        # int32 scalar
    key: jax.Array          # PRNG key


class FamilyTelemetry(NamedTuple):
    yes_colors: jax.Array   # int32 — nodes currently colored yes
    switches: jax.Array     # int32 — nodes that changed color this round
    accepted: jax.Array     # int32 — nodes accepted so far (0 for slush)


def _init_colors(key, n_nodes, cfg, yes_fraction):
    k_pref, k_next = jax.random.split(key)
    color = jax.random.bernoulli(k_pref, yes_fraction, (n_nodes,))
    n_byz = int(round(cfg.byzantine_fraction * n_nodes))
    byzantine = jnp.arange(n_nodes) < n_byz
    return color, byzantine, k_next


def _poll_majorities(state, cfg: AvalancheConfig):
    """Shared poll: sample k peers, apply faults, return (yes_maj, no_maj,
    churned alive mask, next key) — the alpha-majority test both protocols
    share."""
    n = state.color.shape[0]
    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(state.key, 5)

    peers = sample_peers_uniform(k_sample, n, cfg.k, cfg.exclude_self,
                                 with_replacement=cfg.sample_with_replacement)
    votes = state.color[peers]                                # [N, k]
    lie = adversary.lie_mask(k_byz, peers, state.byzantine, cfg)
    votes = adversary.apply_1d(k_byz, votes, lie, cfg, state.color)
    responded = state.alive[peers]
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    thresh = math.ceil(cfg.alpha * cfg.k)
    yes_cnt = (votes & responded).sum(axis=1)
    no_cnt = (jnp.logical_not(votes) & responded).sum(axis=1)

    alive = state.alive
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability, (n,))
        alive = jnp.logical_xor(alive, toggle)
    return yes_cnt >= thresh, no_cnt >= thresh, alive, k_next


# --------------------------------------------------------------------------
# Slush


def slush_init(key, n_nodes: int, cfg: AvalancheConfig = DEFAULT_CONFIG,
               yes_fraction: float = 0.5) -> SlushState:
    color, byzantine, k_next = _init_colors(key, n_nodes, cfg, yes_fraction)
    return SlushState(color=color, byzantine=byzantine,
                      alive=jnp.ones((n_nodes,), jnp.bool_),
                      round=jnp.int32(0), key=k_next)


def slush_round(state: SlushState,
                cfg: AvalancheConfig = DEFAULT_CONFIG,
                ) -> Tuple[SlushState, FamilyTelemetry]:
    """One memoryless round: adopt whichever color won an alpha-majority."""
    yes_maj, no_maj, alive, k_next = _poll_majorities(state, cfg)
    new_color = jnp.where(yes_maj, True,
                          jnp.where(no_maj, False, state.color))
    new_color = jnp.where(state.alive, new_color, state.color)
    tel = FamilyTelemetry(
        yes_colors=new_color.sum().astype(jnp.int32),
        switches=(new_color != state.color).sum().astype(jnp.int32),
        accepted=jnp.int32(0),
    )
    return SlushState(color=new_color, byzantine=state.byzantine,
                      alive=alive, round=state.round + 1, key=k_next), tel


def slush_run(state: SlushState, cfg: AvalancheConfig = DEFAULT_CONFIG,
              m_rounds: int = 100) -> Tuple[SlushState, FamilyTelemetry]:
    """The paper's Slush loop: exactly m rounds, stacked telemetry."""

    def body(s, _):
        new_s, t = slush_round(s, cfg)
        return new_s, t

    return lax.scan(body, state, None, length=m_rounds)


# --------------------------------------------------------------------------
# Snowflake


def snowflake_init(key, n_nodes: int,
                   cfg: AvalancheConfig = DEFAULT_CONFIG,
                   yes_fraction: float = 0.5) -> SnowflakeState:
    color, byzantine, k_next = _init_colors(key, n_nodes, cfg, yes_fraction)
    n = n_nodes
    return SnowflakeState(color=color, count=jnp.zeros((n,), jnp.int32),
                          accepted_at=jnp.full((n,), -1, jnp.int32),
                          byzantine=byzantine,
                          alive=jnp.ones((n,), jnp.bool_),
                          round=jnp.int32(0), key=k_next)


def snowflake_round(state: SnowflakeState,
                    cfg: AvalancheConfig = DEFAULT_CONFIG,
                    ) -> Tuple[SnowflakeState, FamilyTelemetry]:
    """One round: alpha-majority for my color -> count += 1; for the other
    -> switch and count = 1; inconclusive -> count = 0 (the paper resets on
    any unsuccessful query). Accepted nodes are frozen but keep answering
    polls with their accepted color."""
    beta = cfg.finalization_score
    accepted = state.accepted_at >= 0
    yes_maj, no_maj, alive, k_next = _poll_majorities(state, cfg)

    maj_for_mine = jnp.where(state.color, yes_maj, no_maj)
    maj_for_other = jnp.where(state.color, no_maj, yes_maj)
    new_color = jnp.where(maj_for_other, jnp.logical_not(state.color),
                          state.color)
    new_count = jnp.where(maj_for_mine, state.count + 1,
                          jnp.where(maj_for_other, jnp.int32(1),
                                    jnp.int32(0)))

    frozen = accepted | jnp.logical_not(state.alive)
    new_color = jnp.where(frozen, state.color, new_color)
    new_count = jnp.where(frozen, state.count, new_count)

    newly_accepted = (new_count >= beta) & jnp.logical_not(accepted)
    accepted_at = jnp.where(newly_accepted, state.round, state.accepted_at)

    tel = FamilyTelemetry(
        yes_colors=new_color.sum().astype(jnp.int32),
        switches=((new_color != state.color)
                  & jnp.logical_not(frozen)).sum().astype(jnp.int32),
        accepted=(accepted_at >= 0).sum().astype(jnp.int32),
    )
    return SnowflakeState(color=new_color, count=new_count,
                          accepted_at=accepted_at,
                          byzantine=state.byzantine, alive=alive,
                          round=state.round + 1, key=k_next), tel


def snowflake_run(state: SnowflakeState,
                  cfg: AvalancheConfig = DEFAULT_CONFIG,
                  max_rounds: int = 10_000) -> SnowflakeState:
    """Run until every live node accepted (or `max_rounds`); one compile."""

    def cond(s: SnowflakeState) -> jax.Array:
        live_undone = ((s.accepted_at < 0) & s.alive).any()
        return live_undone & (s.round < max_rounds)

    def body(s: SnowflakeState) -> SnowflakeState:
        new_s, _ = snowflake_round(s, cfg)
        return new_s

    return lax.while_loop(cond, body, state)
