"""Single-decree Snowball: N nodes deciding one binary question.

The minimum end-to-end slice (SURVEY.md section 7 phase 2, BASELINE config
"Snowball single-decree: 1k nodes, 1 binary decision").  The whole network is
one `VoteRecordState` of shape ``[nodes]``; a round is:

    sample k random peers per node  ->  gather their preferences  ->
    adversary/drop transforms       ->  fused window update

which replaces the reference example's goroutine-per-node poll loop
(`examples/basic-preconcensus/main.go:91-166`) with one jitted step function
`lax.scan`/`while_loop`-ed across rounds.

Divergence from the reference example, by design: a node whose record
finalized keeps answering polls with its *final* preference.  The example
instead deletes the record and re-admits on the next poll with the target's
initial prior (`processor.go:114-116` + `main.go:177-183`) — an artifact of
its delete-then-gossip plumbing, not of the protocol.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG, VoteMode
from go_avalanche_tpu.obs import sink as obs_sink
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import adversary, inflight, voterecord as vr
from go_avalanche_tpu.ops.sampling import sample_peers_uniform


class SnowballState(NamedTuple):
    """Whole-network state; a pytree of ``[nodes]`` arrays + scalars."""

    records: vr.VoteRecordState   # [N] uint8/uint8/uint16
    byzantine: jax.Array          # bool [N] — adversarial voters
    alive: jax.Array              # bool [N] — churn mask
    finalized_at: jax.Array       # int32 [N]; -1 until finalized
    round: jax.Array              # int32 scalar
    key: jax.Array                # PRNG key
    inflight: Optional[inflight.InflightState] = None
                                  # pending-query ring (ops/inflight.py);
                                  # present iff cfg.async_queries()
    fault_params: Optional[inflight.FaultParams] = None
                                  # realized stochastic fault parameters
                                  # (draw_fault_params); present iff the
                                  # script schedules stochastic events
    trace: Optional[obs_trace.TraceBuffer] = None
                                  # on-device trace plane (obs/trace.py);
                                  # attach with `with_trace` — None =
                                  # statically absent


class RoundTelemetry(NamedTuple):
    """Per-round scalars, accumulated on device (SURVEY.md section 5).

    The async-era ring counters (PR 5) mirror `SimTelemetry`'s at
    (querier, draw) entry granularity; statically zero when the
    in-flight engine is off.
    """

    flips: jax.Array          # int32 — preference flips this round
    finalizations: jax.Array  # int32 — records that finalized this round
    yes_preferences: jax.Array  # int32 — nodes currently preferring yes
    deliveries: jax.Array     # int32 — ring entries delivered this round
    expiries: jax.Array       # int32 — ring entries expired unanswered
    ring_occupancy: jax.Array  # int32 — entries in flight after the round
    partition_blocked: jax.Array  # int32 — this round's draws cut by the
                              # active partition


# The snowball round's trace-plane column manifest (all int32).
TRACE_COLUMNS = obs_trace.columns_from_fields(RoundTelemetry._fields)


def with_trace(state: SnowballState, cfg: AvalancheConfig,
               n_rounds: int) -> SnowballState:
    """Attach the on-device trace plane (obs/trace.py) for an
    `n_rounds`-horizon run; no-op when `cfg.trace_every == 0`."""
    return state._replace(trace=obs_trace.alloc(cfg, n_rounds,
                                                TRACE_COLUMNS))


def init(
    key: jax.Array,
    n_nodes: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    yes_fraction: float = 0.5,
) -> SnowballState:
    """Fresh network: each node seeded yes with prob `yes_fraction`, the
    first `byzantine_fraction` of nodes adversarial."""
    k_pref, k_next = jax.random.split(key)
    initial = jax.random.bernoulli(k_pref, yes_fraction, (n_nodes,))
    n_byz = int(round(cfg.byzantine_fraction * n_nodes))
    byzantine = jnp.arange(n_nodes) < n_byz
    return SnowballState(
        records=vr.init_state(initial),
        byzantine=byzantine,
        alive=jnp.ones((n_nodes,), jnp.bool_),
        finalized_at=jnp.full((n_nodes,), -1, jnp.int32),
        round=jnp.int32(0),
        key=k_next,
        inflight=(inflight.init_ring(cfg, n_nodes)
                  if inflight.enabled(cfg) else None),
        fault_params=inflight.draw_fault_params(cfg, key, n_nodes),
    )


def round_step(
    state: SnowballState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> Tuple[SnowballState, RoundTelemetry]:
    """One simulated network round.  Pure; jit/scan-able."""
    if cfg.round_engine != "phased":
        raise ValueError(
            "round_engine 'megakernel' is wired for the dense avalanche "
            "round only; the snowball/snowflake/slush family keeps the "
            "phased path — the knob would be inert here")
    n = state.records.votes.shape[0]
    k_sample, k_byz, k_drop, k_churn, k_next = jax.random.split(state.key, 5)

    # --- poll: every node samples k peers (`getSuitableNodeToQuery`
    # replacement) and reads their current preference (the example's
    # synchronous `query`, `main.go:168-193`, as a gather).
    peers = sample_peers_uniform(k_sample, n, cfg.k, cfg.exclude_self,
                                 with_replacement=cfg.sample_with_replacement)
    prefs = vr.is_accepted(state.records.confidence)

    # --- adversary: byzantine peers lie with `flip_probability` per draw;
    # what the lie says is `cfg.adversary_strategy` (ops/adversary.py — the
    # reference hook at `main.go:184-187` is strategy FLIP).
    lie = adversary.lie_mask(k_byz, peers, state.byzantine, cfg)

    # --- failure model: dropped responses and dead peers are abstentions
    # (neutral votes model non-responsive peers, `vote.go:56`).
    responded = state.alive[peers]
    if cfg.drop_probability > 0.0:
        responded &= ~jax.random.bernoulli(k_drop, cfg.drop_probability,
                                           peers.shape)

    # --- adaptive adversary (cfg.adversary_policy, ops/adversary.py):
    # per-round context from the pre-round state — scalar honest-split
    # tally for split_vote, per-querier near-quorum gate for
    # withholding; statically absent (None) with the policy off.
    # Snowball carries no stake plane, so stake_eclipse degenerates to
    # uniform weights (and is config-rejected without stake anyway).
    pol = adversary.policy_ctx(cfg, state.records, state.byzantine, None,
                               prefs=prefs)
    lie, responded, withheld = adversary.apply_policy_issue(cfg, pol, lie,
                                                            responded)

    fin_before = vr.has_finalized(state.records.confidence, cfg)
    update_mask = jnp.logical_not(fin_before) & state.alive

    ring = state.inflight
    if inflight.enabled(cfg):
        # Async query lifecycle (ops/inflight.py): the response gather and
        # adversary transform move to DELIVERY time inside `deliver_1d`;
        # this round only stamps latencies and enqueues.  Snowball carries
        # no latency_weight plane, so the "weighted" mode degenerates to
        # uniform weights (all-zero latency).
        lat = inflight.draw_latency(k_sample, cfg, peers,
                                    jnp.ones((n,), jnp.float32), n)
        lat = adversary.apply_policy_latency(cfg, lat, lie, withheld)
        lat = inflight.apply_faults(lat, cfg, state.round, 0, peers, n,
                                    state.fault_params)
        ring = inflight.enqueue(state.inflight, state.round, peers, lat,
                                responded, lie, update_mask)
        records, changed = inflight.deliver_1d_engine(ring, state.records, cfg,
                                               prefs, k_byz, state.round,
                                               live_rows=state.alive,
                                               ctx=pol)
    elif cfg.vote_mode is VoteMode.SEQUENTIAL:
        # Faithful per-vote window semantics: pack the k votes into uint8 bit
        # planes and run k fused window updates (`processor.go:94-117`).
        peer_votes = adversary.apply_1d(k_byz, prefs[peers], lie, cfg,
                                        prefs, pol)
        shifts = jnp.arange(cfg.k, dtype=jnp.uint8)
        yes_pack = (peer_votes.astype(jnp.uint8) << shifts).sum(
            axis=1).astype(jnp.uint8)
        consider_pack = (responded.astype(jnp.uint8) << shifts).sum(
            axis=1).astype(jnp.uint8)
        records, changed = vr.register_packed_votes_engine(
            state.records, yes_pack, consider_pack, cfg.k, cfg, update_mask)
    else:
        # Paper-style majority chit: one conclusive vote per round when
        # >= ceil(alpha*k) of the sampled peers agree, else neutral.
        peer_votes = adversary.apply_1d(k_byz, prefs[peers], lie, cfg,
                                        prefs, pol)
        thresh = math.ceil(cfg.alpha * cfg.k)
        yes_cnt = (peer_votes & responded).sum(axis=1)
        no_cnt = (jnp.logical_not(peer_votes) & responded).sum(axis=1)
        err = jnp.where(yes_cnt >= thresh, jnp.int32(0),
                        jnp.where(no_cnt >= thresh, jnp.int32(1),
                                  jnp.int32(-1)))
        records, changed = vr.register_vote(state.records, err, cfg,
                                            update_mask)

    # --- lifecycle + telemetry
    fin_after = vr.has_finalized(records.confidence, cfg)
    newly_final = fin_after & jnp.logical_not(fin_before)
    finalized_at = jnp.where(
        newly_final & (state.finalized_at < 0),
        state.round, state.finalized_at)

    # --- churn: nodes toggle dead<->alive (+ scheduled churn bursts).
    alive = state.alive
    if cfg.churn_probability > 0.0:
        toggle = jax.random.bernoulli(k_churn, cfg.churn_probability, (n,))
        alive = jnp.logical_xor(alive, toggle)
    alive = inflight.apply_churn_bursts(alive, cfg, state.round, k_churn)

    rt = inflight.ring_telemetry(ring, cfg, state.round)
    cut = (inflight.partition_cut(cfg, state.round, 0, peers, n,
                                  state.fault_params)
           if inflight.enabled(cfg) else None)
    telemetry = RoundTelemetry(
        flips=(changed & jnp.logical_not(newly_final)).sum().astype(jnp.int32),
        finalizations=newly_final.sum().astype(jnp.int32),
        yes_preferences=vr.is_accepted(
            records.confidence).sum().astype(jnp.int32),
        deliveries=rt.deliveries,
        expiries=rt.expiries,
        ring_occupancy=rt.occupancy,
        partition_blocked=(jnp.int32(0) if cut is None
                           else cut.sum().astype(jnp.int32)),
    )
    obs_sink.emit_round(cfg, state.round, telemetry)
    new_state = SnowballState(
        records=records,
        byzantine=state.byzantine,
        alive=alive,
        finalized_at=finalized_at,
        round=state.round + 1,
        key=k_next,
        inflight=ring,
        fault_params=state.fault_params,
        trace=obs_trace.write_round(state.trace, cfg, state.round,
                                    telemetry),
    )
    return new_state, telemetry


def run(
    state: SnowballState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 1000,
) -> SnowballState:
    """Run rounds until every live node finalized (or `max_rounds`).

    Early exit via `lax.while_loop`; compile once, no host round-trips.
    """

    def cond(s: SnowballState) -> jax.Array:
        live_unfinished = (jnp.logical_not(
            vr.has_finalized(s.records.confidence, cfg)) & s.alive).any()
        return live_unfinished & (s.round < max_rounds)

    def body(s: SnowballState) -> SnowballState:
        new_s, _ = round_step(s, cfg)
        return new_s

    return lax.while_loop(cond, body, state)


def run_scan(
    state: SnowballState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 200,
) -> Tuple[SnowballState, RoundTelemetry]:
    """Run a fixed number of rounds, returning stacked per-round telemetry
    (for rounds-to-finality curves and benchmarking)."""

    def step(s: SnowballState, _):
        new_s, t = round_step(s, cfg)
        return new_s, t

    return lax.scan(step, state, None, length=n_rounds)
