"""Batched network simulators (layer L4)."""
