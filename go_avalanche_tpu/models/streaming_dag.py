"""Streaming conflict-set DAG: the north-star workload in bounded HBM.

BASELINE.json's north star is 100k nodes x 1M *pending* txs where "the UTXO
conflict-set DAG ... [is] sharded over the mesh" — conflicting spends must
be resolved, not just independent txs settled.  `models/backlog` streams 1M
independent txs through a bounded window; `models/dag` resolves conflicts
densely.  This module composes them: the admission unit becomes the whole
**conflict set**, so double-spend resolution happens inside the bounded
``[nodes, window]`` working set while the 1M-tx conflict graph waits as
cheap ``[sets, c]`` metadata.

The design hinges on one invariant that keeps every shape static: conflict
sets are stored at a fixed capacity ``c`` (short sets pad with invalid
lanes, which never poll — invalid targets stop polling,
`processor.go:155-157`), and the window is ``S_w`` set-slots of ``c``
contiguous tx slots.  The window's conflict partition is therefore the
*constant* ``arange(W) // c`` — independent of which backlog sets currently
occupy the slots — so:

  * the inner consensus round is **exactly `models/dag.round_step`** on a
    `DagSimState` whose `conflict_set` never changes: preferred-in-set
    responses, rival-settled freezes, every adversary/fault knob, and the
    tx-shard-compatible segment layout all compose unchanged;
  * retire/refill is the `models/backlog` scheduler lifted from tx
    granularity to set granularity: one cumsum ranks free set-slots, one
    row-scatter per output plane writes retiring sets' member outcomes.

A set-slot retires when no (live node, member) pair is pollable any more —
winners finalized, rivals frozen by the winner (the per-node settle freeze,
`models/dag.py`), stragglers finalized rejected, or the set invalid.  That
is the set-granular form of the reference's all-nodes-finalized condition
(`examples/basic-preconcensus/main.go:159-161`) and subsumes the
degenerate no-winner outcome, so a pathological set cannot wedge its slot.

Reference seams, for parity review: admission order restores the intended
score-descending sort (`avalanche.go:162-174`, disabled at
`processor.go:163`) at set granularity (a set's score is its best
member's); retirement mirrors delete-on-finalize (`processor.go:114-116`);
outcomes record the network-majority winner per set.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu import traffic as tf
from go_avalanche_tpu.config import (
    AvalancheConfig,
    DEFAULT_CONFIG,
    suppress_taps,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.models import dag as dag_model
from go_avalanche_tpu.models.backlog import NO_TX
from go_avalanche_tpu.obs import sink as obs_sink
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import inflight
from go_avalanche_tpu.ops import voterecord as vr

NO_SET = NO_TX  # empty set-slot sentinel (-1), NoNode spirit (`avalanche.go:28`)


class SetBacklog(NamedTuple):
    """The pending conflict graph: ``[S_b, c]`` member planes.

    Row s holds conflict set s's members at fixed capacity ``c``; short
    sets pad with ``valid=False`` lanes.  Row order is admission order —
    build with `make_set_backlog` for the intended score-descending order.
    """

    score: jax.Array      # int32 [S_b, c]
    init_pref: jax.Array  # bool  [S_b, c] — Target.IsAccepted() prior
    valid: jax.Array      # bool  [S_b, c]


class SetOutputs(NamedTuple):
    """Per-member settlement results, written as sets retire; [S_b, c]."""

    settled: jax.Array       # bool  [S_b, c]
    accepted: jax.Array      # bool  [S_b, c] — network-majority winner lane
    accept_votes: jax.Array  # int32 [S_b, c] — nodes finalized-accepted
    settle_round: jax.Array  # int32 [S_b, c]
    admit_round: jax.Array   # int32 [S_b, c]


class StreamingDagState(NamedTuple):
    """Active conflict window + set backlog + outputs."""

    dag: dag_model.DagSimState  # window: [N, W] records, static arange(W)//c
    slot_set: jax.Array         # int32 [S_w] — backlog set per set-slot
    slot_admit_round: jax.Array  # int32 [S_w]
    backlog: SetBacklog         # [S_b, c]
    outputs: SetOutputs         # [S_b, c]
    next_idx: jax.Array         # int32 — next unadmitted backlog set
    traffic: Optional[tf.TrafficState] = None
                                # live-traffic plane (go_avalanche_tpu/
                                #   traffic.py) at SET granularity —
                                #   present iff cfg.arrivals_enabled():
                                #   admission gated on the arrived
                                #   watermark; a retiring set records
                                #   one latency sample per VALID member
                                #   tx.  None = the seed drain path,
                                #   statically absent


def set_capacity(state: StreamingDagState) -> int:
    return state.backlog.score.shape[1]


def make_set_backlog(
    scores: jax.Array,
    init_pref: Optional[jax.Array] = None,
    valid: Optional[jax.Array] = None,
) -> SetBacklog:
    """Sort sets into score-descending admission order (stable on ties).

    All inputs are ``[S_b, c]``; a set's admission score is its best valid
    member's (the set-granular `sortBlockInvsByWork`, `avalanche.go:185`).
    `init_pref` defaults to "first valid member preferred" — the
    deterministic first-seen stand-in used by `models/dag.init`.
    """
    scores = jnp.asarray(scores, jnp.int32)
    s_b, c = scores.shape
    if valid is None:
        valid = jnp.ones((s_b, c), jnp.bool_)
    valid = jnp.asarray(valid, jnp.bool_)
    if init_pref is None:
        first_valid = jnp.argmax(valid, axis=1)
        init_pref = (jnp.arange(c)[None, :] == first_valid[:, None]) & valid
    init_pref = jnp.asarray(init_pref, jnp.bool_)
    set_score = jnp.where(valid, scores, jnp.int32(-2**31 + 1)).max(axis=1)
    order = jnp.argsort(-set_score, stable=True)
    return SetBacklog(score=scores[order], init_pref=init_pref[order],
                      valid=valid[order])


def init(
    key: jax.Array,
    n_nodes: int,
    window_sets: int,
    backlog: SetBacklog,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    track_finality: bool = True,
) -> StreamingDagState:
    """Empty window over a fresh set backlog; first refill is in step 0.

    `track_finality=False` drops the per-(node, tx) `finalized_at` plane
    (`models/avalanche.AvalancheSimState`): streaming latency metrics come
    from the per-set `SetOutputs` rounds, so the plane is pure overhead
    here — an int32 [N, W] read+write per round at north-star shape.
    """
    s_b, c = backlog.score.shape
    w = window_sets * c
    base = av.init(key, n_nodes, w, cfg,
                   added=jnp.zeros((n_nodes, w), jnp.bool_),
                   valid=jnp.zeros((w,), jnp.bool_),
                   track_finality=track_finality)
    window_dag = dag_model.DagSimState(
        base=base,
        conflict_set=jnp.arange(w, dtype=jnp.int32) // c,
        n_sets=window_sets,
        set_size=c,   # static witness: the window partition is arange//c
    )
    zeros = jnp.zeros((s_b, c), jnp.int32)
    return StreamingDagState(
        dag=window_dag,
        slot_set=jnp.full((window_sets,), NO_SET, jnp.int32),
        slot_admit_round=jnp.zeros((window_sets,), jnp.int32),
        backlog=backlog,
        outputs=SetOutputs(
            settled=jnp.zeros((s_b, c), jnp.bool_),
            accepted=jnp.zeros((s_b, c), jnp.bool_),
            accept_votes=zeros,
            settle_round=zeros - 1,
            admit_round=zeros - 1,
        ),
        next_idx=jnp.int32(0),
        traffic=tf.init_traffic(cfg, key, s_b),
    )


def _settled_set_slots(state: StreamingDagState,
                       cfg: AvalancheConfig) -> jax.Array:
    """bool [S_w]: occupied set-slots the network is done with.

    Done = no (live node, member) pair is still pollable: each node either
    saw a member finalize accepted (freezing its rivals), or every member
    it reconciles is finalized/invalid.  Mirrors the pollable mask of
    `dag.round_step` so retirement and polling can never disagree.
    """
    base = state.dag.base
    n, w = base.records.votes.shape
    c = set_capacity(state)
    s_w = w // c
    occupied = state.slot_set != NO_SET

    fin = vr.has_finalized(base.records.confidence, cfg)
    fin_acc = fin & vr.is_accepted(base.records.confidence)
    # Static window partition => segment ops are reshapes.
    node_set_done = fin_acc.reshape(n, s_w, c).any(axis=2)      # [N, S_w]
    rival_settled = (jnp.repeat(node_set_done, c, axis=1)
                     & jnp.logical_not(fin_acc))
    pending = (base.added & base.alive[:, None] & base.valid[None, :]
               & jnp.logical_not(fin) & jnp.logical_not(rival_settled))
    pending_set = pending.reshape(n, s_w, c).any(axis=(0, 2))   # [S_w]
    return occupied & jnp.logical_not(pending_set)


def _retire_and_refill(
    state: StreamingDagState,
    cfg: AvalancheConfig,
    refill: bool = True,
) -> Tuple[StreamingDagState, jax.Array]:
    """Write retiring sets' member outcomes; refill free set-slots.

    The `models/backlog` scheduler at set granularity: one cumsum for the
    slot->backlog-set assignment, one row-scatter per output plane.
    Returns (new_state, sets retired).

    With `cfg.stream_retire_cap` set, at most that many set-slots
    retire+refill per round, and only THEIR window columns are rewritten
    (gather + scatter over <= cap*c columns) instead of a full-plane
    `where` per record plane — the scheduler's [N, W] traffic drops from
    every-element-every-round to proportional-to-churn (PERF_NOTES.md
    "Streaming step traffic split").  Over-cap slots simply stay settled
    and retire on a later round, so any cap is live; when a round's
    settled+empty slots fit the cap, the trajectory is bit-identical to
    the dense path.  The end-of-run harvest (`refill=False`) always runs
    dense so no settled slot is left unrecorded.
    """
    base = state.dag.base
    n, w = base.records.votes.shape
    c = set_capacity(state)
    s_w = w // c
    s_b = state.backlog.score.shape[0]
    settled = _settled_set_slots(state, cfg)
    empty = state.slot_set == NO_SET
    cap = cfg.stream_retire_cap
    sparse = refill and cap is not None
    if sparse:
        k_slots = min(cap, s_w)
        pool = settled | empty   # slots that could retire or admit
        participate = pool & (jnp.cumsum(pool.astype(jnp.int32)) - 1
                              < k_slots)
        settled = settled & participate
        free = participate
    else:
        free = settled | empty

    # --- live traffic: a retiring set records one latency sample per
    # VALID member tx at the set's arrival -> settle latency; admission
    # below is gated on the arrived watermark.
    traffic = state.traffic
    if traffic is not None:
        rows_safe = jnp.clip(state.slot_set, 0, s_b - 1)
        lat = base.round - traffic.arrival_round[rows_safe]
        members = state.backlog.valid[rows_safe].sum(axis=1).astype(
            jnp.int32)
        traffic = traffic._replace(
            lat_hist=traffic.lat_hist + tf.latency_delta(
                cfg, lat, jnp.where(settled, members, 0)))

    # --- retire: member outcomes at the retiring sets' backlog rows.
    conf = base.records.confidence
    fin_acc = vr.has_finalized(conf, cfg) & vr.is_accepted(conf)
    accept_votes = (fin_acc & base.added).sum(axis=0).astype(jnp.int32)  # [W]
    n_live = jnp.maximum(base.alive.sum().astype(jnp.int32), 1)
    accepted = accept_votes * 2 > n_live                                 # [W]

    row_idx = jnp.where(settled, state.slot_set, s_b)   # s_b = dropped write
    out = state.outputs

    def scatter(plane, rows):
        return plane.at[row_idx].set(jnp.broadcast_to(rows, (s_w, c)),
                                     mode="drop")

    out = SetOutputs(
        settled=scatter(out.settled, jnp.bool_(True)),
        accepted=scatter(out.accepted, accepted.reshape(s_w, c)),
        accept_votes=scatter(out.accept_votes, accept_votes.reshape(s_w, c)),
        settle_round=scatter(out.settle_round,
                             base.round.astype(jnp.int32)),
        admit_round=scatter(out.admit_round,
                            state.slot_admit_round[:, None]),
    )

    # --- refill: free set-slots take the next backlog sets in order.
    rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    cand = state.next_idx + rank
    avail = s_b if traffic is None else jnp.minimum(jnp.int32(s_b),
                                                    traffic.arrived_idx)
    take = free & (cand < avail)
    if not refill:   # end-of-run harvest: record outcomes, admit nothing
        take = jnp.zeros_like(take)
    new_set = jnp.where(take, cand, jnp.where(settled, NO_SET,
                                              state.slot_set))
    n_taken = take.sum().astype(jnp.int32)

    cand_safe = jnp.clip(cand, 0, s_b - 1)
    pref_rows = state.backlog.init_pref[cand_safe]               # [S_w, c]
    take_w = jnp.repeat(take, c)                                 # [W]
    occupied_after_w = jnp.repeat(new_set != NO_SET, c)

    if sparse:
        # Columns of slots that actually change: retiring (clear) or
        # admitting (fresh seed).  take ⊆ free and settled ⊆ free, so the
        # static bound k_slots holds; fill rows land at slot id s_w =>
        # column >= W => scatter mode="drop".
        changed = settled | take
        slot_ids = jnp.nonzero(changed, size=k_slots,
                               fill_value=s_w)[0]                # [K]
        sid_safe = jnp.minimum(slot_ids, s_w - 1)
        cols = (slot_ids[:, None].astype(jnp.int32) * c
                + jnp.arange(c, dtype=jnp.int32)[None, :]).reshape(-1)
        cols_safe = jnp.minimum(cols, w - 1)
        take_cols = jnp.repeat(take[sid_safe], c)                # [K*c]
        fresh = vr.init_state(pref_rows[sid_safe].reshape(-1)[None, :])

        def fill_cols(plane, fresh_plane):
            # Admitted columns seed fresh (row-constant); retiring-only
            # columns write their old values back (records of cleared
            # slots are dead: added/valid mask them out of every poll).
            upd = jnp.where(take_cols[None, :], fresh_plane,
                            plane[:, cols_safe])
            return plane.at[:, cols].set(upd.astype(plane.dtype),
                                         mode="drop")

        records = vr.VoteRecordState(
            votes=fill_cols(base.records.votes, fresh.votes),
            consider=fill_cols(base.records.consider, fresh.consider),
            confidence=fill_cols(base.records.confidence, fresh.confidence),
        )
        # Admission seeds every node (the reference example feeds every tx
        # to every node up front, `main.go:49-53`); retired slots clear.
        # Unchanged empty slots are already False (cleared when retired),
        # so touching only changed columns preserves the dense result.
        added = base.added.at[:, cols].set(
            jnp.broadcast_to(take_cols[None, :], (n, k_slots * c)),
            mode="drop")
        if base.finalized_at is None:
            finalized_at = None
        else:   # dense resets stamps only at re-admitted columns
            fa_upd = jnp.where(take_cols[None, :], jnp.int32(-1),
                               base.finalized_at[:, cols_safe])
            finalized_at = base.finalized_at.at[:, cols].set(fa_upd,
                                                             mode="drop")
    else:
        pref_w = pref_rows.reshape(w)                            # [W]
        # Fresh record values are row-constant (every node seeds a
        # re-admitted column identically): build them at [1, W] and let
        # the fill `where` broadcast.  (Cost analysis shows XLA fused the
        # explicit [N, W] broadcast this replaces, so this is clarity,
        # not traffic — PERF_NOTES.md.)
        fresh = vr.init_state(pref_w[None, :])

        def fill(plane, fresh_plane):
            return jnp.where(take_w[None, :], fresh_plane, plane)

        records = vr.VoteRecordState(
            votes=fill(base.records.votes, fresh.votes),
            consider=fill(base.records.consider, fresh.consider),
            confidence=fill(base.records.confidence, fresh.confidence),
        )
        # Admission seeds every node (the reference example feeds every tx
        # to every node up front, `main.go:49-53`); retired slots clear.
        added = jnp.where(take_w[None, :], True,
                          base.added & occupied_after_w[None, :])
        finalized_at = av.reset_finality(base.finalized_at, take_w)

    safe_rows = jnp.clip(new_set, 0, s_b - 1)
    valid = jnp.where(take_w,
                      state.backlog.valid[cand_safe].reshape(w),
                      base.valid & occupied_after_w)
    score = jnp.where(occupied_after_w,
                      state.backlog.score[safe_rows].reshape(w),
                      jnp.int32(-2**31 + 1))

    score_rank, poll_order, poll_order_inv = av.score_rank_with_orders(score)
    new_base = base._replace(
        records=records,
        added=added,
        valid=valid,
        score_rank=score_rank,
        poll_order=poll_order,
        poll_order_inv=poll_order_inv,
        finalized_at=finalized_at,
        # Responses still in flight for a retired set-slot must not land
        # on its NEW occupant: drop the freed columns from every pending
        # ring entry's poll mask (no-op when the async engine is off).
        inflight=inflight.clear_columns(base.inflight,
                                        jnp.repeat(settled | take, c)),
    )
    return StreamingDagState(
        dag=dag_model.DagSimState(new_base, state.dag.conflict_set,
                                  state.dag.n_sets, state.dag.set_size),
        slot_set=new_set,
        slot_admit_round=jnp.where(take, base.round,
                                   state.slot_admit_round),
        backlog=state.backlog,
        outputs=out,
        next_idx=state.next_idx + n_taken,
        traffic=traffic,
    ), settled.sum().astype(jnp.int32)


class StreamingDagTelemetry(NamedTuple):
    """Per-step scalars: inner DAG round telemetry plus scheduler stats."""

    round: av.SimTelemetry
    retired_sets: jax.Array   # int32 — set-slots retired this step
    occupied_sets: jax.Array  # int32 — occupied set-slots after refill
    backlog_left: jax.Array   # int32 — sets not yet admitted
    traffic: Optional[tf.TrafficTelemetry] = None
                              # arrival counters + finality-latency
                              #   percentiles; None (absent from the
                              #   JSONL schema) when arrivals are off


def trace_columns(cfg: AvalancheConfig) -> tuple:
    """The set-scheduler's trace-plane column manifest — the JSONL
    flattening order of `StreamingDagTelemetry`."""
    groups = [av.SimTelemetry._fields,
              ("retired_sets", "occupied_sets", "backlog_left")]
    if cfg.arrivals_enabled():
        groups.append(tf.TrafficTelemetry._fields)
    return obs_trace.columns_from_fields(*groups)


def with_trace(state: StreamingDagState, cfg: AvalancheConfig,
               n_rounds: int) -> StreamingDagState:
    """Attach the on-device trace plane (obs/trace.py) — the SCHEDULER
    owns it, the inner conflict round's write is suppressed (the
    backlog scheduler's contract).  No-op when `cfg.trace_every == 0`."""
    return state._replace(dag=dataclasses.replace(
        state.dag, base=state.dag.base._replace(
            trace=obs_trace.alloc(cfg, n_rounds, trace_columns(cfg)))))


def step(
    state: StreamingDagState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> Tuple[StreamingDagState, StreamingDagTelemetry]:
    """Arrive (traffic mode), retire/refill at set granularity, then one
    conflict round.

    With the in-graph metrics tap on the SCHEDULER emits the full
    `StreamingDagTelemetry` record and suppresses the inner round's own
    emission, so each round writes exactly one JSONL line
    (docs/observability.md) — same contract as `models/backlog.step`,
    and the same for the on-device trace plane (`cfg.trace_every > 0`).
    """
    round_val = state.dag.base.round
    arrivals = jnp.int32(0)
    if state.traffic is not None:
        new_traffic, arrivals = tf.arrive(
            state.traffic, cfg, round_val,
            (state.slot_set != NO_SET).sum().astype(jnp.int32),
            state.slot_set.shape[0])
        state = state._replace(traffic=new_traffic)
    state, retired = _retire_and_refill(state, cfg)
    new_dag, round_tel = dag_model.round_step(state.dag, suppress_taps(cfg))
    tel = StreamingDagTelemetry(
        round=round_tel,
        retired_sets=retired,
        occupied_sets=(state.slot_set != NO_SET).sum().astype(jnp.int32),
        backlog_left=state.backlog.score.shape[0] - state.next_idx,
        traffic=(None if state.traffic is None
                 else tf.traffic_telemetry(state.traffic, arrivals)),
    )
    obs_sink.emit_round(cfg, round_val, tel)
    new_dag = dataclasses.replace(new_dag, base=new_dag.base._replace(
        trace=obs_trace.write_round(new_dag.base.trace, cfg, round_val,
                                    tel)))
    return state._replace(dag=new_dag), tel


def drained(state: StreamingDagState,
            cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """True when the backlog is exhausted and every occupied slot settled."""
    s_b = state.backlog.score.shape[0]
    exhausted = state.next_idx >= s_b
    occupied = state.slot_set != NO_SET
    return exhausted & jnp.logical_not(
        (occupied & jnp.logical_not(_settled_set_slots(state, cfg))).any())


def run(
    state: StreamingDagState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 100_000,
) -> StreamingDagState:
    """Stream the whole conflict graph through the window; single compile."""

    def cond(s: StreamingDagState) -> jax.Array:
        return jnp.logical_not(drained(s, cfg)) & (s.dag.base.round
                                                   < max_rounds)

    def body(s: StreamingDagState) -> StreamingDagState:
        new_s, _ = step(s, cfg)
        return new_s

    final = lax.while_loop(cond, body, state)
    final, _ = _retire_and_refill(final, cfg, refill=False)
    return final


def _run_chunk(
    state: StreamingDagState,
    cfg: AvalancheConfig,
    chunk: int,
    max_rounds: int,
) -> Tuple[StreamingDagState, jax.Array]:
    """At most `chunk` rounds of `run`'s loop; returns (state, drained).

    Identical semantics to the same rounds inside `run` (the while_loop
    checks `drained` before every step), just bounded so one device
    dispatch stays short.  jit with static (cfg, chunk, max_rounds).
    """
    start = state.dag.base.round

    def cond(s: StreamingDagState) -> jax.Array:
        return (jnp.logical_not(drained(s, cfg))
                & (s.dag.base.round < max_rounds)
                & (s.dag.base.round - start < chunk))

    def body(s: StreamingDagState) -> StreamingDagState:
        new_s, _ = step(s, cfg)
        return new_s

    final = lax.while_loop(cond, body, state)
    return final, drained(final, cfg)


# Module-scope jit so repeat run_chunked calls (tests, sweeps, resumed
# drivers) hit the compile cache instead of retracing per call.
_run_chunk_jit = jax.jit(_run_chunk,
                         static_argnames=("cfg", "chunk", "max_rounds"))


def run_chunked(
    state: StreamingDagState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 100_000,
    chunk: int = 256,
    checkpoint_path: Optional[str] = None,
    checkpoint_every_chunks: int = 8,
    checkpoint_fetch_bytes: Optional[int] = 64 << 20,
    checkpoint_fetch_timeout_s: Optional[float] = 120.0,
    progress=None,
) -> StreamingDagState:
    """`run`, dispatched from the host in `chunk`-round device calls.

    Bit-identical final state to `run` (pinned by
    `tests/test_streaming_dag.py::test_run_chunked_matches_run`), but no
    single dispatch exceeds `chunk` rounds.  This is how the north-star
    config (100k nodes x 1M txs, ~8k rounds) is executed on hardware: one
    500k-round `while_loop` dispatch runs >10 minutes and trips the TPU
    worker's liveness watchdog ("TPU worker process crashed or restarted
    ... kernel fault" — the round-3 `benchmarks/results.json` config6
    failure), while ~25s chunks with a host sync between them run to
    completion; a crash then loses one chunk, not the run.

    `checkpoint_path` (optional) saves the state every
    `checkpoint_every_chunks` chunks via `utils/checkpoint` (atomic
    replace), so a killed run resumes from the last checkpoint instead of
    round 0.  Saves run in a BACKGROUND thread: at north-star shape the
    ~1.9 GB device→host fetch takes ~4x a chunk's compute through the
    tunnel (measured; see `benchmarks/PERF_NOTES.md`), so a synchronous
    save would roughly halve throughput.  Device arrays are immutable, so
    snapshotting a chunk-boundary state while later chunks compute is
    race-free; one save runs at a time (boundaries are skipped while one
    is in flight), and the last save is joined before returning, so the
    file exists when this function does.  `progress`, if given, is called
    after every chunk with ``(rounds_done, state)`` — the hook the
    baseline suite uses to log drain rate.

    Each save streams the state in `checkpoint_fetch_bytes`-sized
    transfers with a `checkpoint_fetch_timeout_s` deadline per transfer
    (`save_checkpoint`'s bounded-fetch mode): the round-4 outage was a
    process killed mid-way through one monolithic 1.9 GB fetch, which
    wedged the tunnel for >10 h.  A timed-out or otherwise failed save is
    logged and *dropped* — the run keeps its previous checkpoint and keeps
    computing.  A save failure only surfaces as an exception if the run
    finishes with NO checkpoint successfully written at all and a final
    synchronous retry also fails; otherwise it is reported as a warning so
    a completed computation is never thrown away over a stale-by-one
    checkpoint (the finished state is in the caller's hands anyway).
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    if checkpoint_path and checkpoint_every_chunks < 1:
        raise ValueError("checkpoint_every_chunks must be >= 1, got "
                         f"{checkpoint_every_chunks}")
    import threading
    import warnings

    from go_avalanche_tpu.utils.checkpoint import save_checkpoint

    saver: Optional[threading.Thread] = None
    save_errors: list = []
    saves_ok = [0]

    def _do_save(snapshot):
        save_checkpoint(checkpoint_path, snapshot,
                        max_fetch_bytes=checkpoint_fetch_bytes,
                        fetch_timeout_s=checkpoint_fetch_timeout_s)
        saves_ok[0] += 1

    def _save(snapshot):
        # Capture failures: a daemon thread's exception otherwise only
        # prints to stderr.  A failed save costs a checkpoint, not the run
        # — the next boundary just tries again with fresher state.
        try:
            _do_save(snapshot)
        except Exception as e:  # noqa: BLE001 — surfaced at completion
            save_errors.append(e)
            if len(save_errors) == 1:  # first failure: say so now, in-run
                warnings.warn(f"checkpoint save failed (run continues, "
                              f"will retry next boundary): {e!r}",
                              RuntimeWarning, stacklevel=2)

    try:
        chunks_done = 0
        while True:
            state, done = _run_chunk_jit(state, cfg, chunk, max_rounds)
            # Scalar fetch doubles as the device sync (see bench.py _sync).
            done = bool(jax.device_get(done))
            rounds = int(jax.device_get(state.dag.base.round))
            chunks_done += 1
            if progress is not None:
                progress(rounds, state)
            if (checkpoint_path
                    and chunks_done % checkpoint_every_chunks == 0
                    and (saver is None or not saver.is_alive())):
                saver = threading.Thread(target=_save, args=(state,),
                                         daemon=True)
                saver.start()
            if done or rounds >= max_rounds:
                break
    finally:
        # Always join: an orphaned in-flight save would race a later
        # save_checkpoint to the same tmp path.
        if saver is not None:
            saver.join()
    if checkpoint_path and save_errors:
        if saves_ok[0] == 0:
            # Nothing on disk from this run: one synchronous retry, and
            # only if that also fails does the failure become fatal —
            # the caller asked for resumability it never got.
            try:
                _do_save(state)
            except Exception as e:  # noqa: BLE001
                raise e from save_errors[0]
        if saves_ok[0] > 0 and save_errors:
            warnings.warn(
                f"run completed; {len(save_errors)} checkpoint save(s) "
                f"failed and were dropped (last: {save_errors[-1]!r}); "
                f"latest successful checkpoint kept at {checkpoint_path}",
                RuntimeWarning, stacklevel=2)
    final, _ = _retire_and_refill(state, cfg, refill=False)
    return final


def run_scan(
    state: StreamingDagState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 1000,
) -> Tuple[StreamingDagState, StreamingDagTelemetry]:
    """Fixed-round run with stacked telemetry (bench/throughput curves)."""

    def body(s, _):
        new_s, tel = step(s, cfg)
        return new_s, tel

    return lax.scan(body, state, None, length=n_rounds)


def resolution_summary(state: StreamingDagState) -> dict:
    """Host-side outcome digest: how many sets got exactly one winner."""
    import numpy as np

    out = jax.device_get(state.outputs)
    valid = np.asarray(jax.device_get(state.backlog.valid))
    settled_sets = np.asarray(out.settled).any(axis=1)
    winners = (np.asarray(out.accepted) & valid).sum(axis=1)
    latency = (np.asarray(out.settle_round)
               - np.asarray(out.admit_round))[np.asarray(out.settled)]
    return {
        "sets_settled_fraction": float(settled_sets.mean()),
        "sets_one_winner_fraction": float(
            (winners[settled_sets] == 1).mean()) if settled_sets.any()
        else 0.0,
        "txs_settled": int(np.asarray(out.settled)[valid].sum()),
        "settle_latency_median": float(np.median(latency))
        if latency.size else None,
        "settle_latency_p90": float(np.percentile(latency, 90))
        if latency.size else None,
    }
