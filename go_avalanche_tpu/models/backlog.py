"""Streaming backlog scheduler: 1M-scale tx throughput in bounded HBM.

The north-star workload (BASELINE.json) is 100k nodes × 1M *pending* txs —
but dense ``[nodes, txs]`` state at that size is ~400GB, far beyond any
chip. The reference already contains the answer in miniature: a node never
polls more than `AvalancheMaxElementPoll = 4096` targets at once
(`avalanche.go:17`, truncation at `processor.go:165-167`), and finalized
records are deleted to make room (`processor.go:114-116`). This module
lifts that into a **working-set scheduler**: a bounded window of W active
slots holds dense ``[nodes, W]`` consensus state, while the 1M-tx backlog
lives as cheap ``[B]`` metadata. Slots whose tx the network has settled
retire, their outcome is written to per-tx output arrays, and the freed
slots refill from the backlog in the intended score-descending admission
order (`avalanche.go:162-174`, the sort the reference disabled at
`processor.go:163`) — all inside one jit; nothing round-trips to the host
until the final results are fetched.

Design notes (TPU-first):
  * Retire/refill is pure masking + one cumsum (slot→backlog assignment by
    prefix-sum over free slots) and one scatter into the [B] outputs —
    static shapes throughout; XLA sees the same program every epoch.
  * The inner consensus round is exactly `models/avalanche.round_step`, so
    everything composes: fault knobs, weighted sampling, vote modes,
    Pallas ingest, and the sharded nodes axis (slot metadata is replicated
    across node shards; settling is an `all` over the nodes axis, which
    under `shard_map` becomes one tiny psum).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu import traffic as tf
from go_avalanche_tpu.config import (
    AvalancheConfig,
    DEFAULT_CONFIG,
    suppress_taps,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.obs import sink as obs_sink
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import inflight
from go_avalanche_tpu.ops import voterecord as vr

NO_TX = -1  # empty-slot sentinel, in the spirit of NoNode (`avalanche.go:28`)


class Backlog(NamedTuple):
    """Per-tx metadata for the full pending set; ``[B]`` arrays.

    Admission order is the array order: build with `make_backlog` to get
    the intended score-descending order.
    """

    score: jax.Array      # int32 [B]
    init_pref: jax.Array  # bool  [B] — Target.IsAccepted() prior
    valid: jax.Array      # bool  [B] — Target.IsValid()


class BacklogOutputs(NamedTuple):
    """Per-tx settlement results, written as slots retire; ``[B]`` arrays."""

    settled: jax.Array        # bool  [B]
    accepted: jax.Array       # bool  [B] — network-majority final preference
    accept_votes: jax.Array   # int32 [B] — nodes finalized-accepted
    settle_round: jax.Array   # int32 [B] — global round at retirement
    admit_round: jax.Array    # int32 [B] — global round at admission


class BacklogSimState(NamedTuple):
    """Active window + backlog + outputs; the full streaming-sim state."""

    sim: av.AvalancheSimState  # dense [N, W] window state
    slot_tx: jax.Array         # int32 [W] — backlog index per slot, NO_TX=empty
    slot_admit_round: jax.Array  # int32 [W]
    backlog: Backlog           # [B]
    outputs: BacklogOutputs    # [B]
    next_idx: jax.Array        # int32 — next unadmitted backlog position
    traffic: Optional[tf.TrafficState] = None
                               # live-traffic plane (go_avalanche_tpu/
                               #   traffic.py) — present iff
                               #   cfg.arrivals_enabled(): admission is
                               #   gated on the arrived watermark and
                               #   retiring slots record arrival ->
                               #   settle latency.  None = the
                               #   drain-a-fixed-backlog seed path,
                               #   statically absent from every
                               #   compiled program


def make_backlog(
    scores: jax.Array,
    init_pref: Optional[jax.Array] = None,
    valid: Optional[jax.Array] = None,
) -> Backlog:
    """Sort txs into score-descending admission order (stable on ties)."""
    scores = jnp.asarray(scores, jnp.int32)
    b = scores.shape[0]
    if init_pref is None:
        init_pref = jnp.ones((b,), jnp.bool_)
    if valid is None:
        valid = jnp.ones((b,), jnp.bool_)
    order = jnp.argsort(-scores, stable=True)
    return Backlog(score=scores[order],
                   init_pref=jnp.asarray(init_pref, jnp.bool_)[order],
                   valid=jnp.asarray(valid, jnp.bool_)[order])


def init(
    key: jax.Array,
    n_nodes: int,
    window: int,
    backlog: Backlog,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    track_finality: bool = True,
) -> BacklogSimState:
    """Empty window over a fresh backlog; first `refill` happens in step 0.

    `track_finality=False` drops the per-(node, tx) finalized_at plane
    (`models/avalanche.AvalancheSimState`) — latency here is recorded per
    tx in `BacklogOutputs`, so the plane is pure overhead.
    """
    b = backlog.score.shape[0]
    sim = av.init(key, n_nodes, window, cfg,
                  added=jnp.zeros((n_nodes, window), jnp.bool_),
                  valid=jnp.zeros((window,), jnp.bool_),
                  track_finality=track_finality)
    return BacklogSimState(
        sim=sim,
        slot_tx=jnp.full((window,), NO_TX, jnp.int32),
        slot_admit_round=jnp.zeros((window,), jnp.int32),
        backlog=backlog,
        outputs=BacklogOutputs(
            settled=jnp.zeros((b,), jnp.bool_),
            accepted=jnp.zeros((b,), jnp.bool_),
            accept_votes=jnp.zeros((b,), jnp.int32),
            settle_round=jnp.full((b,), -1, jnp.int32),
            admit_round=jnp.full((b,), -1, jnp.int32),
        ),
        next_idx=jnp.int32(0),
        traffic=tf.init_traffic(cfg, key, b),
    )


def _settled_slots(state: BacklogSimState,
                   cfg: AvalancheConfig) -> jax.Array:
    """bool [W]: occupied slots the network is done with.

    A slot settles when every live node that reconciles it has finalized
    (the batched version of "all 100 nodes fully finalized",
    `examples/basic-preconcensus/main.go:159-161`), or its tx is invalid
    (invalid targets stop polling, `processor.go:155-157`). Slots nobody
    reconciles settle too — with gossip on this only happens for invalid
    txs; without gossip it cannot happen because admission seeds all nodes.
    """
    sim = state.sim
    occupied = state.slot_tx != NO_TX
    fin = vr.has_finalized(sim.records.confidence, cfg)
    pending = sim.added & sim.alive[:, None] & jnp.logical_not(fin)
    return occupied & (jnp.logical_not(pending.any(axis=0))
                       | jnp.logical_not(sim.valid))


def _retire_and_refill(
    state: BacklogSimState,
    cfg: AvalancheConfig,
    refill: bool = True,
) -> Tuple[BacklogSimState, jax.Array]:
    """Write settled slots' outcomes to [B] outputs; refill from backlog.

    Returns (new_state, n_retired). One scatter per output plane plus a
    cumsum for slot→backlog assignment; static shapes. With `refill=False`
    (the end-of-run harvest) settled slots empty instead of taking new
    txs, so `next_idx` never counts txs that were admitted but not polled.
    """
    sim = state.sim
    settled = _settled_slots(state, cfg)

    # --- retire: scatter outcomes at the retiring slots' tx indices.
    # Scatter index NO_TX is out-of-range (mode="drop" semantics) for
    # non-settled lanes via clamping to a dummy: use where on the index and
    # drop writes with mask trick — scatter with indices set to B (OOB) is
    # dropped by jnp .at[].set(mode="drop").
    b = state.backlog.score.shape[0]
    conf = sim.records.confidence
    fin = vr.has_finalized(conf, cfg)
    acc = vr.is_accepted(conf)
    # Votes among nodes that reconcile + finalized; majority of live nodes
    # decides the recorded network outcome.
    accept_votes = (fin & acc & sim.added).sum(axis=0).astype(jnp.int32)
    n_live = jnp.maximum(sim.alive.sum().astype(jnp.int32), 1)
    accepted = accept_votes * 2 > n_live

    idx = jnp.where(settled, state.slot_tx, b)  # b = dropped write
    out = state.outputs
    out = BacklogOutputs(
        settled=out.settled.at[idx].set(True, mode="drop"),
        accepted=out.accepted.at[idx].set(accepted, mode="drop"),
        accept_votes=out.accept_votes.at[idx].set(accept_votes, mode="drop"),
        settle_round=out.settle_round.at[idx].set(sim.round, mode="drop"),
        admit_round=out.admit_round.at[idx].set(state.slot_admit_round,
                                                mode="drop"),
    )

    # --- live traffic: retiring slots record arrival -> settle latency
    # into the fixed-depth histogram; admission below is gated on the
    # arrived watermark (a tx cannot be admitted before it arrives).
    traffic = state.traffic
    if traffic is not None:
        arr = traffic.arrival_round[jnp.clip(state.slot_tx, 0, b - 1)]
        traffic = traffic._replace(lat_hist=traffic.lat_hist + tf.latency_delta(
            cfg, sim.round - arr, settled.astype(jnp.int32)))

    # --- refill: free slots take the next backlog txs in admission order.
    free = settled | (state.slot_tx == NO_TX)
    rank = jnp.cumsum(free.astype(jnp.int32)) - 1        # rank among free
    cand = state.next_idx + rank                          # backlog position
    avail = b if traffic is None else jnp.minimum(jnp.int32(b),
                                                  traffic.arrived_idx)
    take = free & (cand < avail)
    if not refill:
        take = jnp.zeros_like(take)
    new_tx = jnp.where(take, cand, jnp.where(settled, NO_TX, state.slot_tx))
    n_taken = take.sum().astype(jnp.int32)

    cand_safe = jnp.clip(cand, 0, b - 1)
    pref = state.backlog.init_pref[cand_safe]             # bool [W]
    # Row-constant fresh values at [1, W]; the fill `where` broadcasts.
    # (Cost analysis shows XLA fused the explicit [N, W] broadcast this
    # replaces, so this is clarity, not traffic — PERF_NOTES.md.)
    fresh = vr.init_state(pref[None, :])

    def fill(plane, fresh_plane):
        return jnp.where(take[None, :], fresh_plane, plane)

    records = vr.VoteRecordState(
        votes=fill(sim.records.votes, fresh.votes),
        consider=fill(sim.records.consider, fresh.consider),
        confidence=fill(sim.records.confidence, fresh.confidence),
    )
    occupied_after = new_tx != NO_TX
    # Admission seeds every node, as the reference example feeds every tx
    # to every node up front (`main.go:49-53`); retired slots clear.
    added = jnp.where(take[None, :], True,
                      sim.added & occupied_after[None, :])
    valid = jnp.where(take, state.backlog.valid[cand_safe],
                      sim.valid & occupied_after)
    score = jnp.where(occupied_after,
                      state.backlog.score[jnp.clip(new_tx, 0, b - 1)],
                      jnp.int32(-2**31 + 1))
    finalized_at = av.reset_finality(sim.finalized_at, take)

    score_rank, poll_order, poll_order_inv = av.score_rank_with_orders(score)
    new_sim = sim._replace(
        records=records,
        added=added,
        valid=valid,
        score_rank=score_rank,
        poll_order=poll_order,
        poll_order_inv=poll_order_inv,
        finalized_at=finalized_at,
        # Responses still in flight for a retired slot must not land on
        # its NEW occupant: drop the freed columns from every pending
        # ring entry's poll mask (no-op when the async engine is off).
        inflight=inflight.clear_columns(sim.inflight, settled | take),
    )
    return BacklogSimState(
        sim=new_sim,
        slot_tx=new_tx,
        slot_admit_round=jnp.where(take, sim.round, state.slot_admit_round),
        backlog=state.backlog,
        outputs=out,
        next_idx=state.next_idx + n_taken,
        traffic=traffic,
    ), settled.sum().astype(jnp.int32)


class BacklogTelemetry(NamedTuple):
    """Per-step scalars: the inner round's telemetry plus scheduler stats."""

    round: av.SimTelemetry
    retired: jax.Array    # int32 — slots retired this step
    occupied: jax.Array   # int32 — occupied slots after refill
    backlog_left: jax.Array  # int32 — txs not yet admitted
    traffic: Optional[tf.TrafficTelemetry] = None
                          # arrival counters + finality-latency
                          #   percentiles; None (absent from the JSONL
                          #   schema) when arrivals are off


def trace_columns(cfg: AvalancheConfig) -> tuple:
    """The scheduler's trace-plane column manifest: the inner round's
    `SimTelemetry` fields, the scheduler stats, then the traffic fields
    when the arrival plane is on — exactly the JSONL flattening order
    of `BacklogTelemetry`."""
    groups = [av.SimTelemetry._fields,
              ("retired", "occupied", "backlog_left")]
    if cfg.arrivals_enabled():
        groups.append(tf.TrafficTelemetry._fields)
    return obs_trace.columns_from_fields(*groups)


def with_trace(state: BacklogSimState, cfg: AvalancheConfig,
               n_rounds: int) -> BacklogSimState:
    """Attach the on-device trace plane (obs/trace.py) — the SCHEDULER
    owns it (full `BacklogTelemetry` rows; the inner round's write is
    suppressed, mirroring the metrics tap).  No-op when
    `cfg.trace_every == 0`."""
    return state._replace(sim=state.sim._replace(
        trace=obs_trace.alloc(cfg, n_rounds, trace_columns(cfg))))


def step(
    state: BacklogSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> Tuple[BacklogSimState, BacklogTelemetry]:
    """Arrive (traffic mode), retire/refill, then one consensus round on
    the window. Pure; scans.

    With the in-graph metrics tap on (`cfg.metrics_every > 0`) the
    SCHEDULER emits the full `BacklogTelemetry` record — inner round
    counters, retire/occupancy stats, and the traffic plane's
    finality-latency percentiles — and suppresses the inner round's own
    emission so each round writes exactly one JSONL line
    (docs/observability.md).  The on-device trace plane
    (`cfg.trace_every > 0`, obs/trace.py) follows the same contract:
    the scheduler writes the full record into `sim.trace`, the inner
    round's write is suppressed.
    """
    if cfg.round_engine != "phased":
        raise ValueError(
            "round_engine 'megakernel' is wired for the dense avalanche "
            "round only; the backlog window scheduler keeps the phased "
            "inner round (the window width need not satisfy the "
            "kernel's tiling contract) — the knob would be inert here")
    round_val = state.sim.round
    arrivals = jnp.int32(0)
    if state.traffic is not None:
        new_traffic, arrivals = tf.arrive(
            state.traffic, cfg, round_val,
            (state.slot_tx != NO_TX).sum().astype(jnp.int32),
            state.slot_tx.shape[0])
        state = state._replace(traffic=new_traffic)
    state, retired = _retire_and_refill(state, cfg)
    new_sim, round_tel = av.round_step(state.sim, suppress_taps(cfg))
    tel = BacklogTelemetry(
        round=round_tel,
        retired=retired,
        occupied=(state.slot_tx != NO_TX).sum().astype(jnp.int32),
        backlog_left=state.backlog.score.shape[0] - state.next_idx,
        traffic=(None if state.traffic is None
                 else tf.traffic_telemetry(state.traffic, arrivals)),
    )
    obs_sink.emit_round(cfg, round_val, tel)
    new_sim = new_sim._replace(
        trace=obs_trace.write_round(new_sim.trace, cfg, round_val, tel))
    return state._replace(sim=new_sim), tel


def drained(state: BacklogSimState,
            cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """True when the backlog is exhausted and every occupied slot settled."""
    b = state.backlog.score.shape[0]
    exhausted = state.next_idx >= b
    occupied = state.slot_tx != NO_TX
    return exhausted & jnp.logical_not(
        (occupied & jnp.logical_not(_settled_slots(state, cfg))).any())


def run(
    state: BacklogSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    max_rounds: int = 100_000,
) -> BacklogSimState:
    """Stream the whole backlog through the window; single compile.

    A final retire pass harvests the last settled slots' outputs.
    """

    def cond(s: BacklogSimState) -> jax.Array:
        return jnp.logical_not(drained(s, cfg)) & (s.sim.round < max_rounds)

    def body(s: BacklogSimState) -> BacklogSimState:
        new_s, _ = step(s, cfg)
        return new_s

    final = lax.while_loop(cond, body, state)
    final, _ = _retire_and_refill(final, cfg, refill=False)
    return final


def run_scan(
    state: BacklogSimState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 1000,
) -> Tuple[BacklogSimState, BacklogTelemetry]:
    """Fixed-round run with stacked telemetry (bench/throughput curves)."""

    def body(s: BacklogSimState, _):
        new_s, tel = step(s, cfg)
        return new_s, tel

    return lax.scan(body, state, None, length=n_rounds)
