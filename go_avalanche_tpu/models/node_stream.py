"""Node-axis streaming scheduler: million-node registries in bounded HBM.

`models/backlog.py` opened the TX axis: a bounded window of W slots
streams a 1M-tx backlog through dense ``[N, W]`` state.  This module is
its mirror on the NODE axis — the last un-scaled dimension.  A
production network has a *registry* of R nodes (R can be 1M+), but only
a bounded ACTIVE working set participates in any round (DAG-Sword,
PAPERS.md arXiv 2311.04638, simulates large topologies by keeping only
an active set resident).  Here:

  * the **registry** lives as cheap ``[R]`` metadata (stake, residency)
    — megabytes at 1M nodes, noise next to the window planes;
  * the **active window** is a dense ``[W, T]`` `AvalancheSimState`
    whose row r hosts registry node `slot_node[r]`; the inner consensus
    round is exactly `models/avalanche.round_step`, so everything
    composes — stake-weighted committee draws (`cfg.stake_mode`, row
    propensities are the residents' registry stakes), fault scripts,
    vote modes, ingest engines, and the sharded nodes axis
    (`parallel/sharded_node_stream.py`);
  * the working set is drawn **stake-proportionally** from the registry
    (exact weighted-without-replacement Gumbel top-k,
    `stake.draw_working_set`) and **churn** rotates it: each step every
    active row departs with probability `cfg.node_churn_rate`;
    departing rows' vote records retire (the node leaves, its window
    rows are surrendered) and arriving rows initialize from the
    registry prior — exactly how a fresh `NewVoteRecord(t.IsAccepted())`
    seeds (`processor.go:56`).  The window stays FULL: a departure
    without a drawable replacement (the non-resident pool is exhausted
    of positive-stake nodes) is cancelled.

This is what makes ``nodes >> devices * VMEM`` a supported regime
instead of an OOM: HBM holds ``W x T`` consensus state however large R
grows, and the registry axis costs one ``[R]`` top-k per step.

Determinism contract (mirrors the live-traffic plane,
`go_avalanche_tpu/traffic.py`): the churn stream folds its OWN key off
the sim init key (`_CHURN_FOLD`), so (1) the consensus PRNG is
untouched — a churn-rate-0 run is bit-identical to the plain window
sim — and (2) the dense and sharded schedulers realize the SAME
working-set trajectory for the same key (the draw runs on replicated
registry state; `tests/test_node_stream.py` pins `slot_node` /
`resident` / the churn counters leaf-exact dense vs sharded, the same
window-parity the acceptance criterion names — the inner round's
per-shard PRNG streams differ by design, like every sharded model).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu import stake as stake_mod
from go_avalanche_tpu.config import (
    AvalancheConfig,
    DEFAULT_CONFIG,
    suppress_taps,
)
from go_avalanche_tpu.models import avalanche as av
from go_avalanche_tpu.obs import sink as obs_sink
from go_avalanche_tpu.obs import trace as obs_trace
from go_avalanche_tpu.ops import inflight
from go_avalanche_tpu.ops import voterecord as vr

# fold_in constant deriving the registry-churn stream from the sim's
# init key: rotating the window must never perturb the consensus
# draws (a node_churn_rate-0 node-stream trajectory is bit-identical
# to the plain [W, T] sim's), and the replicated draw is what makes
# dense == sharded on the working-set window.
_CHURN_FOLD = 0x2E617


class NodeStreamState(NamedTuple):
    """Active window + registry; the full node-streaming sim state."""

    sim: av.AvalancheSimState   # dense [W, T] window state; row r hosts
                                #   registry node slot_node[r]
    slot_node: jax.Array        # int32 [W] — registry id per window row
    resident: jax.Array         # bool [R] — registry nodes currently in
                                #   the window (always exactly W True)
    stake: jax.Array            # float32 [R] — the registry stake plane
                                #   (cfg.stake_mode realized over R)
    init_pref: jax.Array        # bool [T] — the prior an arriving
                                #   node's fresh records adopt
    churn_key: jax.Array        # the registry churn PRNG stream (its
                                #   own fold off the init key)
    churned_in: jax.Array       # int32 — cumulative arrivals
    churned_out: jax.Array      # int32 — cumulative departures


class NodeStreamTelemetry(NamedTuple):
    """Per-step scalars: the inner round's telemetry plus registry
    stats."""

    round: av.SimTelemetry
    departed: jax.Array        # int32 — rows rotated out this step
    resident_stake: jax.Array  # float32 — fraction of total registry
                               #   stake currently resident (the
                               #   committee's voting-power coverage)


# The node-stream scheduler's trace-plane column manifest: the inner
# round's counters plus the registry stats — `resident_stake` is the
# repo's one FLOAT telemetry column (stored bitcast, obs/trace.py).
TRACE_COLUMNS = obs_trace.columns_from_fields(
    av.SimTelemetry._fields, ("departed", "resident_stake"),
    floats=frozenset({"resident_stake"}))


def with_trace(state: "NodeStreamState", cfg: AvalancheConfig,
               n_rounds: int) -> "NodeStreamState":
    """Attach the on-device trace plane (obs/trace.py) — the SCHEDULER
    owns it (full `NodeStreamTelemetry` rows); no-op when
    `cfg.trace_every == 0`."""
    return state._replace(sim=state.sim._replace(
        trace=obs_trace.alloc(cfg, n_rounds, TRACE_COLUMNS)))


def _registry_byzantine(cfg: AvalancheConfig, r: int) -> jax.Array:
    """bool [R]: the registry's adversarial nodes — the first
    ``round(byzantine_fraction * R)`` ids, the same convention as
    `av.init` (with zipf stake this is the TOP-stake adversary — the
    worst case, documented in config.py)."""
    n_byz = int(round(cfg.byzantine_fraction * r))
    return jnp.arange(r, dtype=jnp.int32) < n_byz


def init(
    key: jax.Array,
    n_txs: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    init_pref: Optional[jax.Array] = None,
    scores: Optional[jax.Array] = None,
    track_finality: bool = True,
) -> NodeStreamState:
    """Fresh registry + a stake-proportionally drawn initial window.

    R/W come from `cfg.registry_nodes` / `cfg.active_nodes` (validated
    together with `cfg.stake_mode` at config construction).  The
    initial W residents are an exact weighted-without-replacement draw
    over the registry stake; `init_pref` (bool ``[T]``, default
    all-accepted) is both the window's initial prior and the prior
    every later arrival adopts.
    """
    if not stake_mod.registry_enabled(cfg):
        raise ValueError(
            "the node-stream scheduler needs cfg.registry_nodes / "
            "cfg.active_nodes set (the registry-off window sim is "
            "models/avalanche)")
    r, w = cfg.registry_nodes, cfg.active_nodes
    stake_r = stake_mod.node_stake(cfg, r)
    churn_key = jax.random.fold_in(key, _CHURN_FOLD)
    churn_key, k_draw = jax.random.split(churn_key)
    # Every built-in stake mode realizes strictly positive stakes
    # (config-validated for "explicit"), so the full W-draw is always
    # honored here — `valid` only matters for the churn pass's masked
    # pool.
    ids, _ = stake_mod.draw_working_set(k_draw, stake_r, w)
    if init_pref is None:
        init_pref = jnp.ones((n_txs,), jnp.bool_)
    init_pref = jnp.asarray(init_pref, jnp.bool_)
    # Canonical ascending row order for the initial window (top-k order
    # is score-sorted; rows are an arbitrary hosting assignment).
    slot_node = jnp.sort(ids)
    resident = (jnp.zeros((r,), jnp.bool_)
                .at[slot_node].set(True))
    sim = av.init(key, w, n_txs, cfg, init_pref=init_pref,
                  scores=scores, track_finality=track_finality)
    byz_r = _registry_byzantine(cfg, r)
    sim = sim._replace(
        # Row propensities are the RESIDENTS' registry stakes — row
        # index is a hosting slot, not a node id, so av.init's
        # positional stake fold is skipped under the registry
        # (models/avalanche.init) and the plane is owned here.
        latency_weight=stake_r[slot_node],
        byzantine=byz_r[slot_node],
    )
    return NodeStreamState(
        sim=sim,
        slot_node=slot_node,
        resident=resident,
        stake=stake_r,
        init_pref=init_pref,
        churn_key=churn_key,
        churned_in=jnp.int32(0),
        churned_out=jnp.int32(0),
    )


def draw_churn_swaps(state: NodeStreamState, cfg: AvalancheConfig):
    """The churn pass's REPLICATED draw: which rows rotate, to whom,
    and the updated residency — everything a shard can compute
    identically from replicated registry planes.  Returns
    ``(swap [W], new_slot [W], resident [R], n_swapped, next key)``.

    THE one spelling of the rotation rule, shared verbatim by the
    dense scheduler below and the sharded twin
    (`parallel/sharded_node_stream._local_churn`): the dense-vs-
    sharded leaf-exact window parity rests on both drivers executing
    THIS draw, so a second copy could silently diverge.

    Exact stake-proportional arrivals from the non-resident pool; the
    pool holds R - W entries, so at most min(W, R - W) swaps can be
    honored per step (excess departures are cancelled — the window
    never runs rows empty).
    """
    w = state.slot_node.shape[0]
    r = state.resident.shape[0]
    k_dep, k_arr, k_next = jax.random.split(state.churn_key, 3)
    depart = jax.random.bernoulli(k_dep, cfg.node_churn_rate, (w,))
    cap = min(w, r - w)
    cand_ids, cand_valid = stake_mod.draw_working_set(
        k_arr, state.stake, cap, mask=jnp.logical_not(state.resident))
    rank = jnp.cumsum(depart.astype(jnp.int32)) - 1     # rank among departs
    rank_safe = jnp.clip(rank, 0, cap - 1)
    swap = depart & (rank < cap) & cand_valid[rank_safe]
    new_slot = jnp.where(swap, cand_ids[rank_safe], state.slot_node)
    # Residency flip: departing ids out, arriving ids in (one dropped-
    # write scatter each; swaps are disjoint by construction).
    resident = (state.resident
                .at[jnp.where(swap, state.slot_node, r)]
                .set(False, mode="drop")
                .at[jnp.where(swap, new_slot, r)]
                .set(True, mode="drop"))
    return swap, new_slot, resident, swap.sum().astype(jnp.int32), k_next


def churn(state: NodeStreamState,
          cfg: AvalancheConfig) -> Tuple[NodeStreamState, jax.Array]:
    """One churn pass: rotate departing rows out, draw replacements
    stake-proportionally from the non-resident registry.  Returns
    ``(new_state, rows_swapped)``.  Statically absent (state passes
    through untraced) when `cfg.node_churn_rate` is 0.

    Every draw here runs on REPLICATED registry planes from the
    dedicated churn key (`draw_churn_swaps`), so the sharded twin
    realizes the identical swap sequence (the dense-vs-sharded
    window-parity contract).
    """
    if cfg.node_churn_rate <= 0.0:
        return state, jnp.int32(0)
    sim = state.sim
    r = state.resident.shape[0]
    swap, new_slot, resident, n_swapped, k_next = draw_churn_swaps(
        state, cfg)

    # Rotate the window rows: departing records RETIRE (surrendered
    # with the row), arrivals seed fresh records from the registry
    # prior — exactly the backlog scheduler's refill shape, on the
    # other axis.
    fresh = vr.init_state(jnp.broadcast_to(state.init_pref[None, :],
                                           sim.records.votes.shape))

    def fill(plane, fresh_plane):
        return jnp.where(swap[:, None], fresh_plane, plane)

    records = vr.VoteRecordState(
        votes=fill(sim.records.votes, fresh.votes),
        consider=fill(sim.records.consider, fresh.consider),
        confidence=fill(sim.records.confidence, fresh.confidence),
    )
    added = jnp.where(swap[:, None], True, sim.added)
    finalized_at = (None if sim.finalized_at is None
                    else jnp.where(swap[:, None], -1, sim.finalized_at))
    byz_r = _registry_byzantine(cfg, r)
    new_sim = sim._replace(
        records=records,
        added=added,
        finalized_at=finalized_at,
        latency_weight=state.stake[new_slot],
        byzantine=byz_r[new_slot],
        alive=jnp.where(swap, True, sim.alive),
        # Responses still in flight for a departed node must not land
        # on — or be answered by proxy of — its replacement (the swap
        # mask gates both the querier and the polled-peer side).
        inflight=inflight.clear_rows(sim.inflight, swap,
                                     peer_rows=swap),
    )
    return state._replace(
        sim=new_sim,
        slot_node=new_slot,
        resident=resident,
        churn_key=k_next,
        churned_in=state.churned_in + n_swapped,
        churned_out=state.churned_out + n_swapped,
    ), n_swapped


def step(
    state: NodeStreamState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> Tuple[NodeStreamState, NodeStreamTelemetry]:
    """Churn the window, then one consensus round on it.  Pure; scans.

    With the in-graph metrics tap on (`cfg.metrics_every > 0`) the
    SCHEDULER emits the full `NodeStreamTelemetry` record and
    suppresses the inner round's own emission, one JSONL line per
    round (the backlog scheduler's convention, docs/observability.md).
    """
    round_val = state.sim.round
    state, swapped = churn(state, cfg)
    new_sim, round_tel = av.round_step(state.sim, suppress_taps(cfg))
    total = state.stake.sum()
    tel = NodeStreamTelemetry(
        round=round_tel,
        departed=swapped,
        resident_stake=(jnp.where(state.resident, state.stake, 0.0).sum()
                        / jnp.maximum(total, jnp.float32(1e-38))),
    )
    obs_sink.emit_round(cfg, round_val, tel)
    new_sim = new_sim._replace(
        trace=obs_trace.write_round(new_sim.trace, cfg, round_val, tel))
    return state._replace(sim=new_sim), tel


def run_scan(
    state: NodeStreamState,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    n_rounds: int = 100,
) -> Tuple[NodeStreamState, NodeStreamTelemetry]:
    """Fixed-round run with stacked telemetry (the node axis has no
    drain condition — the registry never exhausts)."""

    def body(s, _):
        new_s, tel = step(s, cfg)
        return new_s, tel

    return lax.scan(body, state, None, length=n_rounds)


def window_summary(state: NodeStreamState,
                   cfg: AvalancheConfig = DEFAULT_CONFIG) -> dict:
    """Host-side digest of a final state: window finality, churn
    totals, and resident stake coverage (one device_get batch)."""
    fin = vr.has_finalized(state.sim.records.confidence, cfg)
    total = state.stake.sum()
    out = jax.device_get({
        "finalized_fraction": fin.mean(),
        "churned_in": state.churned_in,
        "churned_out": state.churned_out,
        "resident_stake_fraction":
            jnp.where(state.resident, state.stake, 0.0).sum()
            / jnp.maximum(total, jnp.float32(1e-38)),
        "resident_count": state.resident.sum(),
    })
    return {"finalized_fraction": float(out["finalized_fraction"]),
            "churned_in": int(out["churned_in"]),
            "churned_out": int(out["churned_out"]),
            "resident_stake_fraction":
                float(out["resident_stake_fraction"]),
            "resident_count": int(out["resident_count"])}
