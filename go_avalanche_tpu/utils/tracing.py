"""Tracing, profiling, and determinism auditing.

SURVEY.md §5: the reference's only performance artifacts are a wall-clock
print and a logging flag (`main.go:46,63`, `main.go:24-29`); its only safety
net is caller-side locking with no `-race` in CI (`.travis.yml:12`).  The
TPU-native replacements:

  * `trace(dir)`       — JAX profiler traces (XPlane/TensorBoard format) of
                         whole runs; `annotate(name)` names phases inside jit
                         so profiles read as poll/sample/gossip/ingest.
  * `TelemetryRecorder`— accumulates the on-device `SimTelemetry` stream and
                         derives the north-star metrics (votes/sec,
                         finalizations per round) host-side.
  * `determinism_audit`— JAX's functional model makes data races structurally
                         impossible; what remains to check is *determinism*
                         (fixed PRNG key -> bit-identical trajectories),
                         which this verifies by re-running a step function
                         and comparing every state leaf bit-for-bit.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Iterator, List, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace of the enclosed block into `log_dir`.

    View with TensorBoard's profile plugin or xprof.  Wraps
    `jax.profiler.trace` so callers don't import the profiler directly.
    """
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region visible in profiler timelines AND in HLO metadata.

    Usable as context manager inside traced code (`jax.named_scope`) — the
    simulators annotate their phases with this.  Under
    `collect_phase_times`, the same spans double as wall-clock phase timers
    (bench.py --profile) with no changes to the annotated code.
    """
    if _PHASE_SINK is not None:
        return _TimedPhase(name)
    return jax.named_scope(name)


# Active `collect_phase_times` accumulator, or None (the default: annotate
# spans are pure named scopes).  Module-level on purpose — the annotated
# simulators must not need a handle to the collector.
_PHASE_SINK: Optional[Dict[str, float]] = None


def _quiesce() -> None:
    """Drain the device queue: block on every live array.

    The span boundaries need a barrier — eager dispatch is asynchronous, so
    without one a phase's wall time would bleed into whichever span fetches
    a result first.  Everything an eager phase dispatched is reachable from
    a live array, so blocking on all of them is a sound (if blunt) fence.
    """
    for arr in jax.live_arrays():
        try:
            arr.block_until_ready()
        except RuntimeError:
            pass  # deleted/donated buffers have nothing to wait for


class _TimedPhase:
    """annotate()'s span under `collect_phase_times`: quiesce, time,
    accumulate.  Eager execution only — under a jit trace the barrier sees
    no new arrays and the span records ~0, it never breaks tracing."""

    def __init__(self, name: str) -> None:
        self._name = name

    def __enter__(self) -> "_TimedPhase":
        _quiesce()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        _quiesce()
        if _PHASE_SINK is not None:
            self._record(time.perf_counter() - self._t0)
        return False

    def _record(self, dt: float) -> None:
        _PHASE_SINK[self._name] = _PHASE_SINK.get(self._name, 0.0) + dt


@contextlib.contextmanager
def collect_phase_times() -> Iterator[Dict[str, float]]:
    """Collect wall seconds per `annotate` span for the enclosed block.

    Run the annotated code EAGERLY inside (phases inside a jit execute as
    one fused program — there is nothing per-span to time there).  Yields
    the accumulating ``{span name: seconds}`` dict; nesting restores the
    outer collector on exit.
    """
    global _PHASE_SINK
    prev, _PHASE_SINK = _PHASE_SINK, {}
    try:
        yield _PHASE_SINK
        _quiesce()  # un-annotated tail work completes before the caller's
    finally:        # surrounding timer (bench.py --profile) stops
        _PHASE_SINK = prev


def start_server(port: int = 9999):
    """Start the live profiler server (connect with TensorBoard capture)."""
    return jax.profiler.start_server(port)


class TelemetryRecorder:
    """Accumulates per-round `SimTelemetry` pytrees and derives run metrics.

    Keep everything on device during the run (append stacked telemetry from
    `run_scan` once per chunk, not per round); fetches happen lazily at
    report time.
    """

    def __init__(self) -> None:
        self._chunks: List = []
        self._t0 = time.perf_counter()
        self._elapsed: Optional[float] = None

    def append(self, telemetry) -> None:
        """Add one telemetry pytree — scalar (one round) or stacked (scan)."""
        self._chunks.append(telemetry)

    def finish(self) -> None:
        self._elapsed = time.perf_counter() - self._t0

    @property
    def elapsed_s(self) -> float:
        return (self._elapsed if self._elapsed is not None
                else time.perf_counter() - self._t0)

    def _stacked(self) -> Dict[str, np.ndarray]:
        if not self._chunks:
            return {}
        out: Dict[str, List[np.ndarray]] = {}
        for chunk in self._chunks:
            for field in chunk._fields:
                arr = np.atleast_1d(np.asarray(jax.device_get(
                    getattr(chunk, field))))
                out.setdefault(field, []).append(arr)
        return {k: np.concatenate(v) for k, v in out.items()}

    def per_round(self) -> Dict[str, np.ndarray]:
        """Per-round series, one entry per recorded round."""
        return self._stacked()

    def summary(self) -> Dict[str, float]:
        """Run totals plus derived rates (votes/sec is the north star)."""
        series = self._stacked()
        out: Dict[str, float] = {f"total_{k}": float(v.sum())
                                 for k, v in series.items()}
        out["rounds"] = float(len(next(iter(series.values()), [])))
        out["elapsed_s"] = self.elapsed_s
        if "votes_applied" in series and self.elapsed_s > 0:
            out["votes_per_sec"] = out["total_votes_applied"] / self.elapsed_s
        return out


def determinism_audit(
    step_fn: Callable,
    state,
    n_repeats: int = 2,
) -> Dict[str, object]:
    """Replay `step_fn(state)` `n_repeats` times; compare outputs bit-exactly.

    `step_fn` must be pure (state in, state/aux out) — true of every
    simulator step in `models/` and `parallel/`.  Returns a report dict:
    `deterministic` plus the leaf paths that mismatched, if any.
    """

    def _raw(x):
        # Typed PRNG keys refuse numpy conversion; compare their key data.
        if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
                x.dtype, jax.dtypes.prng_key):
            return jax.random.key_data(x)
        return x

    outputs = [jax.device_get(jax.tree.map(_raw, step_fn(state)))
               for _ in range(n_repeats)]
    mismatched: List[str] = []

    ref_leaves, treedef = jax.tree.flatten(outputs[0])
    paths = [jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(outputs[0])[0]]
    for other in outputs[1:]:
        leaves, other_def = jax.tree.flatten(other)
        if other_def != treedef:
            return {"deterministic": False, "mismatches": ["<structure>"]}
        for path, a, b in zip(paths, ref_leaves, leaves):
            a, b = np.asarray(a), np.asarray(b)
            # Raw-bytes compare: bit-for-bit is the contract, and unlike
            # np.array_equal it treats identical NaNs as equal.
            if (a.shape != b.shape or a.dtype != b.dtype
                    or a.tobytes() != b.tobytes()):
                mismatched.append(path)
    return {"deterministic": not mismatched,
            "mismatches": sorted(set(mismatched))}
