"""Tracing, profiling, and determinism auditing.

SURVEY.md §5: the reference's only performance artifacts are a wall-clock
print and a logging flag (`main.go:46,63`, `main.go:24-29`); its only safety
net is caller-side locking with no `-race` in CI (`.travis.yml:12`).  The
TPU-native replacements:

  * `trace(dir)`       — JAX profiler traces (XPlane/TensorBoard format) of
                         whole runs; `annotate(name)` names phases inside jit
                         so profiles read as poll/sample/gossip/ingest.
  * `TelemetryRecorder`— accumulates the on-device `SimTelemetry` stream and
                         derives the north-star metrics (votes/sec,
                         finalizations per round) host-side.
  * `determinism_audit`— JAX's functional model makes data races structurally
                         impossible; what remains to check is *determinism*
                         (fixed PRNG key -> bit-identical trajectories),
                         which this verifies by re-running a step function
                         and comparing every state leaf bit-for-bit.
"""

from __future__ import annotations

import contextlib
import re
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace of the enclosed block into `log_dir`.

    View with TensorBoard's profile plugin or xprof.  Wraps
    `jax.profiler.trace` so callers don't import the profiler directly.
    """
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region visible in profiler timelines AND in HLO metadata.

    Usable as context manager inside traced code (`jax.named_scope`) — the
    simulators annotate their phases with this.  Under
    `collect_phase_times`, the same spans double as wall-clock phase timers
    (bench.py --profile) with no changes to the annotated code.

    `name` must be one of the canonical `obs.tags.PHASE_SPANS` — the
    span strings are the join key between the eager wall timers, the
    device-time xplane harvest (`device_phase_times`) and the archived
    profile artifacts, so an ad-hoc spelling here would mint a phase
    row nothing else can join against (it would also stamp the drifted
    name into every pinned program's HLO metadata).
    """
    from go_avalanche_tpu.obs.tags import PHASE_SPANS

    if name not in PHASE_SPANS:
        raise ValueError(
            f"unknown phase span {name!r}: annotate() names are the "
            f"canonical obs.tags.PHASE_SPANS "
            f"({', '.join(PHASE_SPANS)}) — register a new phase there "
            f"(one spelling) before annotating with it")
    if _PHASE_SINK is not None:
        return _TimedPhase(name)
    return jax.named_scope(name)


# Active `collect_phase_times` accumulator, or None (the default: annotate
# spans are pure named scopes).  Module-level on purpose — the annotated
# simulators must not need a handle to the collector.
_PHASE_SINK: Optional[Dict[str, float]] = None


def _quiesce() -> None:
    """Drain the device queue: block on every live array.

    The span boundaries need a barrier — eager dispatch is asynchronous, so
    without one a phase's wall time would bleed into whichever span fetches
    a result first.  Everything an eager phase dispatched is reachable from
    a live array, so blocking on all of them is a sound (if blunt) fence.
    """
    for arr in jax.live_arrays():
        try:
            arr.block_until_ready()
        except RuntimeError:
            pass  # deleted/donated buffers have nothing to wait for


class _TimedPhase:
    """annotate()'s span under `collect_phase_times`: quiesce, time,
    accumulate.  Eager execution only — under a jit trace the barrier sees
    no new arrays and the span records ~0, it never breaks tracing."""

    def __init__(self, name: str) -> None:
        self._name = name

    def __enter__(self) -> "_TimedPhase":
        _quiesce()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        _quiesce()
        if _PHASE_SINK is not None:
            self._record(time.perf_counter() - self._t0)
        return False

    def _record(self, dt: float) -> None:
        _PHASE_SINK[self._name] = _PHASE_SINK.get(self._name, 0.0) + dt


@contextlib.contextmanager
def collect_phase_times() -> Iterator[Dict[str, float]]:
    """Collect wall seconds per `annotate` span for the enclosed block.

    Run the annotated code EAGERLY inside (phases inside a jit execute as
    one fused program — there is nothing per-span to time there).  Yields
    the accumulating ``{span name: seconds}`` dict; nesting restores the
    outer collector on exit.
    """
    global _PHASE_SINK
    prev, _PHASE_SINK = _PHASE_SINK, {}
    try:
        yield _PHASE_SINK
        _quiesce()  # un-annotated tail work completes before the caller's
    finally:        # surrounding timer (bench.py --profile) stops
        _PHASE_SINK = prev


def start_server(port: int = 9999):
    """Start the live profiler server (connect with TensorBoard capture)."""
    return jax.profiler.start_server(port)


# --------------------------------------------------------------------------
# Device-time profile harvest (the resource-observability plane).
#
# `collect_phase_times` above measures WALL time of an eager replay —
# dispatch overhead rides along and the timed program itself is never
# touched.  The harvest below reads the same phases out of the REAL timed
# program: `jax.profiler.trace` writes an XSpace protobuf containing one
# event per executed HLO op with its device duration; the compiled HLO's
# `op_name` metadata carries the `annotate` scope path; joining the two
# gives per-phase DEVICE time for the exact program `bench.py` times.
# This container's jax (0.4.37) has no `jax.profiler.ProfileData`, so the
# XSpace is read with a minimal protobuf wire-format walk — only the
# fields the join needs (plane/line/event/stat + the two metadata maps).
# --------------------------------------------------------------------------


def _varint(buf: bytes, i: int):
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _proto_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _varint(buf, i)
        elif wire == 2:
            ln, i = _varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = buf[i:i + 4]
            i += 4
        elif wire == 1:
            val = buf[i:i + 8]
            i += 8
        else:  # groups (3/4) never appear in XSpace
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val


def _metadata_name(entry: bytes):
    """(id, name) from one XEventMetadata / XStatMetadata map entry."""
    mid, name = 0, ""
    for f, _, v in _proto_fields(entry):
        if f == 2:
            for mf, _, mv in _proto_fields(v):
                if mf == 1:
                    mid = mv
                elif mf == 2:
                    name = mv.decode(errors="replace")
    return mid, name


def xplane_op_durations(log_dir, module_name: Optional[str] = None
                        ) -> Dict[str, int]:
    """Total device duration in PICOSECONDS per executed HLO op, from
    every ``*.xplane.pb`` under a `trace(log_dir)` capture.

    Only events carrying an ``hlo_op`` stat count (the per-op execution
    events XLA emits on the device/runtime lines); python host-trace
    events have no such stat and are ignored.  `module_name` restricts
    the sum to events whose ``hlo_module`` stat matches (the profiled
    block may execute helper programs — e.g. the sync fetch — whose op
    names would otherwise collide).
    """
    import pathlib

    totals: Dict[str, int] = {}
    for path in sorted(pathlib.Path(log_dir).rglob("*.xplane.pb")):
        data = path.read_bytes()
        for f, _, plane in _proto_fields(data):
            if f != 1:
                continue
            lines = []
            stat_names: Dict[int, str] = {}
            for pf, _, pv in _proto_fields(plane):
                if pf == 3:
                    lines.append(pv)
                elif pf == 5:
                    mid, name = _metadata_name(pv)
                    stat_names[mid] = name
            if not lines or not stat_names:
                continue
            by_name = {name: mid for mid, name in stat_names.items()}
            op_key = by_name.get("hlo_op")
            mod_key = by_name.get("hlo_module")
            if op_key is None:
                continue  # no op-level events on this plane
            for line in lines:
                for lf, _, lv in _proto_fields(line):
                    if lf != 4:
                        continue
                    dur = 0
                    op = mod = None
                    for ef, _, ev in _proto_fields(lv):
                        if ef == 3:
                            dur = ev
                        elif ef == 4:
                            smid = ref = None
                            for sf, _, sv in _proto_fields(ev):
                                if sf == 1:
                                    smid = sv
                                elif sf == 7:
                                    ref = sv
                            if smid == op_key and ref is not None:
                                op = stat_names.get(ref)
                            elif smid == mod_key and ref is not None:
                                mod = stat_names.get(ref)
                    if op is None:
                        continue
                    if module_name is not None and mod != module_name:
                        continue
                    totals[op] = totals.get(op, 0) + dur
    return totals


_HLO_INSTR_RE = re.compile(
    r'%([\w.-]+)\s*=.*?metadata=\{[^}]*op_name="([^"]*)"')
_HLO_MODULE_RE = re.compile(r"^HloModule\s+([\w.-]+)", re.MULTILINE)


def hlo_phase_map(compiled_text: str,
                  phases: Optional[Sequence[str]] = None
                  ) -> Dict[str, str]:
    """Map compiled-HLO instruction name -> canonical phase span.

    `compiled_text` is the optimized HLO (``lowered.compile().as_text()``
    — the instruction names there are the ones the profiler's op events
    carry).  An instruction belongs to a phase iff that span name appears
    as a path segment of its ``op_name`` metadata (the `annotate`
    scope path survives lowering and fusion).  `phases` defaults to
    `obs.tags.PHASE_SPANS`.
    """
    if phases is None:
        from go_avalanche_tpu.obs.tags import PHASE_SPANS as phases

    phase_set = set(phases)
    mapping: Dict[str, str] = {}
    for instr, op_name in _HLO_INSTR_RE.findall(compiled_text):
        for segment in op_name.split("/"):
            if segment in phase_set:
                mapping[instr] = segment
                break
    return mapping


def hlo_module_name(compiled_text: str) -> Optional[str]:
    """The ``HloModule`` name of a compiled program's text (the
    ``hlo_module`` stat the profiler stamps on its op events)."""
    m = _HLO_MODULE_RE.search(compiled_text)
    return m.group(1) if m else None


def device_phase_times(fn: Callable, *args, compiled_text: str,
                       phases: Optional[Sequence[str]] = None):
    """Execute ``fn(*args)`` once under the JAX profiler and return
    ``(result, {phase: device ms})`` for the program `compiled_text`
    describes.

    The returned dict carries one entry per canonical phase observed,
    plus ``other_device_ms`` (op time outside every annotated span —
    scan plumbing, donation copies, un-annotated phases) and
    ``device_total_ms``.  The caller must pass the OPTIMIZED HLO text of
    the jitted `fn` (``fn.lower(*args).compile().as_text()``) — the
    instruction-name join is only valid against the program that
    actually ran.  Works with donated `fn` (the consumed args are
    replaced by the returned result, which the caller keeps).
    """
    import shutil
    import tempfile

    log_dir = tempfile.mkdtemp(prefix="xplane_phase_")
    try:
        with trace(log_dir):
            result = fn(*args)
            jax.block_until_ready(result)
        per_op = xplane_op_durations(
            log_dir, module_name=hlo_module_name(compiled_text))
    finally:
        shutil.rmtree(log_dir, ignore_errors=True)

    phase_of = hlo_phase_map(compiled_text, phases)
    out: Dict[str, float] = {}
    other = total = 0
    for op, ps in per_op.items():
        total += ps
        phase = phase_of.get(op)
        if phase is None:
            other += ps
        else:
            out[phase] = out.get(phase, 0.0) + ps
    ms = {name: round(ps / 1e9, 3) for name, ps in sorted(out.items())}
    ms["other_device_ms"] = round(other / 1e9, 3)
    ms["device_total_ms"] = round(total / 1e9, 3)
    return result, ms


class TelemetryRecorder:
    """Accumulates per-round `SimTelemetry` pytrees and derives run metrics.

    Keep everything on device during the run (append stacked telemetry from
    `run_scan` once per chunk, not per round); fetches happen lazily at
    report time.
    """

    def __init__(self) -> None:
        self._chunks: List = []
        self._t0 = time.perf_counter()
        self._elapsed: Optional[float] = None

    def append(self, telemetry) -> None:
        """Add one telemetry pytree — scalar (one round) or stacked (scan)."""
        self._chunks.append(telemetry)

    def finish(self) -> None:
        self._elapsed = time.perf_counter() - self._t0

    @property
    def elapsed_s(self) -> float:
        return (self._elapsed if self._elapsed is not None
                else time.perf_counter() - self._t0)

    def _stacked(self) -> Dict[str, np.ndarray]:
        if not self._chunks:
            return {}
        out: Dict[str, List[np.ndarray]] = {}
        for chunk in self._chunks:
            for field in chunk._fields:
                arr = np.atleast_1d(np.asarray(jax.device_get(
                    getattr(chunk, field))))
                out.setdefault(field, []).append(arr)
        return {k: np.concatenate(v) for k, v in out.items()}

    def per_round(self) -> Dict[str, np.ndarray]:
        """Per-round series, one entry per recorded round."""
        return self._stacked()

    def summary(self) -> Dict[str, float]:
        """Run totals plus derived rates (votes/sec is the north star)."""
        series = self._stacked()
        out: Dict[str, float] = {f"total_{k}": float(v.sum())
                                 for k, v in series.items()}
        out["rounds"] = float(len(next(iter(series.values()), [])))
        out["elapsed_s"] = self.elapsed_s
        if "votes_applied" in series and self.elapsed_s > 0:
            out["votes_per_sec"] = out["total_votes_applied"] / self.elapsed_s
        return out


def determinism_audit(
    step_fn: Callable,
    state,
    n_repeats: int = 2,
) -> Dict[str, object]:
    """Replay `step_fn(state)` `n_repeats` times; compare outputs bit-exactly.

    `step_fn` must be pure (state in, state/aux out) — true of every
    simulator step in `models/` and `parallel/`.  Returns a report dict:
    `deterministic` plus the leaf paths that mismatched, if any.
    """

    def _raw(x):
        # Typed PRNG keys refuse numpy conversion; compare their key data.
        if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
                x.dtype, jax.dtypes.prng_key):
            return jax.random.key_data(x)
        return x

    outputs = [jax.device_get(jax.tree.map(_raw, step_fn(state)))
               for _ in range(n_repeats)]
    mismatched: List[str] = []

    ref_leaves, treedef = jax.tree.flatten(outputs[0])
    paths = [jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(outputs[0])[0]]
    for other in outputs[1:]:
        leaves, other_def = jax.tree.flatten(other)
        if other_def != treedef:
            return {"deterministic": False, "mismatches": ["<structure>"]}
        for path, a, b in zip(paths, ref_leaves, leaves):
            a, b = np.asarray(a), np.asarray(b)
            # Raw-bytes compare: bit-for-bit is the contract, and unlike
            # np.array_equal it treats identical NaNs as equal.
            if (a.shape != b.shape or a.dtype != b.dtype
                    or a.tobytes() != b.tobytes()):
                mismatched.append(path)
    return {"deterministic": not mismatched,
            "mismatches": sorted(set(mismatched))}
