"""Golden oracle, checkpointing, metrics."""
