"""Scalar pure-Python oracle of the vote-record state machine.

A deliberately boring, loop-and-branch transcription of the semantics in
`vote.go:24-98` (see SURVEY.md section 2.2), used as the ground truth that the
vectorized JAX kernel (`ops/voterecord.py`) and the Pallas kernel are
property-tested against with random vote streams, and from which the golden
vectors mirroring `avalanche_test.go:13-92` are generated.  Never used on the
hot path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.types import Status, normalize_err


@dataclass
class ScalarVoteRecord:
    """One target's Snowball record; semantics of `vote.go:24-98`."""

    votes: int = 0
    consider: int = 0
    confidence: int = 0
    cfg: AvalancheConfig = DEFAULT_CONFIG

    @classmethod
    def new(cls, accepted: bool,
            cfg: AvalancheConfig = DEFAULT_CONFIG) -> "ScalarVoteRecord":
        # `vote.go:33-35`: confidence starts at the preference bit.
        return cls(confidence=1 if accepted else 0, cfg=cfg)

    def is_accepted(self) -> bool:
        return (self.confidence & 1) == 1

    def get_confidence(self) -> int:
        return self.confidence >> 1

    def has_finalized(self) -> bool:
        return self.get_confidence() >= self.cfg.finalization_score

    def register_vote(self, err: int) -> bool:
        """Apply one vote; True iff acceptance/finalization state changed."""
        err = normalize_err(err)
        window_mask = (1 << self.cfg.window) - 1
        self.votes = ((self.votes << 1) | (1 if err == 0 else 0)) & window_mask
        self.consider = ((self.consider << 1)
                         | (1 if err >= 0 else 0)) & window_mask

        threshold = self.cfg.quorum - 1
        yes = bin(self.votes & self.consider).count("1") > threshold
        no = bin((~self.votes) & self.consider & window_mask).count("1") \
            > threshold

        if not yes and not no:
            return False  # inconclusive round (`vote.go:61-63`)

        if self.is_accepted() == yes:
            # Saturate the counter at its 15-bit ceiling, mirroring the
            # batched kernel (the reference deletes records before this
            # matters; long-lived batched records must not wrap uint16).
            if self.get_confidence() < 0x7FFF:
                self.confidence += 2
            # True only at the exact finalization moment (`vote.go:68`).
            return self.get_confidence() == self.cfg.finalization_score

        # Conclusive disagreement: flip preference, reset counter.
        self.confidence = 1 if yes else 0
        return True

    def status(self) -> Status:
        fin, acc = self.has_finalized(), self.is_accepted()
        if fin:
            return Status.FINALIZED if acc else Status.INVALID
        return Status.ACCEPTED if acc else Status.REJECTED


def replay(accepted: bool, errs: Sequence[int],
           cfg: AvalancheConfig = DEFAULT_CONFIG,
           ) -> List[Tuple[int, int, int, bool]]:
    """Replay a vote stream; per-vote (votes, consider, confidence, changed).

    The trace format the kernel parity tests consume.
    """
    vr = ScalarVoteRecord.new(accepted, cfg)
    out = []
    for e in errs:
        changed = vr.register_vote(e)
        out.append((vr.votes, vr.consider, vr.confidence, changed))
    return out


def golden_vector_sequence() -> List[Tuple[int, bool, bool, int]]:
    """The reference suite's exhaustive golden sequence.

    Reproduces the scripted expectations of `TestVoteRecord`
    (`avalanche_test.go:13-92`) as (err, expect_accepted, expect_finalized,
    expect_confidence) tuples, starting from NewVoteRecord(false):
    6 warm-up yes votes, the 7th flips, neutral-stall behavior, count to 128
    and finalize, then flip to rejection and re-finalize the no state.
    """
    seq: List[Tuple[int, bool, bool, int]] = []
    fin = DEFAULT_CONFIG.finalization_score

    # 6 warm-up yes votes before the window can go conclusive.
    for _ in range(6):
        seq.append((0, False, False, 0))
    # 7th yes vote flips preference to accepted.
    seq.append((0, True, False, 0))
    # A single neutral vote changes nothing (window still conclusive-yes).
    seq.append((-1, True, False, 1))
    for i in range(2, 8):
        seq.append((0, True, False, i))
    # Two neutral votes stall progress at confidence 7.
    seq.append((-1, True, False, 7))
    seq.append((-1, True, False, 7))
    for _ in range(2, 8):
        seq.append((0, True, False, 7))
    # Confidence now rises monotonically to the finalization score.
    for i in range(8, fin):
        seq.append((0, True, False, i))
    # The next vote finalizes — even a no vote (window still conclusive-yes).
    seq.append((1, True, True, fin))
    # A few more no votes: window inconclusive, nothing moves.
    for _ in range(5):
        seq.append((1, True, True, fin))
    # 7th no vote flips to rejected, confidence resets.
    seq.append((1, False, False, 0))
    # Mirror image: neutral stalls and the climb to finalized rejection.
    seq.append((-1, False, False, 1))
    for i in range(2, 8):
        seq.append((1, False, False, i))
    seq.append((-1, False, False, 7))
    seq.append((-1, False, False, 7))
    for _ in range(2, 8):
        seq.append((1, False, False, 7))
    for i in range(8, fin):
        seq.append((1, False, False, i))
    # Finalize the rejection (a yes vote; window still conclusive-no).
    seq.append((0, False, True, fin))
    return seq
