"""Checkpoint / resume for simulator states.

The reference has no checkpointing — its entire state is three Go maps
(`processor.go:16-19`) that die with the process (SURVEY.md section 5).  The
batched states here are pytrees of dense arrays + a PRNG key + the round
counter, so a checkpoint is an exact, bit-for-bit resumable snapshot: restore
and the simulation continues on the identical deterministic trajectory.

Two interchangeable backends, same pytree/template contract:

  * `save_checkpoint` / `restore_checkpoint` — a single .npz of the
    flattened leaves (typed PRNG keys serialized via
    `jax.random.key_data`).  Zero extra dependencies, one file, ideal for
    single-host simulation sweeps.
  * `save_checkpoint_orbax` / `restore_checkpoint_orbax` — orbax
    `StandardCheckpointer` directory format: sharding-aware and
    multi-host-safe, the right backend when the state lives on a
    `jax.sharding.Mesh` across processes (`parallel/runtime.py`).
    Gated on `import orbax` so the core package keeps its jax+numpy-only
    dependency footprint (`pyproject.toml` extra: `checkpoint`).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

_KEY_PREFIX = "__prngkey__"


def _is_key(leaf: Any) -> bool:
    return isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
        leaf.dtype, jax.dtypes.prng_key)


def save_checkpoint(path: str, state: Any) -> None:
    """Save any simulator state pytree to `path` (.npz)."""
    leaves, _ = jax.tree_util.tree_flatten(state)
    payload = {"__leaf_count__": np.asarray(len(leaves))}
    for i, leaf in enumerate(leaves):
        if _is_key(leaf):
            payload[f"{_KEY_PREFIX}{i}"] = np.asarray(
                jax.random.key_data(leaf))
        else:
            payload[f"leaf_{i}"] = np.asarray(jax.device_get(leaf))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic: no torn checkpoints on interruption


def restore_checkpoint(path: str, template: Any) -> Any:
    """Restore a state saved by `save_checkpoint`.

    `template` is any state with the same pytree structure (e.g. a freshly
    `init()`-ed one); its structure and static aux data are reused, its array
    values are replaced.  Shape/dtype mismatches raise ValueError.
    """
    leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(path) as data:
        if "__leaf_count__" in data:   # absent in pre-marker checkpoints
            saved = int(data["__leaf_count__"])
            if saved != len(leaves):
                raise ValueError(
                    f"checkpoint has {saved} leaves, template has "
                    f"{len(leaves)} — saved from a structurally different "
                    f"state (e.g. the opposite `track_finality` mode, or "
                    f"another model/config); rebuild the template to match "
                    f"how the checkpoint was produced")
        restored = []
        for i, leaf in enumerate(leaves):
            key_name, plain_name = f"{_KEY_PREFIX}{i}", f"leaf_{i}"
            if _is_key(leaf):
                if key_name not in data:
                    raise ValueError(
                        f"checkpoint leaf {i}: expected a PRNG key")
                restored.append(jax.random.wrap_key_data(
                    jax.numpy.asarray(data[key_name])))
                continue
            if plain_name not in data:
                raise ValueError(f"checkpoint missing leaf {i} "
                                 f"(template/checkpoint structure mismatch)")
            arr = data[plain_name]
            want = jax.numpy.asarray(leaf)
            if arr.shape != want.shape or arr.dtype != want.dtype:
                raise ValueError(
                    f"checkpoint leaf {i}: got {arr.dtype}{list(arr.shape)}, "
                    f"template has {want.dtype}{list(want.shape)}")
            restored.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, restored)


# ---------------------------------------------------------------------------
# Orbax backend (optional dependency; sharding-aware, multi-host-safe)


def _split_keys(state: Any):
    """(state with PRNG keys replaced by raw key data, key-position mask)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    mask = [_is_key(x) for x in leaves]
    plain = [jax.random.key_data(x) if m else x
             for x, m in zip(leaves, mask)]
    return jax.tree_util.tree_unflatten(treedef, plain), mask


def save_checkpoint_orbax(path: str, state: Any) -> None:
    """Save a state pytree as an orbax checkpoint directory at `path`.

    Unlike the .npz backend this preserves shardings and coordinates
    multi-host saves; use it when the state was placed on a mesh.
    """
    import orbax.checkpoint as ocp

    plain, _ = _split_keys(state)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), plain, force=True)


def restore_checkpoint_orbax(path: str, template: Any) -> Any:
    """Restore an orbax checkpoint saved by `save_checkpoint_orbax`.

    `template` supplies structure, dtypes, and (if placed on a mesh) the
    target shardings; PRNG keys are re-wrapped from raw key data.
    """
    import orbax.checkpoint as ocp

    plain_tmpl, mask = _split_keys(template)
    with ocp.StandardCheckpointer() as ckptr:
        plain = ckptr.restore(os.path.abspath(path), plain_tmpl)
    leaves, treedef = jax.tree_util.tree_flatten(plain)
    restored = [jax.random.wrap_key_data(x) if m else x
                for x, m in zip(leaves, mask)]
    return jax.tree_util.tree_unflatten(treedef, restored)
