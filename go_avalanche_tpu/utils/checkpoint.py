"""Checkpoint / resume for simulator states.

The reference has no checkpointing — its entire state is three Go maps
(`processor.go:16-19`) that die with the process (SURVEY.md section 5).  The
batched states here are pytrees of dense arrays + a PRNG key + the round
counter, so a checkpoint is an exact, bit-for-bit resumable snapshot: restore
and the simulation continues on the identical deterministic trajectory.

Two interchangeable backends, same pytree/template contract:

  * `save_checkpoint` / `restore_checkpoint` — a single .npz of the
    flattened leaves (typed PRNG keys serialized via
    `jax.random.key_data`).  Zero extra dependencies, one file, ideal for
    single-host simulation sweeps.
  * `save_checkpoint_orbax` / `restore_checkpoint_orbax` — orbax
    `StandardCheckpointer` directory format: sharding-aware and
    multi-host-safe, the right backend when the state lives on a
    `jax.sharding.Mesh` across processes (`parallel/runtime.py`).
    Gated on `import orbax` so the core package keeps its jax+numpy-only
    dependency footprint (`pyproject.toml` extra: `checkpoint`).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

_KEY_PREFIX = "__prngkey__"


class CheckpointFetchTimeout(TimeoutError):
    """A bounded device→host fetch missed its deadline.

    Raised by `save_checkpoint(..., fetch_timeout_s=...)` so the caller can
    abort the *save* and keep the run alive — a wedged tunnel must cost a
    checkpoint, never the simulation (the round-4 outage was triggered by a
    process killed mid-way through a 1.9 GB monolithic fetch;
    `benchmarks/PERF_NOTES.md`).
    """


def _is_key(leaf: Any) -> bool:
    return isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
        leaf.dtype, jax.dtypes.prng_key)


def _fetch(arr: Any, timeout_s: Optional[float]) -> np.ndarray:
    """`jax.device_get` with an optional deadline.

    The fetch runs on a throwaway *daemon* thread (not a pool:
    `ThreadPoolExecutor` workers are non-daemon and joined at interpreter
    exit, so one wedged transfer would hang process shutdown — the exact
    failure mode this exists to contain).  On timeout the worker stays
    blocked on the dead transfer (it cannot be cancelled) and is simply
    orphaned; the caller's thread is never the one stuck.
    """
    if timeout_s is None:
        return np.asarray(jax.device_get(arr))
    import threading

    box: list = []

    def work() -> None:
        try:
            box.append(("ok", np.asarray(jax.device_get(arr))))
        except Exception as e:  # noqa: BLE001 — re-raised in the caller
            box.append(("err", e))

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if not box:
        raise CheckpointFetchTimeout(
            f"device→host fetch of {getattr(arr, 'nbytes', '?')} bytes "
            f"exceeded {timeout_s}s — aborting this save (run continues)")
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


def _row_blocks(shape: tuple, nbytes: int,
                cap: Optional[int]):
    """Axis-0 slice ranges bounding each transfer to ~`cap` bytes.

    The ONE place the block math lives — save (`_fetch_leaf`) and restore
    (`_put_bounded`) must never disagree on transfer bounds.  Yields
    nothing when the whole array fits (or can't be row-sliced): callers
    then move it in one transfer.
    """
    if (cap is None or nbytes <= cap or len(shape) == 0 or shape[0] <= 1):
        return
    row_bytes = max(1, nbytes // shape[0])
    rows = max(1, cap // row_bytes)
    for lo in range(0, shape[0], rows):
        yield lo, min(lo + rows, shape[0])


def _fetch_leaf(
    leaf: Any,
    max_fetch_bytes: Optional[int],
    fetch_timeout_s: Optional[float],
) -> np.ndarray:
    """Materialize one leaf on host, never moving more than
    `max_fetch_bytes` per transfer.

    Oversized leaves are sliced on-device along axis 0 in row blocks, so the
    tunnel sees a sequence of bounded transfers instead of one monolithic
    fetch, and each block independently gets the `fetch_timeout_s` deadline.
    """
    if not isinstance(leaf, jax.Array):
        return np.asarray(leaf)
    blocks = list(_row_blocks(leaf.shape, leaf.size * leaf.dtype.itemsize,
                              max_fetch_bytes))
    if not blocks:
        return _fetch(leaf, fetch_timeout_s)
    out = np.empty(leaf.shape, dtype=leaf.dtype)
    for lo, hi in blocks:
        out[lo:hi] = _fetch(leaf[lo:hi], fetch_timeout_s)
    return out


def save_checkpoint(
    path: str,
    state: Any,
    *,
    max_fetch_bytes: Optional[int] = None,
    fetch_timeout_s: Optional[float] = None,
) -> None:
    """Save any simulator state pytree to `path` (.npz).

    `max_fetch_bytes` bounds every single device→host transfer: leaves
    bigger than the cap are pulled in row blocks sliced on-device, so a
    north-star-scale state (~1.9 GB of `[N, W]` planes) streams through the
    tunnel as e.g. 64 MB pieces instead of one monolithic fetch — the
    documented round-4 outage trigger.  `fetch_timeout_s` puts a deadline on
    each transfer; a miss raises `CheckpointFetchTimeout` *before* anything
    is written, so the partial save is simply discarded and the caller's run
    continues.  Defaults (`None`) keep the original unbounded behavior.
    """
    leaves, _ = jax.tree_util.tree_flatten(state)
    payload = {"__leaf_count__": np.asarray(len(leaves))}
    for i, leaf in enumerate(leaves):
        if _is_key(leaf):
            payload[f"{_KEY_PREFIX}{i}"] = np.asarray(
                jax.random.key_data(leaf))
        else:
            payload[f"leaf_{i}"] = _fetch_leaf(
                leaf, max_fetch_bytes, fetch_timeout_s)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic: no torn checkpoints on interruption


def _put_bounded(arr: np.ndarray,
                 max_transfer_bytes: Optional[int]) -> Any:
    """Host→device placement, never moving more than `max_transfer_bytes`
    per transfer (the restore-side mirror of `_fetch_leaf`: a process
    killed mid-way through one monolithic transfer is the documented
    tunnel-wedge trigger, and the north-star watchdog can legitimately
    kill a worker mid-restore).  Oversized leaves go up in row blocks and
    are concatenated on device (transiently 2x that leaf's bytes)."""
    import jax.numpy as jnp

    blocks = list(_row_blocks(arr.shape, arr.nbytes, max_transfer_bytes))
    if not blocks:
        return jnp.asarray(arr)
    return jnp.concatenate([jnp.asarray(arr[lo:hi]) for lo, hi in blocks],
                           axis=0)


def restore_checkpoint(path: str, template: Any, *,
                       max_transfer_bytes: Optional[int] = None) -> Any:
    """Restore a state saved by `save_checkpoint`.

    `template` is any state with the same pytree structure (e.g. a freshly
    `init()`-ed one); its structure and static aux data are reused, its array
    values are replaced.  Shape/dtype mismatches raise ValueError.
    `max_transfer_bytes` bounds each host→device transfer (see
    `_put_bounded`); `None` keeps whole-leaf placement.
    """
    leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(path) as data:
        if "__leaf_count__" in data:   # absent in pre-marker checkpoints
            saved = int(data["__leaf_count__"])
            if saved != len(leaves):
                raise ValueError(
                    f"checkpoint has {saved} leaves, template has "
                    f"{len(leaves)} — saved from a structurally different "
                    f"state (e.g. the opposite `track_finality` mode, or "
                    f"another model/config); rebuild the template to match "
                    f"how the checkpoint was produced")
        restored = []
        for i, leaf in enumerate(leaves):
            key_name, plain_name = f"{_KEY_PREFIX}{i}", f"leaf_{i}"
            if _is_key(leaf):
                if key_name not in data:
                    raise ValueError(
                        f"checkpoint leaf {i}: expected a PRNG key")
                restored.append(jax.random.wrap_key_data(
                    jax.numpy.asarray(data[key_name])))
                continue
            if plain_name not in data:
                raise ValueError(f"checkpoint missing leaf {i} "
                                 f"(template/checkpoint structure mismatch)")
            arr = data[plain_name]
            # Validate against what the leaf becomes ON DEVICE (the old
            # behavior): under jax_enable_x64=False an int64/float64
            # template leaf materializes as int32/float32, and a
            # checkpoint that only matches the wider host dtype must
            # still fail LOUDLY rather than silently downcast.
            want = jax.numpy.asarray(leaf)
            if arr.shape != want.shape or arr.dtype != want.dtype:
                raise ValueError(
                    f"checkpoint leaf {i}: got {arr.dtype}{list(arr.shape)}, "
                    f"template has {want.dtype}{list(want.shape)}")
            restored.append(_put_bounded(arr, max_transfer_bytes))
    return jax.tree_util.tree_unflatten(treedef, restored)


# ---------------------------------------------------------------------------
# Orbax backend (optional dependency; sharding-aware, multi-host-safe)


def _split_keys(state: Any):
    """(state with PRNG keys replaced by raw key data, key-position mask)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    mask = [_is_key(x) for x in leaves]
    plain = [jax.random.key_data(x) if m else x
             for x, m in zip(leaves, mask)]
    return jax.tree_util.tree_unflatten(treedef, plain), mask


def save_checkpoint_orbax(path: str, state: Any) -> None:
    """Save a state pytree as an orbax checkpoint directory at `path`.

    Unlike the .npz backend this preserves shardings and coordinates
    multi-host saves; use it when the state was placed on a mesh.
    """
    import orbax.checkpoint as ocp

    plain, _ = _split_keys(state)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), plain, force=True)


def restore_checkpoint_orbax(path: str, template: Any) -> Any:
    """Restore an orbax checkpoint saved by `save_checkpoint_orbax`.

    `template` supplies structure, dtypes, and (if placed on a mesh) the
    target shardings; PRNG keys are re-wrapped from raw key data.
    """
    import orbax.checkpoint as ocp

    plain_tmpl, mask = _split_keys(template)
    with ocp.StandardCheckpointer() as ckptr:
        plain = ckptr.restore(os.path.abspath(path), plain_tmpl)
    leaves, treedef = jax.tree_util.tree_flatten(plain)
    restored = [jax.random.wrap_key_data(x) if m else x
                for x, m in zip(leaves, mask)]
    return jax.tree_util.tree_unflatten(treedef, restored)
