"""Observability: finality statistics, status-update extraction, throughput.

The reference's only observability is a logging flag and the `StatusUpdate`
stream (`avalanche.go:59-62`, example `main.go:143-157`); SURVEY.md section 5
calls for keeping that stream concept plus the north-star metrics
(votes/sec, rounds-to-finality histograms).  Everything here consumes the
on-device telemetry/state and reduces on host — nothing runs in the hot loop.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.types import Status, StatusUpdate


def rounds_to_finality(finalized_at) -> Dict[str, float]:
    """Summary statistics of the `finalized_at` plane (-1 = never).

    The paper-curve metric (BASELINE.json): min / mean / median / p90 / max
    rounds until finalization, plus the unfinalized fraction.

    A state built with `track_finality=False` has no plane; raise a
    directed error rather than a bare TypeError deep in numpy.
    """
    if finalized_at is None:
        raise ValueError(
            "finalized_at is None: the state was built with "
            "track_finality=False; per-(node,tx) finality stats need "
            "init(track_finality=True) (streaming paths record latency "
            "per set/tx in their output planes instead)")
    fat = np.asarray(jax.device_get(finalized_at)).ravel()
    done = fat[fat >= 0]
    out = {"unfinalized_fraction": float((fat < 0).mean())}
    if done.size:
        out.update(
            min=float(done.min()),
            mean=float(done.mean()),
            median=float(np.median(done)),
            p90=float(np.percentile(done, 90)),
            max=float(done.max()),
        )
    return out


def finality_curve(finalizations, population: int) -> np.ndarray:
    """Cumulative finalized fraction per round from stacked telemetry — the
    rounds-to-finality curve to plot against the Avalanche paper's."""
    f = np.asarray(jax.device_get(finalizations)).astype(np.float64)
    return np.cumsum(f) / float(population)


def safety_failure(decided, value, honest=None) -> bool:
    """Did two honest nodes irreversibly decide OPPOSITE values?

    The Avalanche paper's safety event (single-decree): `decided` is a bool
    [N] plane of irreversible decisions (finalized / accepted-at>=0),
    `value` the bool [N] decided color, `honest` an optional bool [N] mask
    (byzantine nodes cannot violate safety by construction — they have no
    honest decision to contradict).
    """
    decided = np.asarray(jax.device_get(decided)).astype(bool).ravel()
    value = np.asarray(jax.device_get(value)).astype(bool).ravel()
    if honest is not None:
        h = np.asarray(jax.device_get(honest)).astype(bool).ravel()
        decided = decided & h
    dv = value[decided]
    return bool(dv.size and dv.any() and not dv.all())


def status_plane(confidence, cfg: AvalancheConfig = DEFAULT_CONFIG):
    """Per-record Status codes (int8 plane), device-side."""
    return vr.status(confidence, cfg)


def extract_status_updates(
    changed,
    confidence,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
) -> List[StatusUpdate]:
    """Host-side StatusUpdate list for one node's row (or any 1-D slice).

    The batched equivalent of the `updates` out-param of RegisterVotes
    (`processor.go:111`): records whose `changed` flag fired, with their new
    status.  Target "hash" is the array index.
    """
    changed = np.asarray(jax.device_get(changed)).ravel()
    codes = np.asarray(jax.device_get(status_plane(confidence, cfg))).ravel()
    return [StatusUpdate(int(i), Status(int(codes[i])))
            for i in np.nonzero(changed)[0]]


def votes_per_second(total_votes: int, seconds: float) -> float:
    return total_votes / seconds if seconds > 0 else float("inf")


def telemetry_summary(telemetry) -> Dict[str, int]:
    """Sum stacked per-round telemetry into run totals.

    ONE `jax.device_get` on the whole telemetry pytree — a single
    device->host transfer however many fields the tuple grows — then
    host-side sums per field.
    """
    host = jax.device_get(telemetry)
    return {
        field: int(np.asarray(getattr(host, field)).sum())
        for field in host._fields
    }
