"""Live-traffic service mode: streaming tx arrival + finality-latency SLOs.

Everything the repo simulated before this module drained a fixed
pre-seeded backlog; a production pre-consensus layer ingests a *stream*
of transactions with user-facing latency SLOs (TangleSim, PAPERS.md
arXiv 2305.01232, frames exactly this confirmation-latency-under-load
question for DAG ledgers).  This module adds the three planes the
streaming schedulers (`models/backlog.py`, `models/streaming_dag.py`)
thread through their state when `cfg.arrivals_enabled()`:

  * **arrival process** — jit-static rate schedules (Poisson / bursty /
    diurnal, `schedule_rate`), realized per round from a PRNG key folded
    off the sim's init key (`init_traffic`).  The backlog array order IS
    the arrival stream order: a per-round Poisson draw advances an
    `arrived_idx` watermark, and admission (`_retire_and_refill`'s
    `take`) is gated on it — fresh txs enter the working set as
    finalized columns retire, never before they arrive.  The draw is a
    pure function of (config, key, round, occupancy), so dense and
    sharded runs — and every Monte-Carlo fleet trial — realize the SAME
    arrival sequence for the same key (`tests/test_traffic.py`).
  * **per-tx arrival-round plane** — `arrival_round` ``[B]`` stamps the
    round each unit arrived, making finality latency (arrival round →
    settle round) computable in-graph: retiring slots scatter-add their
    latencies into a fixed-depth histogram (`latency_delta`), from which
    the flight recorder emits EXACT nearest-rank p50/p99/p999
    percentiles per round (`percentile_from_hist`; host twin
    `latency_percentiles_host` recomputes them bit-for-bit from the
    per-tx outputs — the acceptance check of
    `examples/capacity_planning.py`).
  * **closed-loop admission** — `backpressure_factor` throttles the
    scheduled rate by working-set occupancy ((lo, hi) fractions, linear
    ramp), turning the simulator into a capacity-planning tool: "what
    sustained tx/s does an N-node network absorb at p99 finality < X
    rounds?".

`arrival_mode="external"` allocates the same planes but draws nothing:
arrivals are pushed from outside the graph (`push_arrivals`), which is
how the Connector service (`connector/server.py` SIM_SUBMIT) lets an
external harness act as a live load generator.

Everything here is statically absent when `cfg.arrival_mode == "off"`
(`init_traffic` returns None, the schedulers skip every call at the
Python level), so every archived hlo pin stays byte-identical —
machine-checked by `benchmarks/hlo_pin.py --verify-off-path`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from go_avalanche_tpu.config import AvalancheConfig

# Key-derivation fold for the arrival stream: the traffic key is
# fold_in(sim init key, this), so arrivals never perturb the consensus
# PRNG stream — an arrival-on run with everything arrived at round 0 is
# bit-identical to the arrival-off run (tests/test_traffic.py).
_TRAFFIC_FOLD = 0x7AF1C

# The nearest-rank percentile fractions the recorder emits, as exact
# integer (num, den) pairs — p50 / p99 / p999.
PERCENTILES = ((1, 2), (99, 100), (999, 1000))


class TrafficState(NamedTuple):
    """The live-traffic plane carried in a streaming scheduler's state.

    ``B`` is the scheduler's admission-unit count (txs for
    `models/backlog`, conflict SETS for `models/streaming_dag`); ``L``
    is `cfg.arrival_latency_buckets`.  Replicated (`P()`) across every
    mesh axis in the sharded drivers — the draw is identical on every
    shard, like the backlog metadata it gates.
    """

    key: jax.Array            # PRNG key — the arrival stream's own fold
    arrived_idx: jax.Array    # int32 — units arrived so far (admission
                              #   watermark into the backlog order)
    arrival_round: jax.Array  # int32 [B] — round each unit arrived;
                              #   -1 while still in the future
    lat_hist: jax.Array       # int32 [L] — settled finality-latency
                              #   histogram (arrival -> settle rounds,
                              #   clamped into [0, L))


class TrafficTelemetry(NamedTuple):
    """Per-round traffic scalars (flattened into the JSONL schema,
    docs/observability.md): the arrival counters plus the cumulative
    finality-latency percentiles."""

    arrivals: jax.Array       # int32 — units arrived this round
    arrived_total: jax.Array  # int32 — cumulative arrivals
    lat_count: jax.Array      # int32 — settled units in the histogram
    lat_p50: jax.Array        # int32 — nearest-rank percentiles over
    lat_p99: jax.Array        #   every settled unit so far; -1 while
    lat_p999: jax.Array       #   nothing has settled


def init_traffic(cfg: AvalancheConfig, key: jax.Array,
                 n_units: int) -> Optional[TrafficState]:
    """The scheduler-side constructor: None (statically absent) when
    arrivals are off, else a fresh plane over `n_units` backlog units.
    `key` is the sim's init key — the traffic stream folds its own
    subkey off it, so consensus draws are untouched."""
    if not cfg.arrivals_enabled():
        return None
    return TrafficState(
        key=jax.random.fold_in(key, _TRAFFIC_FOLD),
        arrived_idx=jnp.int32(0),
        arrival_round=jnp.full((n_units,), -1, jnp.int32),
        lat_hist=jnp.zeros((cfg.arrival_latency_buckets,), jnp.int32),
    )


def schedule_rate(cfg: AvalancheConfig, round_: jax.Array) -> jax.Array:
    """float32 scalar: the jit-static schedule's offered rate at
    `round_` (before admission control).  The schedule SHAPE is static
    config; only the round is traced."""
    rate = jnp.float32(cfg.arrival_rate)
    if cfg.arrival_mode == "poisson":
        return rate
    if cfg.arrival_mode == "bursty":
        burst_rounds = max(1, int(round(cfg.arrival_duty
                                        * cfg.arrival_period)))
        in_burst = jnp.mod(round_, cfg.arrival_period) < burst_rounds
        return jnp.where(in_burst,
                         rate * jnp.float32(cfg.arrival_burst_factor),
                         rate)
    if cfg.arrival_mode == "diurnal":
        phase = (2.0 * np.pi / cfg.arrival_period) * round_.astype(
            jnp.float32)
        return rate * (1.0 + jnp.float32(cfg.arrival_depth)
                       * jnp.sin(phase))
    # "external": the schedule draws nothing (push_arrivals feeds it).
    return jnp.float32(0.0)


def backpressure_factor(cfg: AvalancheConfig,
                        occupancy_frac: jax.Array) -> jax.Array:
    """float32 in [0, 1]: the closed-loop admission multiplier — 1 below
    the lo occupancy fraction, 0 above hi, linear ramp in between.
    Statically 1.0 (no traced op) without `cfg.arrival_backpressure`."""
    if cfg.arrival_backpressure is None:
        return jnp.float32(1.0)
    lo, hi = cfg.arrival_backpressure
    return jnp.clip((jnp.float32(hi) - occupancy_frac.astype(jnp.float32))
                    / jnp.float32(hi - lo), 0.0, 1.0)


def arrive(traffic: TrafficState, cfg: AvalancheConfig,
           round_: jax.Array, occupied: jax.Array,
           capacity: int) -> Tuple[TrafficState, jax.Array]:
    """One round of the arrival process: draw `Poisson(schedule *
    backpressure)` new units, advance the watermark, stamp their
    arrival rounds.  Returns (new_traffic, arrivals this round).

    `occupied` is the working set's occupied-slot count at step entry
    (an int32 scalar, identical dense and sharded — the sharded drivers
    psum it over the txs axis), `capacity` the static slot count; their
    ratio is the backpressure signal.
    """
    b = traffic.arrival_round.shape[0]
    if cfg.arrival_mode == "external":
        # Pushed arrivals only: no draw, no key consumption — the plane
        # advances exclusively through `push_arrivals`.
        return traffic, jnp.int32(0)
    lam = (schedule_rate(cfg, round_)
           * backpressure_factor(
               cfg, occupied.astype(jnp.float32) / jnp.float32(capacity)))
    if cfg.arrival_cluster_weights is not None:
        # Per-cluster arrival skew (hot regions): units partition into
        # n_clusters contiguous admission-order blocks via THE one
        # cluster_of spelling (`ops/sampling.py` — the same partition
        # nodes use), and the draw's rate scales by the stream head's
        # region weight, so a hot region's block drains proportionally
        # faster.  Statically absent when unset (flagship_traffic pin
        # byte-identical).
        from go_avalanche_tpu.ops.sampling import cluster_of

        wts = jnp.asarray(cfg.arrival_cluster_weights, jnp.float32)
        head = cluster_of(jnp.clip(traffic.arrived_idx, 0, b - 1),
                          cfg.n_clusters, b)
        lam = lam * wts[head]
    key, sub = jax.random.split(traffic.key)
    n_new = jnp.minimum(
        jax.random.poisson(sub, lam).astype(jnp.int32),
        jnp.int32(b) - traffic.arrived_idx)
    new_idx = traffic.arrived_idx + n_new
    pos = jnp.arange(b, dtype=jnp.int32)
    arrival_round = jnp.where(
        (pos >= traffic.arrived_idx) & (pos < new_idx),
        round_.astype(jnp.int32), traffic.arrival_round)
    return traffic._replace(key=key, arrived_idx=new_idx,
                            arrival_round=arrival_round), n_new


def push_arrivals(traffic: TrafficState, count, round_) -> TrafficState:
    """Advance the arrival watermark by `count` units arriving NOW —
    the external-load-generator path (`arrival_mode="external"`; the
    Connector SIM_SUBMIT message).  Composes with any mode: pushed
    units stamp like drawn ones."""
    b = traffic.arrival_round.shape[0]
    count = jnp.asarray(count, jnp.int32)
    new_idx = jnp.minimum(traffic.arrived_idx + jnp.maximum(count, 0),
                          jnp.int32(b))
    pos = jnp.arange(b, dtype=jnp.int32)
    arrival_round = jnp.where(
        (pos >= traffic.arrived_idx) & (pos < new_idx),
        jnp.asarray(round_, jnp.int32), traffic.arrival_round)
    return traffic._replace(arrived_idx=new_idx,
                            arrival_round=arrival_round)


def latency_delta(cfg: AvalancheConfig, latency: jax.Array,
                  count: jax.Array) -> jax.Array:
    """int32 [L] histogram increment: `count[i]` samples at bucket
    `clamp(latency[i], 0, L-1)` wherever `count[i] > 0`.

    Returned as a DELTA (scatter-add into zeros) rather than an updated
    histogram so the sharded drivers can psum per-shard deltas over the
    txs axis before adding — integer adds, so sharded == dense
    bit-for-bit.
    """
    buckets = cfg.arrival_latency_buckets
    idx = jnp.clip(latency, 0, buckets - 1)
    idx = jnp.where(count > 0, idx, buckets)          # buckets = dropped
    return (jnp.zeros((buckets,), jnp.int32)
            .at[idx].add(jnp.maximum(count, 0), mode="drop"))


def percentile_from_hist(hist: jax.Array, q_num: int,
                         q_den: int) -> jax.Array:
    """int32 scalar: the exact nearest-rank q-th percentile of the
    integer samples in `hist` — the smallest bucket v with
    ``cumsum(hist)[v] >= ceil(q * total)``; -1 while the histogram is
    empty.  Integer arithmetic throughout so the host twin
    (`latency_percentiles_host`) reproduces it bit-for-bit."""
    total = hist.sum().astype(jnp.int32)
    target = (total * q_num + (q_den - 1)) // q_den
    cum = jnp.cumsum(hist)
    idx = jnp.argmax(cum >= target).astype(jnp.int32)
    return jnp.where(total > 0, idx, jnp.int32(-1))


def traffic_telemetry(traffic: TrafficState,
                      arrivals: jax.Array) -> TrafficTelemetry:
    """Assemble the per-round traffic scalars (percentiles are over
    every unit settled SO FAR — the cumulative SLO view)."""
    (p50n, p50d), (p99n, p99d), (p999n, p999d) = PERCENTILES
    return TrafficTelemetry(
        arrivals=arrivals,
        arrived_total=traffic.arrived_idx,
        lat_count=traffic.lat_hist.sum().astype(jnp.int32),
        lat_p50=percentile_from_hist(traffic.lat_hist, p50n, p50d),
        lat_p99=percentile_from_hist(traffic.lat_hist, p99n, p99d),
        lat_p999=percentile_from_hist(traffic.lat_hist, p999n, p999d),
    )


def latency_percentiles(traffic: Optional[TrafficState]) -> dict:
    """Host-side digest of a final state's traffic plane: arrived
    total, settled sample count, and the p50/p99/p999 the recorder
    would emit (one device_get).  {} when the plane is absent."""
    if traffic is None:
        return {}
    tel = jax.device_get(traffic_telemetry(traffic, jnp.int32(0)))
    return {
        "arrived_total": int(tel.arrived_total),
        "finality_latency_count": int(tel.lat_count),
        "finality_latency_p50": int(tel.lat_p50),
        "finality_latency_p99": int(tel.lat_p99),
        "finality_latency_p999": int(tel.lat_p999),
    }


def latency_percentiles_host(arrival_round, settle_round, weights,
                             buckets: int) -> dict:
    """The HOST twin of the in-graph percentiles: rebuild the clamped
    histogram from per-unit outputs (numpy) and apply the same integer
    nearest-rank formula — must match `latency_percentiles` bit-for-bit
    on the same run (the capacity-planning acceptance check).

    `weights[i]` is unit i's sample count (0 = not settled; a conflict
    set contributes one sample per valid member).
    """
    arrival = np.asarray(arrival_round).reshape(-1)
    settle = np.asarray(settle_round).reshape(-1)
    w = np.asarray(weights).astype(np.int64).reshape(-1)
    mask = w > 0
    lat = np.clip(settle[mask] - arrival[mask], 0, buckets - 1)
    hist = np.zeros((buckets,), np.int64)
    np.add.at(hist, lat.astype(np.int64), w[mask])
    total = int(hist.sum())
    cum = np.cumsum(hist)
    out = {"finality_latency_count": total}
    for name, (num, den) in zip(("p50", "p99", "p999"), PERCENTILES):
        if total == 0:
            out[f"finality_latency_{name}"] = -1
            continue
        target = (total * num + (den - 1)) // den
        out[f"finality_latency_{name}"] = int(
            np.argmax(cum >= target))
    return out
