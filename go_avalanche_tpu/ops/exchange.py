"""The fused peer-exchange engine: single-gather vote collection and
one-shot gossip scatter.

The legacy round (`models/avalanche.round_step` pre-fusion) structured its
peer-exchange phase as k sequential passes: k row-gathers of the bit-packed
preference plane (one per draw, `adversary.pack_adversarial_votes`) and, with
gossip on, k sequential scatter-ORs for admission.  DAG-Sword
(arxiv 2311.04638) and TangleSim (arxiv 2305.01232) both identify
message-exchange aggregation as the scaling bottleneck of large-network
ledger simulators; on TPU the same bottleneck shows up as gather/scatter
DISPATCH COUNT — k serially-dependent HLO ops where one would do.  This
module collapses both loops:

  * `fused_vote_packs` — ONE flattened gather of ``peers.reshape(N*k)`` rows
    of the packed ``[n_src, ceil(T/8)]`` preference plane, bit-transposed
    (element-wise, fully fusable) into the ``(yes_pack, consider_pack)``
    ``[N, T]`` uint8 k-vote planes that `voterecord.register_packed_votes`
    consumes.  The gather moves exactly the bytes the k legacy gathers moved
    (N*k*T/8), but as a single HLO with no inter-pass dependencies.
  * `fused_gossip_heard` — scatter-max over the flattened
    ``(peer, polled-plane)`` pairs instead of k serially-dependent
    scatter-ORs, bit-packed so each pass's update operand is ``[N*k, T/8]``
    (values are single-bit bytes, so max IS or; duplicate peer draws
    combine exactly as the k-pass loop combined them).

Both are bit-exact against the legacy loops on every config axis
(tests/test_exchange.py golden parity); `gather_vote_packs` dispatches on
`cfg.fused_exchange` so either engine can be selected per run.

The sharded drivers reuse `gather_vote_packs` with the all-gathered
replicated plane as `packed_prefs` (global peer ids index it directly).
Their gossip path keeps its own variant
(`parallel/sharded._gossip_heard_packed`) — same per-bit packed scatter
idiom as `fused_gossip_heard`, plus the cross-shard `all_to_all` OR the
single-chip form doesn't need.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from typing import Optional

from go_avalanche_tpu.config import AvalancheConfig
from go_avalanche_tpu.ops import adversary
from go_avalanche_tpu.ops.bitops import pack_bool_plane, unpack_bool_plane


def fused_vote_packs(
    packed_prefs: jax.Array,
    peers: jax.Array,
    responded: jax.Array,
    lie: jax.Array,
    key: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
    t: int,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> tuple:
    """Single-gather k-vote collection; returns ``(yes_pack, consider_pack)``.

    `packed_prefs` is the bit-packed preference plane ``[n_src, ceil(t/8)]``
    (n_src >= N in sharded use: peer ids are global); `peers` int32
    ``[N, k]``, `responded`/`lie` bool ``[N, k]``.  The gathered
    ``[N, k, ceil(t/8)]`` cube is unpacked and re-packed along the DRAW axis
    (bit j of `yes_pack` = draw j's vote) — a bit-transpose that is pure
    element-wise shift/sum, so XLA fuses it into the gather's consumers and
    the bool ``[N, k, T]`` cube never materializes in HBM.
    """
    n, k = peers.shape
    if not (0 < k <= 8):
        raise ValueError("k must be in (0, 8] for uint8 packing")
    t8 = packed_prefs.shape[-1]
    flat = packed_prefs[peers.reshape(n * k)]            # THE one gather
    votes = unpack_bool_plane(flat.reshape(n, k, t8), t)   # [N, k, T] bools
    votes = adversary.apply_draw_planes(key, votes, lie, cfg, minority_t,
                                        ctx)
    shifts = jnp.arange(k, dtype=jnp.uint8)
    yes_pack = (votes.astype(jnp.uint8) << shifts[None, :, None]).sum(
        axis=1).astype(jnp.uint8)
    consider = (responded.astype(jnp.uint8) << shifts[None, :]).sum(
        axis=1).astype(jnp.uint8)
    consider_pack = jnp.broadcast_to(consider[:, None], (n, t))
    return yes_pack, consider_pack


def legacy_vote_packs(
    packed_prefs: jax.Array,
    peers: jax.Array,
    responded: jax.Array,
    lie: jax.Array,
    key: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
    t: int,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> tuple:
    """The k-pass engine: one row-gather + unpack + adversary pass per draw
    (`adversary.pack_adversarial_votes`).  Kept selectable
    (`cfg.fused_exchange=False`) as the golden-parity reference."""
    return adversary.pack_adversarial_votes(
        lambda j: unpack_bool_plane(packed_prefs[peers[:, j]], t),
        responded, lie, key, cfg, minority_t, ctx)


def gather_vote_packs(
    packed_prefs: jax.Array,
    peers: jax.Array,
    responded: jax.Array,
    lie: jax.Array,
    key: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
    t: int,
    ctx: Optional[adversary.PolicyCtx] = None,
) -> tuple:
    """The exchange-engine dispatch every multi-target round calls
    (`models/avalanche`, `models/dag`, `parallel/sharded*`): fused
    single-gather engine or the legacy k-pass loop, per
    `cfg.fused_exchange`.  Both return identical bits."""
    engine = fused_vote_packs if cfg.fused_exchange else legacy_vote_packs
    return engine(packed_prefs, peers, responded, lie, key, cfg,
                  minority_t, t, ctx)


def fused_gossip_heard(peers: jax.Array, polled_u8: jax.Array) -> jax.Array:
    """Flattened gossip admission scatter; uint8 ``[N, T]`` heard plane.

    The flattened form of the k-pass scatter-OR loop (`main.go:177`
    batched): every (poller i, draw j) pair contributes poller i's polled
    plane to row ``peers[i, j]``, all N*k pairs per scatter — no serial
    dependency between passes, unlike the legacy loop's k chained
    scatter-ORs.  The polled plane is BIT-PACKED along txs first and
    scattered one bit position per pass (a max-scatter of values in
    {0, 1<<b} IS an or-scatter — `parallel/sharded._gossip_heard_packed`'s
    idiom), so the repeated update operand is ``[N*k, T/8]``: at k=8 the
    transient equals the legacy loop's single ``[N, T]`` operand instead
    of 8x it (a bare one-shot uint8 scatter would stage ~1.6 GB at the
    100k x 2048 north-star shape).  `jnp.repeat` aligns update rows with
    ``peers.reshape(N*k)`` — row-major, so pair (i, j) sits at i*k + j.
    Duplicate targets resolve exactly as the sequential maxes did.
    """
    n, t = polled_u8.shape
    k = peers.shape[1]
    idx = peers.reshape(n * k)
    packed = pack_bool_plane(polled_u8.astype(jnp.bool_))   # [N, ceil(T/8)]
    t8 = packed.shape[1]
    heard8 = jnp.zeros((n, t8), jnp.uint8)
    for b in range(8):
        src = packed & jnp.uint8(1 << b)
        upd = jnp.repeat(src, k, axis=0)                    # [N*k, T/8]
        heard8 |= jnp.zeros((n, t8), jnp.uint8).at[idx].max(upd)
    return unpack_bool_plane(heard8, t).astype(jnp.uint8)


def legacy_gossip_heard(peers: jax.Array, polled_u8: jax.Array) -> jax.Array:
    """The k-pass gossip admission: one scatter-OR per draw (golden-parity
    reference for `fused_gossip_heard`)."""
    n, t = polled_u8.shape
    heard = jnp.zeros((n, t), jnp.uint8)
    for j in range(peers.shape[1]):
        heard = heard.at[peers[:, j]].max(polled_u8)
    return heard


def gossip_heard(peers: jax.Array, polled_u8: jax.Array,
                 cfg: AvalancheConfig) -> jax.Array:
    """Gossip-admission dispatch on `cfg.fused_exchange`."""
    if cfg.fused_exchange:
        return fused_gossip_heard(peers, polled_u8)
    return legacy_gossip_heard(peers, polled_u8)
