"""Adversary vote transforms (SURVEY.md §2.4 item 5).

The reference's only adversarial hook is the commented-out random vote flip
in the example (`examples/basic-preconcensus/main.go:184-187`).  The
Avalanche paper that the reference links (`README.md:15`) analyses stronger
adversaries; this module implements the three standard strategies as pure
transforms applied to gathered peer votes, shared by every model in the
family (`models/snowball`, `models/family`, `models/avalanche`,
`models/dag`, `parallel/sharded`):

  FLIP            — lie with the opposite of the peer's true preference
                    (the reference hook, verbatim).
  EQUIVOCATE      — lie with a fresh coin per (querier, draw[, target]):
                    the same byzantine peer tells different queriers
                    different things within one round.
  OPPOSE_MAJORITY — lie with the current global *minority* color, the
                    paper's liveness adversary: it fights convergence by
                    pulling the network back toward an even split.

Every strategy triggers per (querier, draw) with `cfg.flip_probability`,
and only for byzantine peers, so `FLIP` with `flip_probability=0.35`
reproduces the reference hook exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig


def lie_mask(
    key: jax.Array,
    peers: jax.Array,
    byzantine: jax.Array,
    cfg: AvalancheConfig,
) -> jax.Array:
    """Bool ``[N, k]`` — draws on which the sampled peer lies.

    A draw lies iff the sampled peer is byzantine AND an independent
    Bernoulli(`cfg.flip_probability`) fires for this (querier, draw).
    """
    return byzantine[peers] & jax.random.bernoulli(
        key, cfg.flip_probability, peers.shape)


def minority_color(prefs: jax.Array) -> jax.Array:
    """Scalar bool — the color currently held by *fewer* nodes.

    `prefs` is the bool ``[N]`` true-preference plane.  Ties count "no" as
    the minority, so a perfectly split network keeps being pulled down.
    """
    n = prefs.shape[0]
    return prefs.sum() * 2 < n


def minority_plane(prefs: jax.Array) -> jax.Array:
    """Bool ``[T]`` — per-target minority color over a ``[N, T]`` plane."""
    n = prefs.shape[0]
    return prefs.sum(axis=0) * 2 < n


def apply_1d(
    key: jax.Array,
    votes: jax.Array,
    lie: jax.Array,
    cfg: AvalancheConfig,
    prefs: jax.Array,
) -> jax.Array:
    """Adversary transform for single-decree models.

    `votes`/`lie` are bool ``[N, k]``; `prefs` is the bool ``[N]`` true
    preference plane (used only by OPPOSE_MAJORITY).  Returns the
    post-adversary ``[N, k]`` votes.  `key` may be the same key used for
    `lie_mask` — the coin folds in a constant to decorrelate.
    """
    s = cfg.adversary_strategy
    if s is AdversaryStrategy.FLIP:
        return jnp.logical_xor(votes, lie)
    if s is AdversaryStrategy.EQUIVOCATE:
        coin = jax.random.bernoulli(jax.random.fold_in(key, 0x5A), 0.5,
                                    votes.shape)
        return jnp.where(lie, coin, votes)
    return jnp.where(lie, minority_color(prefs), votes)


def pack_adversarial_votes(
    get_vote_plane,
    responded: jax.Array,
    lie: jax.Array,
    key: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
) -> tuple:
    """The k-draw vote-pack loop shared by every multi-target round.

    `get_vote_plane(j)` returns the bool ``[N, T]`` response plane gathered
    for draw j; `responded`/`lie` are bool ``[N, k]``.  Applies the
    adversary transform per draw and packs the k votes into the
    ``(yes_pack, consider_pack)`` uint8 bit planes consumed by
    `voterecord.register_packed_votes`.
    """
    n, k = responded.shape
    t = minority_t.shape[0]
    yes_pack = jnp.zeros((n, t), jnp.uint8)
    consider_pack = jnp.zeros((n, t), jnp.uint8)
    for j in range(cfg.k):
        vote_j = apply_plane(key, j, get_vote_plane(j), lie[:, j], cfg,
                             minority_t)
        yes_pack |= vote_j.astype(jnp.uint8) << jnp.uint8(j)
        consider_pack |= (responded[:, j].astype(jnp.uint8)
                          << jnp.uint8(j))[:, None]
    return yes_pack, consider_pack


def apply_draw_planes(
    key: jax.Array,
    votes: jax.Array,
    lie: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
) -> jax.Array:
    """Adversary transform for ALL k draws at once (the fused exchange).

    `votes` is the bool ``[N, k, T]`` gathered-response cube (draw axis 1),
    `lie` the bool ``[N, k]`` lie mask.  Bit-exact twin of k `apply_plane`
    calls: pure boolean selects for FLIP / OPPOSE_MAJORITY, and the
    EQUIVOCATE coins are drawn per draw with the identical
    ``fold_in(fold_in(key, 0x5A), draw)`` keys, so the fused engine and the
    legacy k-pass loop see the same random stream.
    """
    s = cfg.adversary_strategy
    if s is AdversaryStrategy.FLIP:
        return jnp.logical_xor(votes, lie[:, :, None])
    if s is AdversaryStrategy.EQUIVOCATE:
        n, k, t = votes.shape
        base = jax.random.fold_in(key, 0x5A)
        coins = jnp.stack(
            [jax.random.bernoulli(jax.random.fold_in(base, j), 0.5, (n, t))
             for j in range(k)], axis=1)
        return jnp.where(lie[:, :, None], coins, votes)
    return jnp.where(lie[:, :, None], minority_t[None, None, :], votes)


def apply_plane(
    key: jax.Array,
    draw: int,
    vote_j: jax.Array,
    lie_j: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
) -> jax.Array:
    """Adversary transform for one draw of a multi-target model.

    Called inside the unrolled k-loop: `vote_j` is the bool ``[N, T]``
    gathered response plane for draw `draw`, `lie_j` the bool ``[N]`` lie
    mask column, `minority_t` the precomputed bool ``[T]`` minority plane
    (pass anything, e.g. `vote_j`, for non-OPPOSE strategies).  The
    equivocation coin folds `draw` plus a constant into `key` so each draw
    lies independently and `key` may be shared with `lie_mask`.
    """
    s = cfg.adversary_strategy
    if s is AdversaryStrategy.FLIP:
        return jnp.logical_xor(vote_j, lie_j[:, None])
    if s is AdversaryStrategy.EQUIVOCATE:
        coin = jax.random.bernoulli(
            jax.random.fold_in(jax.random.fold_in(key, 0x5A), draw), 0.5,
            vote_j.shape)
        return jnp.where(lie_j[:, None], coin, vote_j)
    return jnp.where(lie_j[:, None], minority_t[None, :], vote_j)
