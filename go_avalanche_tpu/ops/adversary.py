"""Adversary vote transforms (SURVEY.md §2.4 item 5).

The reference's only adversarial hook is the commented-out random vote flip
in the example (`examples/basic-preconcensus/main.go:184-187`).  The
Avalanche paper that the reference links (`README.md:15`) analyses stronger
adversaries; this module implements the three standard strategies as pure
transforms applied to gathered peer votes, shared by every model in the
family (`models/snowball`, `models/family`, `models/avalanche`,
`models/dag`, `parallel/sharded`):

  FLIP            — lie with the opposite of the peer's true preference
                    (the reference hook, verbatim).
  EQUIVOCATE      — lie with a fresh coin per (querier, draw[, target]):
                    the same byzantine peer tells different queriers
                    different things within one round.
  OPPOSE_MAJORITY — lie with the current global *minority* color, the
                    paper's liveness adversary: it fights convergence by
                    pulling the network back toward an even split.

Every strategy triggers per (querier, draw) with `cfg.flip_probability`,
and only for byzantine peers, so `FLIP` with `flip_probability=0.35`
reproduces the reference hook exactly.

ADAPTIVE POLICIES (`cfg.adversary_policy`, PR 13).  The strategies are
state-BLIND: a lie's content is a pure per-draw transform.  arXiv
2401.02811 shows a small adversary choosing votes *as a function of
observed network state* can stall finality indefinitely, and arXiv
2409.02217 quantifies the resulting liveness/safety probabilities vs
(byzantine fraction, k, quorum).  The policy layer adds that class:

  * `policy_ctx` — ONE per-round context (`PolicyCtx`) read from the
    pre-round state planes (preference tallies, window vote counts,
    stake weights), shared by every model round; statically None with
    the policy off, so every archived hlo pin is byte-identical;
  * `apply_policy_issue` — issue-time effects on the (lie, responded)
    masks: stake_eclipse restricts lies to the top-stake honest
    queriers, withhold_near_quorum turns lying draws into SILENCE for
    near-quorum queriers;
  * `apply_policy_latency` — latency-plane effects (async engine):
    timing delays lies to the last deliverable age, withheld draws get
    the never-delivers sentinel and expire through the existing
    timeout machinery;
  * split_vote overrides the lie CONTENT inside the strategy
    transforms below: lies vote the HONEST population's minority color
    (fresh equivocation coins on an exact tie), holding the honest
    split even — the 2401.02811 stall attack.

Every context plane is a pure function of (config, state), so the
policies are vmap-clean (realized per fleet trial) and the sharded
drivers reproduce them exactly from psum'd tallies
(`parallel/sharded._policy_ctx_sharded`).  The in-graph liveness
detector that catches what these attacks cause lives in
`fleet.liveness_stalled`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from go_avalanche_tpu.config import AdversaryStrategy, AvalancheConfig

# fold_in constant deriving the split_vote tie-breaker coins from the
# round's adversary key — a stream of its own, like EQUIVOCATE's 0x5A,
# so turning the policy on never perturbs the strategy draws.
_SPLIT_FOLD = 0xB511


class PolicyCtx(NamedTuple):
    """Per-round adaptive-adversary context (`cfg.adversary_policy`).

    Built once per round by `policy_ctx` (dense) or
    `parallel/sharded._policy_ctx_sharded` (psum'd twin) from the
    PRE-round state, then threaded through the exchange/inflight
    engines exactly like `minority_t`.  Only the active policy's
    fields are populated; the rest stay None (statically absent).
    """

    split_t: Optional[jax.Array] = None
                         # split_vote: bool [T] (scalar for snowball) —
                         # the HONEST population's minority color per
                         # target, the lie content that pulls the
                         # honest tally toward an even split
    split_even: Optional[jax.Array] = None
                         # split_vote: bool [T] / scalar — exact honest
                         # tie; lies fall back to fresh equivocation
                         # coins there (a fixed color would break the
                         # tie the attack is holding)
    withhold_q: Optional[jax.Array] = None
                         # withhold_near_quorum: bool [rows] — queriers
                         # holding a live record within
                         # cfg.adversary_margin window votes of the
                         # conclusive quorum; their lying draws go
                         # silent
    eclipse_q: Optional[jax.Array] = None
                         # stake_eclipse: bool [rows] — the top-
                         # max(1, round(byzantine_fraction * N))-stake
                         # HONEST queriers lies concentrate on


def honest_split_plane(prefs: jax.Array, byzantine: jax.Array):
    """``(minority, even)`` of the HONEST preference tally.

    `prefs` is the response plane (bool ``[N]`` or ``[N, T]``),
    `byzantine` bool ``[N]``.  Unlike `minority_plane` (all rows), the
    tally quantifies over honest rows only — the split the 2401.02811
    adversary is holding is the honest one; its own rows' preferences
    are irrelevant.  Ties report `even` (the transforms equivocate
    there) rather than leaning one color.
    """
    honest = jnp.logical_not(byzantine)
    n_honest = honest.sum()
    if prefs.ndim == 1:
        yes = (prefs & honest).sum()
    else:
        yes = (prefs & honest[:, None]).sum(axis=0)
    return yes * 2 < n_honest, yes * 2 == n_honest


def near_quorum_rows(records, cfg: AvalancheConfig) -> jax.Array:
    """Bool ``[rows]`` — queriers holding any LIVE record whose window
    yes- or no-count is within `cfg.adversary_margin` votes of the
    conclusive quorum (>= quorum - margin): one more conclusive round
    could finalize them, so withholding now denies the finishing
    votes.  Finalized records are excluded (nothing left to deny).
    On a tx-sharded driver this reduces the LOCAL columns only; the
    caller psums the any() across tx shards
    (`parallel/sharded._policy_ctx_sharded`)."""
    from go_avalanche_tpu.ops import voterecord as vr
    from go_avalanche_tpu.ops.bitops import popcount8

    yes = popcount8(records.votes & records.consider)
    cons = popcount8(records.consider)
    near = (jnp.maximum(yes, cons - yes).astype(jnp.int32)
            >= jnp.int32(cfg.quorum - cfg.adversary_margin))
    near = near & jnp.logical_not(
        vr.has_finalized(records.confidence, cfg))
    return near if near.ndim == 1 else near.any(axis=1)


def eclipse_rows(latency_weight: jax.Array, byzantine: jax.Array,
                 cfg: AvalancheConfig) -> jax.Array:
    """Bool ``[N]`` — the top-stake HONEST queriers the eclipse
    concentrates on.

    The eclipse set holds the ``max(1, round(byzantine_fraction * N))``
    heaviest honest rows of the sampling-propensity plane (the stake
    fold, `stake.py`): the most-sampled responders, whose poisoned
    preferences propagate furthest through stake-weighted committees.
    Byzantine rows are excluded — under zipf the adversary itself
    holds the top stake (`av.init`), and lying to itself is wasted
    budget; the exclusion holds even when the requested set size
    exceeds the honest population (the threshold then bottoms out at
    the byzantine -inf fill, and the finite-weight mask SATURATES the
    set at "every honest querier" rather than leaking byzantine rows
    in).  Ties at the threshold weight all qualify (deterministic,
    shard-independent).  NOTE the set size reads cfg.byzantine_fraction
    at ROUND time, so run configs must keep the init-time fraction.
    """
    n = latency_weight.shape[0]
    m = min(n, max(1, int(round(cfg.byzantine_fraction * n))))
    w = jnp.where(byzantine, -jnp.inf, latency_weight.astype(jnp.float32))
    kth = jax.lax.top_k(w, m)[0][-1]
    return (w >= kth) & jnp.isfinite(w)


def policy_ctx(cfg: AvalancheConfig, records, byzantine: jax.Array,
               latency_weight: Optional[jax.Array],
               prefs: Optional[jax.Array] = None) -> Optional[PolicyCtx]:
    """The dense per-round policy context; None (statically) with the
    policy off — the round's traced program is byte-identical to the
    pre-policy one.

    `records` is the PRE-round `VoteRecordState`; `prefs` overrides the
    response plane the split tally reads (the DAG round's
    preferred-in-set plane — what responders would actually SAY; by
    default `vr.is_accepted(records.confidence)`, which XLA CSEs with
    the round's own gather).  `latency_weight` None means a uniform
    plane (snowball carries none; stake_eclipse is config-rejected
    without stake anyway).
    """
    if cfg.adversary_policy == "off":
        return None
    if cfg.adversary_policy == "split_vote":
        from go_avalanche_tpu.ops import voterecord as vr

        if prefs is None:
            prefs = vr.is_accepted(records.confidence)
        split_t, even = honest_split_plane(prefs, byzantine)
        return PolicyCtx(split_t=split_t, split_even=even)
    if cfg.adversary_policy == "withhold_near_quorum":
        return PolicyCtx(withhold_q=near_quorum_rows(records, cfg))
    if cfg.adversary_policy == "stake_eclipse":
        if latency_weight is None:
            latency_weight = jnp.ones(byzantine.shape, jnp.float32)
        return PolicyCtx(eclipse_q=eclipse_rows(latency_weight,
                                                byzantine, cfg))
    return PolicyCtx()   # timing: latency-plane only (apply_policy_latency)


def apply_policy_issue(cfg: AvalancheConfig, ctx: Optional[PolicyCtx],
                       lie: jax.Array, responded: jax.Array):
    """Issue-time policy effects on the round's ``[rows, k]`` masks;
    returns ``(lie, responded, withheld)``.

    stake_eclipse restricts the lie mask to the eclipse queriers (the
    other draws answer honestly — concentration, not amplification);
    withhold_near_quorum turns the flagged queriers' lying draws into
    SILENCE — the `responded` bit drops (sync rounds: the drop/absence
    semantics of `vote.go:56`) and the draw stops lying (it says
    nothing at all); `withheld` hands the mask to
    `apply_policy_latency`, which stamps the never-delivers sentinel
    so async rounds expire it through the timeout machinery instead.
    Pass-through (statically) when `ctx` is None or the policy has no
    issue-time effect.
    """
    if ctx is None:
        return lie, responded, None
    if cfg.adversary_policy == "stake_eclipse":
        return lie & ctx.eclipse_q[:, None], responded, None
    if cfg.adversary_policy == "withhold_near_quorum":
        withheld = lie & ctx.withhold_q[:, None]
        keep = jnp.logical_not(withheld)
        return lie & keep, responded & keep, withheld
    return lie, responded, None


def apply_policy_latency(cfg: AvalancheConfig, lat: jax.Array,
                         lie: jax.Array,
                         withheld: Optional[jax.Array]) -> jax.Array:
    """Latency-plane policy effects, applied to the round's issue-time
    draws BEFORE the fault-script pass (scheduled cuts still override
    with the sentinel — a partitioned lie is lost like any other
    query; spikes shifting a timed lie past the timeout expire it).

    timing  — lying draws land at age ``timeout_rounds() - 1``, the
              last deliverable age: the stalest possible response,
              maximum time-in-flight per lie.
    withhold — withheld draws get the never-delivers sentinel
              (``timeout_rounds()``) and expire unanswered at the
              timeout age, the host Processor's reap — silence feeds
              the existing expiry/occupancy telemetry.

    Statically absent otherwise (pins unchanged).
    """
    if cfg.adversary_policy == "timing":
        return jnp.where(lie, jnp.int32(cfg.timeout_rounds() - 1), lat)
    if withheld is not None:
        return jnp.where(withheld, jnp.int32(cfg.timeout_rounds()), lat)
    return lat


def _split_content(key: jax.Array, shape, even, split) -> jax.Array:
    """The split_vote lie content: the honest-minority color, or a
    fresh coin on an exact honest tie.  `even`/`split` broadcast
    against `shape`."""
    coin = jax.random.bernoulli(key, 0.5, shape)
    return jnp.where(even, coin, split)


def lie_mask(
    key: jax.Array,
    peers: jax.Array,
    byzantine: jax.Array,
    cfg: AvalancheConfig,
) -> jax.Array:
    """Bool ``[N, k]`` — draws on which the sampled peer lies.

    A draw lies iff the sampled peer is byzantine AND an independent
    Bernoulli(`cfg.flip_probability`) fires for this (querier, draw).
    """
    return byzantine[peers] & jax.random.bernoulli(
        key, cfg.flip_probability, peers.shape)


def minority_color(prefs: jax.Array) -> jax.Array:
    """Scalar bool — the color currently held by *fewer* nodes.

    `prefs` is the bool ``[N]`` true-preference plane.  Ties count "no" as
    the minority, so a perfectly split network keeps being pulled down.
    """
    n = prefs.shape[0]
    return prefs.sum() * 2 < n


def minority_plane(prefs: jax.Array) -> jax.Array:
    """Bool ``[T]`` — per-target minority color over a ``[N, T]`` plane."""
    n = prefs.shape[0]
    return prefs.sum(axis=0) * 2 < n


def _require_split_ctx(ctx: Optional[PolicyCtx]) -> PolicyCtx:
    if ctx is None or ctx.split_t is None:
        raise ValueError(
            "adversary_policy 'split_vote' needs the round's PolicyCtx "
            "(policy_ctx / _policy_ctx_sharded) threaded through the "
            "exchange engine — every model round builds it")
    return ctx


def apply_1d(
    key: jax.Array,
    votes: jax.Array,
    lie: jax.Array,
    cfg: AvalancheConfig,
    prefs: jax.Array,
    ctx: Optional[PolicyCtx] = None,
) -> jax.Array:
    """Adversary transform for single-decree models.

    `votes`/`lie` are bool ``[N, k]``; `prefs` is the bool ``[N]`` true
    preference plane (used only by OPPOSE_MAJORITY).  Returns the
    post-adversary ``[N, k]`` votes.  `key` may be the same key used for
    `lie_mask` — the coin folds in a constant to decorrelate.  Under
    `cfg.adversary_policy = "split_vote"` the lie content is the
    policy's instead (`ctx.split_t`/`split_even` scalars): the honest
    minority color, a fresh coin per (querier, draw) on an exact tie.
    """
    if cfg.adversary_policy == "split_vote":
        ctx = _require_split_ctx(ctx)
        content = _split_content(jax.random.fold_in(key, _SPLIT_FOLD),
                                 votes.shape, ctx.split_even, ctx.split_t)
        return jnp.where(lie, content, votes)
    s = cfg.adversary_strategy
    if s is AdversaryStrategy.FLIP:
        return jnp.logical_xor(votes, lie)
    if s is AdversaryStrategy.EQUIVOCATE:
        coin = jax.random.bernoulli(jax.random.fold_in(key, 0x5A), 0.5,
                                    votes.shape)
        return jnp.where(lie, coin, votes)
    return jnp.where(lie, minority_color(prefs), votes)


def pack_adversarial_votes(
    get_vote_plane,
    responded: jax.Array,
    lie: jax.Array,
    key: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
    ctx: Optional[PolicyCtx] = None,
) -> tuple:
    """The k-draw vote-pack loop shared by every multi-target round.

    `get_vote_plane(j)` returns the bool ``[N, T]`` response plane gathered
    for draw j; `responded`/`lie` are bool ``[N, k]``.  Applies the
    adversary transform per draw and packs the k votes into the
    ``(yes_pack, consider_pack)`` uint8 bit planes consumed by
    `voterecord.register_packed_votes`.
    """
    n, k = responded.shape
    t = minority_t.shape[0]
    yes_pack = jnp.zeros((n, t), jnp.uint8)
    consider_pack = jnp.zeros((n, t), jnp.uint8)
    for j in range(cfg.k):
        vote_j = apply_plane(key, j, get_vote_plane(j), lie[:, j], cfg,
                             minority_t, ctx)
        yes_pack |= vote_j.astype(jnp.uint8) << jnp.uint8(j)
        consider_pack |= (responded[:, j].astype(jnp.uint8)
                          << jnp.uint8(j))[:, None]
    return yes_pack, consider_pack


def apply_draw_planes(
    key: jax.Array,
    votes: jax.Array,
    lie: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
    ctx: Optional[PolicyCtx] = None,
) -> jax.Array:
    """Adversary transform for ALL k draws at once (the fused exchange).

    `votes` is the bool ``[N, k, T]`` gathered-response cube (draw axis 1),
    `lie` the bool ``[N, k]`` lie mask.  Bit-exact twin of k `apply_plane`
    calls: pure boolean selects for FLIP / OPPOSE_MAJORITY, and the
    EQUIVOCATE coins are drawn per draw with the identical
    ``fold_in(fold_in(key, 0x5A), draw)`` keys, so the fused engine and the
    legacy k-pass loop see the same random stream.  split_vote
    (`cfg.adversary_policy`) follows the same per-draw key discipline
    with its own `_SPLIT_FOLD` stream.
    """
    if cfg.adversary_policy == "split_vote":
        ctx = _require_split_ctx(ctx)
        n, k, t = votes.shape
        base = jax.random.fold_in(key, _SPLIT_FOLD)
        content = jnp.stack(
            [_split_content(jax.random.fold_in(base, j), (n, t),
                            ctx.split_even[None, :], ctx.split_t[None, :])
             for j in range(k)], axis=1)
        return jnp.where(lie[:, :, None], content, votes)
    s = cfg.adversary_strategy
    if s is AdversaryStrategy.FLIP:
        return jnp.logical_xor(votes, lie[:, :, None])
    if s is AdversaryStrategy.EQUIVOCATE:
        n, k, t = votes.shape
        base = jax.random.fold_in(key, 0x5A)
        coins = jnp.stack(
            [jax.random.bernoulli(jax.random.fold_in(base, j), 0.5, (n, t))
             for j in range(k)], axis=1)
        return jnp.where(lie[:, :, None], coins, votes)
    return jnp.where(lie[:, :, None], minority_t[None, None, :], votes)


def apply_plane(
    key: jax.Array,
    draw: int,
    vote_j: jax.Array,
    lie_j: jax.Array,
    cfg: AvalancheConfig,
    minority_t: jax.Array,
    ctx: Optional[PolicyCtx] = None,
) -> jax.Array:
    """Adversary transform for one draw of a multi-target model.

    Called inside the unrolled k-loop: `vote_j` is the bool ``[N, T]``
    gathered response plane for draw `draw`, `lie_j` the bool ``[N]`` lie
    mask column, `minority_t` the precomputed bool ``[T]`` minority plane
    (pass anything, e.g. `vote_j`, for non-OPPOSE strategies).  The
    equivocation coin folds `draw` plus a constant into `key` so each draw
    lies independently and `key` may be shared with `lie_mask`; the
    split_vote tie coins (`cfg.adversary_policy`) do the same on their
    own `_SPLIT_FOLD` stream — bit-exact with `apply_draw_planes`.
    """
    if cfg.adversary_policy == "split_vote":
        ctx = _require_split_ctx(ctx)
        content = _split_content(
            jax.random.fold_in(jax.random.fold_in(key, _SPLIT_FOLD), draw),
            vote_j.shape, ctx.split_even[None, :], ctx.split_t[None, :])
        return jnp.where(lie_j[:, None], content, vote_j)
    s = cfg.adversary_strategy
    if s is AdversaryStrategy.FLIP:
        return jnp.logical_xor(vote_j, lie_j[:, None])
    if s is AdversaryStrategy.EQUIVOCATE:
        coin = jax.random.bernoulli(
            jax.random.fold_in(jax.random.fold_in(key, 0x5A), draw), 0.5,
            vote_j.shape)
        return jnp.where(lie_j[:, None], coin, vote_j)
    return jnp.where(lie_j[:, None], minority_t[None, :], vote_j)
