"""Peer sampling — the topology module (SURVEY.md section 2.4 item 2).

Replaces the reference's placeholder peer selection (always the lowest node
id, `processor.go:173-182`) and the example's deterministic round-robin
(`examples/basic-preconcensus/main.go:111`) with the protocol-correct random
k-peer subsample, entirely on device: every node draws k peers per round from
a keyed PRNG with no host round-trips (SURVEY.md section 7 hard part (a)).

Latency weighting uses inverse-CDF sampling over a cumulative weight vector —
O(N·k·log N) and mesh-friendly — instead of materializing per-node categorical
logits (which would be O(N^2) at 100k nodes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_peers_uniform(
    key: jax.Array,
    n_nodes: int,
    k: int,
    exclude_self: bool = True,
    n_local: int | None = None,
    id_offset: int | jax.Array = 0,
) -> jax.Array:
    """Uniform k-peer sample per node; int32 ``[n_local or n_nodes, k]`` of
    *global* peer ids in [0, n_nodes).

    With `exclude_self`, node i never draws i: each draw is taken from
    [0, n_nodes-1) and values >= i are shifted up by one — an exact uniform
    distribution over the other n-1 nodes, with replacement.

    `n_local`/`id_offset` support sharded use: a shard owning global rows
    [id_offset, id_offset + n_local) samples peers for just its own nodes
    (ids remain global, so gathers cross shards).
    """
    if exclude_self and n_nodes < 2:
        raise ValueError("exclude_self requires at least 2 nodes")
    rows = n_nodes if n_local is None else n_local
    self_ids = (jnp.arange(rows, dtype=jnp.int32)
                + jnp.asarray(id_offset, jnp.int32))[:, None]
    if exclude_self:
        draws = jax.random.randint(key, (rows, k), 0, n_nodes - 1,
                                   dtype=jnp.int32)
        return draws + (draws >= self_ids).astype(jnp.int32)
    return jax.random.randint(key, (rows, k), 0, n_nodes, dtype=jnp.int32)


def sample_peers_weighted(
    key: jax.Array,
    weights: jax.Array,
    n_rows: int,
    k: int,
) -> jax.Array:
    """Weighted k-peer sample; int32 ``[n_rows, k]`` of global peer ids, with
    replacement, drawn proportionally to `weights`.

    `weights` is a non-negative ``[n_peers]`` vector (e.g. inverse expected
    latency, times an aliveness mask so churned-out peers are never drawn).
    Self-draws are NOT excluded here — per-row exclusion would need an O(N^2)
    weight matrix; callers mask self-draws to neutral votes instead (see
    `models/avalanche.round_step`, weighted branch).
    """
    weights = jnp.asarray(weights, jnp.float32)
    cdf = jnp.cumsum(weights)
    total = cdf[-1]
    u = jax.random.uniform(key, (n_rows, k), jnp.float32) * total
    idx = jnp.searchsorted(cdf, u, side="right")
    return jnp.clip(idx, 0, weights.shape[0] - 1).astype(jnp.int32)


def self_sample_mask(peers: jax.Array,
                     id_offset: int | jax.Array = 0) -> jax.Array:
    """Bool ``[n, k]``: True where a draw landed on the sampling node itself.

    Row i holds the node with global id `id_offset + i` (sharded use).
    """
    n = peers.shape[0]
    self_ids = (jnp.arange(n, dtype=peers.dtype)
                + jnp.asarray(id_offset, peers.dtype))[:, None]
    return peers == self_ids
