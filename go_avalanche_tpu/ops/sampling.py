"""Peer sampling — the topology module (SURVEY.md section 2.4 item 2).

Replaces the reference's placeholder peer selection (always the lowest node
id, `processor.go:173-182`) and the example's deterministic round-robin
(`examples/basic-preconcensus/main.go:111`) with the protocol-correct random
k-peer subsample, entirely on device: every node draws k peers per round from
a keyed PRNG with no host round-trips (SURVEY.md section 7 hard part (a)).

Latency weighting uses inverse-CDF sampling over a cumulative weight vector —
O(N·k·log N) and mesh-friendly — instead of materializing per-node categorical
logits (which would be O(N^2) at 100k nodes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_peers_uniform(
    key: jax.Array,
    n_nodes: int,
    k: int,
    exclude_self: bool = True,
    n_local: int | None = None,
    id_offset: int | jax.Array = 0,
    with_replacement: bool = True,
) -> jax.Array:
    """Uniform k-peer sample per node; int32 ``[n_local or n_nodes, k]`` of
    *global* peer ids in [0, n_nodes).

    With `exclude_self`, node i never draws i: each draw is taken from
    [0, n_nodes-1) and values >= i are shifted up by one — an exact uniform
    distribution over the other n-1 nodes, with replacement.

    With ``with_replacement=False`` the k draws per row are *distinct* —
    the protocol's real k-peer sample (the placeholder this module replaces,
    `processor.go:173-182`, stands in for "sample k random peers", and the
    Avalanche paper's query is k distinct peers).  See
    `sample_peers_distinct`.

    `n_local`/`id_offset` support sharded use: a shard owning global rows
    [id_offset, id_offset + n_local) samples peers for just its own nodes
    (ids remain global, so gathers cross shards).
    """
    if not with_replacement:
        return sample_peers_distinct(key, n_nodes, k, exclude_self,
                                     n_local, id_offset)
    if exclude_self and n_nodes < 2:
        raise ValueError("exclude_self requires at least 2 nodes")
    rows = n_nodes if n_local is None else n_local
    self_ids = (jnp.arange(rows, dtype=jnp.int32)
                + jnp.asarray(id_offset, jnp.int32))[:, None]
    if exclude_self:
        draws = jax.random.randint(key, (rows, k), 0, n_nodes - 1,
                                   dtype=jnp.int32)
        return draws + (draws >= self_ids).astype(jnp.int32)
    return jax.random.randint(key, (rows, k), 0, n_nodes, dtype=jnp.int32)


def sample_peers_distinct(
    key: jax.Array,
    n_nodes: int,
    k: int,
    exclude_self: bool = True,
    n_local: int | None = None,
    id_offset: int | jax.Array = 0,
) -> jax.Array:
    """Uniform k-DISTINCT-peer sample per node; int32 ``[rows, k]``.

    Iterated draw-and-shift, the without-replacement extension of the
    `exclude_self` trick: draw j takes a uniform rank in the remaining pool
    ``n - excluded - j`` and shifts it past every already-taken id in
    ascending order, which maps the rank to the rank-th smallest untaken id
    exactly.  k is small (protocol default 8), so the O(k^2) shift chain and
    the per-draw sort of the k+1 taken-id buffer are noise next to the vote
    planes; everything is [rows, k]-shaped — no O(N^2) anywhere, no host
    round-trips, exact uniformity over k-subsets (each draw is uniform over
    the remaining pool, so any ordered k-tuple has probability
    1/(p * (p-1) * ... * (p-k+1)) with p the pool size).
    """
    excl = 1 if exclude_self else 0
    if n_nodes - excl < k:
        raise ValueError(
            f"cannot draw {k} distinct peers from {n_nodes} nodes"
            + (" excluding self" if exclude_self else ""))
    rows = n_nodes if n_local is None else n_local
    self_ids = (jnp.arange(rows, dtype=jnp.int32)
                + jnp.asarray(id_offset, jnp.int32))
    sentinel = jnp.int32(n_nodes)  # never reached by a shifted candidate
    taken = jnp.full((rows, k + 1), sentinel, jnp.int32)
    if exclude_self:
        taken = taken.at[:, 0].set(self_ids)
    keys = jax.random.split(key, k)
    out = []
    for j in range(k):
        pool = n_nodes - excl - j
        cand = jax.random.randint(keys[j], (rows,), 0, pool, dtype=jnp.int32)
        srt = jnp.sort(taken, axis=1)
        for i in range(j + excl):  # only the first j+excl entries are real
            cand = cand + (cand >= srt[:, i]).astype(jnp.int32)
        out.append(cand)
        taken = taken.at[:, j + excl].set(cand)
    return jnp.stack(out, axis=1)


def sample_peers_weighted(
    key: jax.Array,
    weights: jax.Array,
    n_rows: int,
    k: int,
) -> jax.Array:
    """Weighted k-peer sample; int32 ``[n_rows, k]`` of global peer ids, with
    replacement, drawn proportionally to `weights`.

    `weights` is a non-negative ``[n_peers]`` vector (e.g. inverse expected
    latency, times an aliveness mask so churned-out peers are never drawn).
    Self-draws are NOT excluded here — per-row exclusion would need an O(N^2)
    weight matrix; callers mask self-draws to neutral votes instead (see
    `models/avalanche.round_step`, weighted branch).
    """
    weights = jnp.asarray(weights, jnp.float32)
    cdf = jnp.cumsum(weights)
    total = cdf[-1]
    u = jax.random.uniform(key, (n_rows, k), jnp.float32) * total
    idx = jnp.searchsorted(cdf, u, side="right")
    return jnp.clip(idx, 0, weights.shape[0] - 1).astype(jnp.int32)


def sample_peers_hierarchical(
    key: jax.Array,
    weights: jax.Array,
    n_rows: int,
    k: int,
    n_clusters: int,
) -> jax.Array:
    """Two-level stake-weighted k-peer sample; int32 ``[n_rows, k]``,
    with replacement — BIT-IDENTICAL to `sample_peers_weighted` on the
    same key (tests/test_stake.py pins the parity across
    ``n_clusters ∈ {1, 4, 7}`` including C ∤ N).

    The flat inverse-CDF draw binary-searches the full ``[N]`` CDF per
    draw; at million-node registries the committee structure makes that
    decomposable: draw a CLUSTER from the ``[C]`` stake-mass boundary
    values, then the peer WITHIN that cluster's contiguous block —
    log C + log(N/C) probes instead of log N over the whole vector,
    and the cluster level is exactly the stake-mass-per-committee
    table deployments publish.  Clusters are `cluster_of`'s contiguous
    blocks (THE one partition spelling — committees, outages, and RTT
    all agree on it).

    Exactness: both levels compare the SAME flat-CDF floats the oracle
    compares — the cluster search uses the CDF's value at each block's
    last element, the within-block search is a lower-bound binary
    search over the flat CDF restricted to the block — so every
    comparison (and therefore every drawn id) matches
    `searchsorted(cdf, u, side="right")` bit for bit; no re-summed
    per-cluster CDF whose float rounding could drift.
    """
    weights = jnp.asarray(weights, jnp.float32)
    n = weights.shape[0]
    if not (1 <= n_clusters <= n):
        raise ValueError(f"n_clusters={n_clusters} must be in [1, {n}]")
    cdf = jnp.cumsum(weights)
    total = cdf[-1]
    u = jax.random.uniform(key, (n_rows, k), jnp.float32) * total

    # Static block geometry from cluster_of's own partition: block c is
    # [ceil(c*N/C), ceil((c+1)*N/C)).
    starts = [-(-c * n // n_clusters) for c in range(n_clusters)]
    ends = starts[1:] + [n]
    starts_a = jnp.asarray(starts, jnp.int32)
    ends_a = jnp.asarray(ends, jnp.int32)
    bounds = cdf[ends_a - 1]                     # [C] cluster mass marks

    c = jnp.clip(jnp.searchsorted(bounds, u, side="right"),
                 0, n_clusters - 1)
    lo = starts_a[c]
    hi = ends_a[c]
    # Lower-bound binary search over cdf[lo:hi): smallest index whose
    # CDF value exceeds u — identical comparisons to the flat
    # side="right" search restricted to the chosen block.
    max_block = max(e - s for s, e in zip(starts, ends))
    for _ in range(max(1, max_block.bit_length())):
        open_ = lo < hi
        mid = (lo + hi) // 2
        go_right = cdf[jnp.clip(mid, 0, n - 1)] <= u
        lo = jnp.where(open_ & go_right, mid + 1, lo)
        hi = jnp.where(open_ & jnp.logical_not(go_right), mid, hi)
    return jnp.clip(lo, 0, n - 1).astype(jnp.int32)


def cluster_of(ids: jax.Array, n_clusters: int,
               n_nodes: int) -> jax.Array:
    """Cluster of each global node id: ``i * C // N`` — contiguous
    blocks, derived, never stored.  THE one spelling of the clustered
    topology's partition, shared by the clustered sampler below, the
    fault-script engine's regional-outage cuts and the cluster-pair RTT
    latency draw (`ops/inflight.py`), and the watchdog's host-side
    re-derivation (`obs/watchdog.check_ring_cut`) — a second spelling
    anywhere would let "the cluster the sampler draws from" and "the
    cluster the outage severs" silently disagree."""
    return ids * jnp.int32(n_clusters) // jnp.int32(n_nodes)


def sample_peers_clustered(
    key: jax.Array,
    weights: jax.Array,
    n_rows: int,
    k: int,
    n_clusters: int,
    locality: float,
    id_offset: int | jax.Array = 0,
) -> jax.Array:
    """Clustered-topology k-peer sample; int32 ``[n_rows, k]`` global ids.

    Nodes partition into `n_clusters` contiguous-block clusters (cluster of
    global id i = ``i * C // N`` — derived, never stored, so no state plane
    is added).  A draw lands in the drawing node's own cluster with
    probability ``locality`` (for equal-size clusters and uniform base
    weights) and spreads the rest evenly over the other clusters; within a
    cluster, draws follow the base `weights` propensities (latency x
    aliveness).  This is the two-level geographic-locality model the
    DAG-simulator literature uses, kept TPU-shaped: per-source-CLUSTER
    weight rows ``[C, N]`` instead of per-source-node O(N^2), one CDF per
    cluster, and a static C-loop of searchsorted calls.

    With replacement; callers turn self-draws into abstentions via
    `self_sample_mask` (as in the weighted mode).
    """
    weights = jnp.asarray(weights, jnp.float32)
    n_nodes = weights.shape[0]
    c_ids = jnp.arange(n_clusters, dtype=jnp.int32)
    cluster_of_all = cluster_of(jnp.arange(n_nodes, dtype=jnp.int32),
                                n_clusters, n_nodes)            # [N]
    onehot = cluster_of_all[None, :] == c_ids[:, None]          # [C, N]
    spread = (1.0 - locality) / max(n_clusters - 1, 1)
    w_cn = jnp.where(onehot, locality, spread) * weights[None, :]
    cdf = jnp.cumsum(w_cn, axis=1)                              # [C, N]
    total = cdf[:, -1]                                          # [C]

    rows_cluster = cluster_of(jnp.arange(n_rows, dtype=jnp.int32)
                              + jnp.asarray(id_offset, jnp.int32),
                              n_clusters, n_nodes)              # [rows]
    u = jax.random.uniform(key, (n_rows, k), jnp.float32) \
        * total[rows_cluster][:, None]
    peers = jnp.zeros((n_rows, k), jnp.int32)
    for c in range(n_clusters):   # static, C is small (topology knob)
        idx_c = jnp.clip(jnp.searchsorted(cdf[c], u, side="right"),
                         0, n_nodes - 1).astype(jnp.int32)
        peers = jnp.where((rows_cluster == c)[:, None], idx_c, peers)
    return peers


def draw_peers(
    key: jax.Array,
    cfg,
    latency_weight: jax.Array,
    alive: jax.Array,
    n_nodes: int,
    n_local: int | None = None,
    id_offset: int | jax.Array = 0,
) -> tuple:
    """The per-round peer draw shared by every multi-target model.

    Dispatches on the config: stake-weighted committee draws
    (`cfg.stake_mode != "off"` — the stake vector is folded into
    `latency_weight` at init, flat CDF for one cluster and the
    two-level hierarchical engine for a clustered topology, identical
    bits either way), clustered topology (`n_clusters > 1`),
    latency-weighted, or uniform (with/without replacement,
    self-excluded).  Returns ``(peers [rows, k], self_draw)`` where
    `self_draw` is a bool mask in the weighted/clustered/stake families
    (per-row exclusion there would be O(N^2); callers abstain those
    draws) and None in the uniform family (exclusion is exact).

    Stake draws are SOURCE-INDEPENDENT (a committee draw, not a
    locality model): with `stake_mode` on, `cluster_locality` is unread
    and `n_clusters` selects only the two-level sampling engine.
    """
    rows = n_nodes if n_local is None else n_local
    if cfg.stake_mode != "off":
        w = latency_weight * alive.astype(jnp.float32)
        if cfg.n_clusters > 1:
            peers = sample_peers_hierarchical(key, w, rows, cfg.k,
                                              cfg.n_clusters)
        else:
            peers = sample_peers_weighted(key, w, rows, cfg.k)
        return peers, self_sample_mask(peers, id_offset=id_offset)
    if cfg.n_clusters > 1:
        w = latency_weight * alive.astype(jnp.float32)
        peers = sample_peers_clustered(key, w, rows, cfg.k, cfg.n_clusters,
                                       cfg.cluster_locality,
                                       id_offset=id_offset)
        return peers, self_sample_mask(peers, id_offset=id_offset)
    if cfg.weighted_sampling:
        w = latency_weight * alive.astype(jnp.float32)
        peers = sample_peers_weighted(key, w, rows, cfg.k)
        return peers, self_sample_mask(peers, id_offset=id_offset)
    peers = sample_peers_uniform(key, n_nodes, cfg.k, cfg.exclude_self,
                                 n_local=n_local, id_offset=id_offset,
                                 with_replacement=cfg.sample_with_replacement)
    return peers, None


def self_sample_mask(peers: jax.Array,
                     id_offset: int | jax.Array = 0) -> jax.Array:
    """Bool ``[n, k]``: True where a draw landed on the sampling node itself.

    Row i holds the node with global id `id_offset + i` (sharded use).
    """
    n = peers.shape[0]
    self_ids = (jnp.arange(n, dtype=peers.dtype)
                + jnp.asarray(id_offset, peers.dtype))[:, None]
    return peers == self_ids
