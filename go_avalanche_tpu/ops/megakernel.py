"""The whole-round megakernel: exchange -> ingest -> confidence in ONE
Pallas program.

The r05 roofline (PERF_NOTES.md) attributes the remaining flagship gap
to memory, not compute: the phased round is ~6 fused-op islands that
each round-trip the [N, k] vote packs and [N, T] record planes through
HBM between phases.  This module fuses the hot sync round into one
kernel so those intermediates never exist:

  * the fused-exchange gather (`ops/exchange.fused_vote_packs`) becomes
    an IN-KERNEL row gather of the bit-packed preference plane — the
    whole [N, T/32] plane is VMEM-resident per column block, so all k
    draws read it without HBM traffic and the [N, k] vote-pack planes
    are never materialised;
  * the SWAR packed-u32 window ingest and the branch-free closed-form
    confidence fold run on the SAME VMEM-resident record tiles, via the
    seams shared with `ops/pallas_vote` (`swar_window_fold`,
    `swar_confidence_fold`) — the two engines cannot drift;
  * gossip admission stays OUTSIDE the kernel, unchanged: it runs
    before the gather in `models/avalanche.round_step` (and the
    flagship lane runs gossip off), so there is nothing between it and
    the fused program to round-trip.

Layout.  Preferences arrive BIT-packed: `pack_u8_lanes(pack_bool_plane
(prefs))` puts tx column c at bit ``c % 32`` of u32 word ``c // 32``
(the layout algebra of `ops/swar.py` x `ops/bitops.py`), so one
[N, T/32] u32 plane carries every peer's whole preference row at 1
bit/column.  The record planes ride the SWAR u32 layout (4 tx columns
per word); expanding a gathered bit word to SWAR lane-LSB words is a
static nibble spread (`_nibble_expand`), pure element-wise i32.

Adversary coverage matches `config._validate_round_engine`: FLIP is an
in-kernel xor of the lie bit, OPPOSE_MAJORITY an in-kernel select of
the (VMEM-resident) minority row.  EQUIVOCATE and the adaptive
policies draw per-draw host-keyed coin streams that cannot be
reproduced in-kernel without materialising the [N, k, T] planes this
kernel exists to remove — both are rejected at config construction.

Interpreter-mode parity against the phased round is pinned bit-for-bit
by tests/test_megakernel.py (the same protocol as the SWAR ingest
kernel: the body is Mosaic-shaped — element-wise i32 on
identically-shaped tiles plus one row gather — but the hardware
verdict, including Mosaic legalization of the traced-index gather, is
a ROADMAP hardware-window item; this container has no TPU).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from go_avalanche_tpu.config import (AdversaryStrategy, AvalancheConfig,
                                     DEFAULT_CONFIG)
from go_avalanche_tpu.ops import pallas_vote, swar
from go_avalanche_tpu.ops import voterecord as vr
from go_avalanche_tpu.ops.bitops import pack_bool_plane

# Word-shaped like DEFAULT_BLOCK_SWAR: a (64, 128)-word record tile is a
# (64, 512)-column tile; its preference slice is 128 // 8 = 16 bit words.
DEFAULT_BLOCK_MEGA = (64, 128)

_LSB = 0x01010101


def _divisor(dim: int, cap: int, multiple: int = 1) -> Optional[int]:
    """Largest block edge <= cap that divides `dim` and is a multiple of
    `multiple` (static Python — grid shapes are compile-time)."""
    for d in range(min(cap, dim), 0, -1):
        if dim % d == 0 and d % multiple == 0:
            return d
    return None


def _nibble_expand(g: jax.Array) -> jax.Array:
    """Bit-packed pref words ``[rows, w32]`` i32 -> SWAR lane-LSB words
    ``[rows, w32 * 8]``: SWAR word w4 covers tx columns ``4*w4 ..
    4*w4+3`` = bits ``4*(w4 % 8) ..`` of bit word ``w4 // 8``, so each
    bit word spreads into 8 nibbles, one bit per byte lane.  Pure
    element-wise i32 after a static-repeat broadcast; `& 0xF` discards
    the arithmetic shift's sign extension."""
    rep = jnp.repeat(g, 8, axis=1)
    col = lax.broadcasted_iota(jnp.int32, rep.shape, 1)
    nib = (rep >> ((col & 7) * 4)) & 0xF
    return ((nib & 1)
            | (((nib >> 1) & 1) << 8)
            | (((nib >> 2) & 1) << 16)
            | (((nib >> 3) & 1) << 24))


def _mega_kernel(votes_ref, consider_ref, conf_refs, prefs_ref, peers_ref,
                 resp_ref, lie_ref, minority_ref, mask_ref, votes_o,
                 consider_o, conf_os, changed_o, *, k: int,
                 cfg: AvalancheConfig) -> None:
    """One [bn, bt4] record tile's whole round: gather each draw's
    preference bits from the VMEM-resident [N, bw32] plane slice, apply
    the static adversary transform, and feed the shared SWAR window +
    confidence seams.  The record tile stays resident across all k
    draws — the grid/block contract of the module docstring."""
    orig_votes = votes_ref[:].astype(jnp.int32)
    orig_consider = consider_ref[:].astype(jnp.int32)
    votes, consider = orig_votes, orig_consider
    prefs_bits = prefs_ref[:].astype(jnp.int32)    # [N, bw32], all rows
    peers = peers_ref[:]                           # [bn, k] i32
    resp = resp_ref[:]                             # [bn, k] i32 {0, 1}
    lie = lie_ref[:]                               # [bn, k] i32 {0, 1}

    attack = cfg.byzantine_fraction > 0.0
    oppose = (attack and cfg.adversary_strategy
              is AdversaryStrategy.OPPOSE_MAJORITY)
    flip = attack and cfg.adversary_strategy is AdversaryStrategy.FLIP
    minority = (_nibble_expand(minority_ref[:].astype(jnp.int32))
                if oppose else None)               # [1, bt4] lane-LSB

    def draw_bits(j):
        gathered = prefs_bits[peers[:, j]]         # [bn, bw32] row gather
        raw = _nibble_expand(gathered)             # [bn, bt4] lane-LSB
        lie_j = lie[:, j:j + 1]
        if oppose:
            sel = lie_j * jnp.int32(-1)            # all-ones where lying
            raw = (raw & ~sel) | (minority & sel)
        elif flip:
            raw = raw ^ (lie_j * _LSB)
        return raw, resp[:, j:j + 1] * _LSB

    votes, consider, out_yes, out_concl = pallas_vote.swar_window_fold(
        votes, consider, draw_bits, k=k, cfg=cfg)

    # Masked select IN-kernel (unlike the SWAR ingest wrapper's outside
    # `where`): the update mask is already a kernel input for the
    # confidence fold, so restoring unpolled records here saves the
    # wrapper two whole-plane HBM round-trips.  keep = 0xFF per polled
    # byte lane (the mask words carry 0/1 per lane).
    keep = mask_ref[:].astype(jnp.int32) * 0xFF
    votes_o[:] = ((votes & keep) | (orig_votes & ~keep)).astype(jnp.uint32)
    consider_o[:] = ((consider & keep)
                     | (orig_consider & ~keep)).astype(jnp.uint32)
    pallas_vote.swar_confidence_fold(out_yes, out_concl, conf_refs,
                                     mask_ref, conf_os, changed_o, cfg=cfg)


def fused_round(
    records: vr.VoteRecordState,
    packed_prefs: jax.Array,
    peers: jax.Array,
    responded: jax.Array,
    lie: jax.Array,
    minority_t: jax.Array,
    polled: jax.Array,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    block: Tuple[int, int] = DEFAULT_BLOCK_MEGA,
    interpret: Optional[bool] = None,
) -> Tuple[vr.VoteRecordState, jax.Array]:
    """The `cfg.round_engine = "megakernel"` dispatch seam: one Pallas
    program for gather -> SWAR ingest -> closed-form confidence.

    Inputs are the phased round's own intermediates — `packed_prefs`
    the bit-packed ``[N, ceil(T/8)]`` preference plane, `peers` int32
    ``[N, k]``, `responded`/`lie` bool ``[N, k]``, `minority_t` bool
    ``[T]``, `polled` the bool update mask — so
    `models/avalanche.round_step` swaps engines without re-deriving
    anything.  Returns ``(new_records, changed)`` bit-identical to
    `exchange.gather_vote_packs` + `voterecord.
    register_packed_votes_engine` on every supported config (pinned by
    tests/test_megakernel.py).

    Shape contract: ``t % 32 == 0`` (whole bit words — the SWAR lane
    split needs t % 4 anyway) and `n` divisible by some block height;
    the column block is the largest divisor of ``t/4`` within `block`
    that keeps whole bit words (a multiple of 8), so odd tilings like
    t = 1184 run with a narrow boundary block rather than failing.
    `interpret` defaults to True off-TPU (the SWAR-kernel protocol).
    """
    n, t = records.votes.shape
    if not (0 < cfg.k <= 8):
        raise ValueError("megakernel packs per-draw outcomes into byte "
                         "lanes: k must be in (0, 8]")
    if t % 32:
        raise ValueError(f"txs axis ({t}) must divide by 32 (whole "
                         f"bit-packed preference words)")
    t4 = t // 4
    bn = _divisor(n, min(block[0], n))
    bt4 = _divisor(t4, min(block[1], t4), multiple=8)
    if bn is None or bt4 is None:
        raise ValueError(f"word shape {(n, t4)} does not tile under "
                         f"{block} (column blocks must keep whole bit "
                         f"words)")
    bw32 = bt4 // 8
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    votes_w = swar.pack_u8_lanes(records.votes)
    cons_w = swar.pack_u8_lanes(records.consider)
    confs = [records.confidence[:, lane::4] for lane in range(4)]
    prefs_bits = swar.pack_u8_lanes(packed_prefs)          # [N, T/32] u32
    minority_bits = swar.pack_u8_lanes(
        pack_bool_plane(minority_t[None, :]))              # [1, T/32] u32
    mask_u8 = polled.astype(jnp.uint8)
    mask_w = swar.pack_u8_lanes(mask_u8)

    k = cfg.k
    rec_spec = pl.BlockSpec((bn, bt4), lambda i, j: (i, j),
                            memory_space=pltpu.VMEM)
    # ALL N preference rows resident per column block: peer ids are
    # arbitrary rows, so the gather must see the whole node axis.  At
    # the 16384^2 flagship that is 16384 * 16 words * 4 B = 1 MB of
    # VMEM — the 8x bit packing is what makes residency affordable.
    prefs_spec = pl.BlockSpec((n, bw32), lambda i, j: (0, j),
                              memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((bn, k), lambda i, j: (i, 0),
                            memory_space=pltpu.VMEM)
    minority_spec = pl.BlockSpec((1, bw32), lambda i, j: (0, j),
                                 memory_space=pltpu.VMEM)
    grid = (n // bn, t4 // bt4)

    def kernel(votes_ref, consider_ref, c0, c1, c2, c3, prefs_ref,
               peers_ref, resp_ref, lie_ref, minority_ref, mask_ref,
               votes_o, consider_o, o0, o1, o2, o3, changed_o):
        _mega_kernel(votes_ref, consider_ref, (c0, c1, c2, c3), prefs_ref,
                     peers_ref, resp_ref, lie_ref, minority_ref, mask_ref,
                     votes_o, consider_o, (o0, o1, o2, o3), changed_o,
                     k=k, cfg=cfg)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[rec_spec] * 6 + [prefs_spec, row_spec, row_spec,
                                   row_spec, minority_spec, rec_spec],
        out_specs=[rec_spec] * 7,
        out_shape=[
            jax.ShapeDtypeStruct((n, t4), jnp.uint32),
            jax.ShapeDtypeStruct((n, t4), jnp.uint32),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint16),
            jax.ShapeDtypeStruct((n, t4), jnp.uint32),
        ],
        interpret=interpret,
    )(votes_w, cons_w, *confs, prefs_bits,
      peers.astype(jnp.int32), responded.astype(jnp.int32),
      lie.astype(jnp.int32), minority_bits, mask_w)
    new_votes_w, new_cons_w, o0, o1, o2, o3, changed_w = out

    new_votes = swar.unpack_u8_lanes(new_votes_w, t)
    new_consider = swar.unpack_u8_lanes(new_cons_w, t)
    confidence = jnp.stack([o0, o1, o2, o3], axis=-1).reshape(n, t)
    # All three planes come back fully masked: the kernel restores
    # unpolled votes/consider lanes itself, so no host-side `where`
    # (and no extra whole-plane HBM round-trip) is needed.
    changed = swar.expand_lane_mask(changed_w, t)
    return (vr.VoteRecordState(new_votes, new_consider, confidence),
            changed)
