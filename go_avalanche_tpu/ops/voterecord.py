"""The vote-record kernel: Snowball confidence tracking, vectorized.

This is layer L0 of the reference (SURVEY.md sections 1, 2.2): the per-target
state machine in `vote.go:24-98`, re-expressed as a branch-free element-wise
update over arrays of any shape — in the simulator, shape ``[nodes, txs]``.
Everything is <=16-bit integer bit-twiddling: shifts, ANDs, SWAR popcounts
(see `ops/bitops.py` for why not `lax.population_count`), and three-way
`where` selects, which XLA fuses into a single VPU pass (there is no
gather/scatter inside the kernel).

State encoding — identical to the reference (`vote.go:25-29, 38-45`):
  votes      : uint8   sliding window of the last 8 votes, bit0 = newest;
               bit set = that vote was a yes            (`vote.go:55`)
  consider   : uint8   sliding window of non-neutral-ness; bit set = that
               vote was NOT an abstention               (`vote.go:56`)
  confidence : uint16  bit 0 = current preference (accepted?); bits 1..15 =
               confidence counter, i.e. isAccepted = confidence & 1
               (`vote.go:38-40`), getConfidence = confidence >> 1
               (`vote.go:43-45`), and "+= 2" bumps the counter by one
               (`vote.go:67`).

Transition, per incoming vote error `err` (`vote.go:54-75`):
  1. shift a yes bit into `votes`, a non-neutral bit into `consider`;
  2. conclusive-yes  iff popcount(votes & consider)  > quorum-1  (>6);
     conclusive-no   iff popcount(~votes & consider) > quorum-1
     (the reference writes ~votes as (-votes-1), `vote.go:61`);
  3. inconclusive -> state unchanged, `changed` = False;
  4. conclusive & agrees with current preference -> counter += 1; `changed`
     is True only at the exact moment the counter hits finalization_score
     (`vote.go:68`: == not >=);
  5. conclusive & disagrees -> preference flips, counter resets to 0
     (`vote.go:72-74`); `changed` = True.

Vote error convention (signed int): 0 = yes, positive = no, negative = neutral
(`vote.go:5`, `vote.go:56`: the uint32 sign-bit test).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from go_avalanche_tpu.config import AvalancheConfig, DEFAULT_CONFIG
from go_avalanche_tpu.ops.bitops import popcount8


class VoteRecordState(NamedTuple):
    """SoA vote-record state; each leaf has the same (arbitrary) shape."""

    votes: jax.Array       # uint8
    consider: jax.Array    # uint8
    confidence: jax.Array  # uint16


def init_state(accepted: jax.Array) -> VoteRecordState:
    """Fresh records seeded with an initial preference (`vote.go:33-35`).

    `accepted` is a bool array of any shape; confidence starts at 0 with the
    preference bit set iff accepted.
    """
    accepted = jnp.asarray(accepted)
    return VoteRecordState(
        votes=jnp.zeros(accepted.shape, jnp.uint8),
        consider=jnp.zeros(accepted.shape, jnp.uint8),
        confidence=accepted.astype(jnp.uint16),
    )


def is_accepted(confidence: jax.Array) -> jax.Array:
    """Preference bit (`vote.go:38-40`)."""
    return (confidence & 1).astype(jnp.bool_)


def get_confidence(confidence: jax.Array) -> jax.Array:
    """Confidence counter (`vote.go:43-45`)."""
    return confidence >> 1


def has_finalized(confidence: jax.Array,
                  cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """Counter reached the finalization score (`vote.go:48-50`)."""
    return get_confidence(confidence) >= cfg.finalization_score


def status(confidence: jax.Array,
           cfg: AvalancheConfig = DEFAULT_CONFIG) -> jax.Array:
    """Status codes (`vote.go:77-91`), as int8 matching types.Status values."""
    acc = is_accepted(confidence)
    fin = has_finalized(confidence, cfg)
    # finalized: accepted -> FINALIZED(3) else INVALID(0)
    # live:      accepted -> ACCEPTED(2)  else REJECTED(1)
    return jnp.where(
        fin,
        jnp.where(acc, jnp.int8(3), jnp.int8(0)),
        jnp.where(acc, jnp.int8(2), jnp.int8(1)),
    )


def _apply_vote_bits(
    votes: jax.Array,
    consider: jax.Array,
    confidence: jax.Array,
    yes_bit: jax.Array,
    non_neutral_bit: jax.Array,
    cfg: AvalancheConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One window-shift + confidence transition (`vote.go:54-75`).

    The single shared core behind `register_vote` and
    `register_packed_votes`; takes the already-extracted yes / non-neutral
    bits.  Returns (votes, consider, confidence, changed).

    The confidence counter saturates at its 15-bit ceiling instead of wrapping
    (the reference deletes finalized records before overflow could matter,
    `processor.go:114-116`; batched records may live on past finalization, and
    a uint16 wrap would silently un-finalize them).
    """
    window_mask = jnp.uint8((1 << cfg.window) - 1)
    votes = ((votes << 1) | yes_bit.astype(jnp.uint8)) & window_mask
    consider = ((consider << 1)
                | non_neutral_bit.astype(jnp.uint8)) & window_mask

    threshold = jnp.uint8(cfg.quorum - 1)  # reference: > 6 with quorum 7
    yes = popcount8(votes & consider) > threshold
    no = popcount8(jnp.bitwise_not(votes) & consider & window_mask) > threshold
    conclusive = yes | no

    accepted = (confidence & 1) == 1
    agree = accepted == yes

    saturated = get_confidence(confidence) >= jnp.uint16(0x7FFF)
    conf_bumped = jnp.where(saturated, confidence,
                            confidence + jnp.uint16(2))
    conf_reset = yes.astype(jnp.uint16)
    new_confidence = jnp.where(
        conclusive,
        jnp.where(agree, conf_bumped, conf_reset),
        confidence,
    )

    finalized_now = (get_confidence(conf_bumped)
                     == cfg.finalization_score) & agree
    changed = conclusive & (jnp.logical_not(agree) | finalized_now)
    return votes, consider, new_confidence, changed


def register_vote(
    state: VoteRecordState,
    err: jax.Array,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Apply one vote per record; returns (new_state, changed).

    `err` is a signed integer array broadcastable to the state shape.
    `changed` mirrors the reference's bool return (`vote.go:54`): True iff the
    acceptance or finalization state changed on this vote.

    `update_mask` (bool, optional) freezes records where False — the batched
    replacement for the reference's delete-on-finalize (`processor.go:114-116`)
    and skip-missing-record (`processor.go:95-99`) map operations: masked-out
    records keep their exact state and report changed=False.
    """
    err = jnp.asarray(err)
    votes, consider, confidence, changed = _apply_vote_bits(
        state.votes, state.consider, state.confidence,
        err == 0, err >= 0, cfg)

    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        votes = jnp.where(update_mask, votes, state.votes)
        consider = jnp.where(update_mask, consider, state.consider)
        confidence = jnp.where(update_mask, confidence, state.confidence)
        changed = changed & update_mask

    return VoteRecordState(votes, consider, confidence), changed


def register_votes_sequence(
    state: VoteRecordState,
    errs: jax.Array,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Apply a sequence of votes (leading axis of `errs`) via `lax.scan`.

    Returns (final_state, changed[num_votes, ...]).  Mirrors replaying the
    reference ingest loop (`processor.go:94-117`) over a whole response.
    """
    errs = jnp.asarray(errs)

    def step(s, e):
        return register_vote(s, e, cfg, update_mask)

    return lax.scan(step, state, errs)


def register_packed_votes(
    state: VoteRecordState,
    yes_pack: jax.Array,
    consider_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig = DEFAULT_CONFIG,
    update_mask: jax.Array | None = None,
    absent_is_skip: bool | None = None,
) -> Tuple[VoteRecordState, jax.Array]:
    """Apply k votes per record from bit-packed planes, oldest-first.

    `yes_pack` / `consider_pack` are uint8 arrays of the state shape; bit j
    (j in [0, k)) holds vote j's yes / non-neutral flag.  Vote 0 is applied
    first.  This is the memory-lean form the simulator uses: the per-round
    gather emits two uint8 planes instead of a [nodes, k, txs] tensor, and the
    k window updates fuse into one element-wise pass (no HBM round-trips
    between them).  Semantically identical to k calls to `register_vote` with
    errs derived from the bits (changed flags are OR-reduced across the k
    votes, which is what one reference response produces at most one status
    update per target from, `processor.go:105-112`).

    `absent_is_skip` selects what a zero consider bit MEANS.  False: a
    DELIVERED neutral vote — it shifts the window with its consider bit
    off, exactly `vote.go:54-75`.  True: a vote that never arrived — the
    slot registers NOTHING (no shift, no confidence transition),
    mirroring the reference HOST path where an expired or missing
    response never reaches RegisterVotes at all (`processor.go:61-122`;
    `response.go:5-51` expiry) and present votes are conclusive.  None
    (the default) reads `cfg.skip_absent_votes`, so every ingest site —
    including the fused/Pallas dispatcher's fallback — follows the
    config with no per-call-site threading; pass a bool to override
    explicitly (tests).  The window-occupancy cost of the False mode is
    quantified in RESULTS.md's churn study.

    Returns (new_state, any_changed).
    """
    if not (0 < k <= 8):
        raise ValueError("k must be in (0, 8] for uint8 packing")

    if absent_is_skip is None:
        absent_is_skip = cfg.skip_absent_votes
    if absent_is_skip:
        return _register_packed_votes_skip(state, yes_pack, consider_pack,
                                           k, cfg, update_mask)

    votes, consider, confidence = state
    any_changed = jnp.zeros(state.votes.shape, jnp.bool_)

    # Hand-fused hot loop.  Semantically identical to k applications of
    # `_apply_vote_bits` (the invariant is pinned by
    # test_packed_votes_match_sequential), but with the per-vote SWAR
    # popcounts replaced by incremental window counters: popcount once
    # before the loop, then +incoming-bit / -evicted-bit per vote.  This
    # roughly halves the VPU op count of the dominant kernel (measured
    # ~6.6ms -> ~3.5ms per round at 8192x8192 on v5e).
    window_mask = jnp.uint8((1 << cfg.window) - 1)
    full_window = cfg.window == 8  # uint8 shifts self-truncate; skip masking
    top_bit = cfg.window - 1
    threshold = jnp.uint8(cfg.quorum - 1)
    one = jnp.uint8(1)

    yes_cnt = popcount8(votes & consider)          # non-neutral yes votes
    cons_cnt = popcount8(consider)                 # non-neutral votes

    for j in range(k):  # unrolled: k is a static config constant
        bit = jnp.uint8(1 << j)
        in_yes_raw = (yes_pack & bit) != 0
        in_cons = ((consider_pack & bit) != 0).astype(jnp.uint8)
        in_yes = in_yes_raw.astype(jnp.uint8) & in_cons  # counted iff considered

        evict_yes = ((votes & consider) >> top_bit) & one
        evict_cons = (consider >> top_bit) & one
        yes_cnt = yes_cnt + in_yes - evict_yes
        cons_cnt = cons_cnt + in_cons - evict_cons

        votes = (votes << 1) | in_yes_raw.astype(jnp.uint8)
        consider = (consider << 1) | in_cons
        if not full_window:
            votes &= window_mask
            consider &= window_mask

        yes = yes_cnt > threshold
        no = (cons_cnt - yes_cnt) > threshold
        conclusive = yes | no

        accepted = (confidence & 1) == 1
        agree = accepted == yes
        saturated = (confidence >> 1) >= jnp.uint16(0x7FFF)
        conf_bumped = jnp.where(saturated, confidence,
                                confidence + jnp.uint16(2))
        confidence = jnp.where(
            conclusive,
            jnp.where(agree, conf_bumped, yes.astype(jnp.uint16)),
            confidence,
        )
        # Counters track votes&consider, which the flip/reset does NOT
        # change (only confidence flips), so no counter fixup is needed.
        finalized_now = ((conf_bumped >> 1) == cfg.finalization_score) & agree
        any_changed |= conclusive & (jnp.logical_not(agree) | finalized_now)

    if not full_window:
        votes &= window_mask
        consider &= window_mask
    new_state = VoteRecordState(votes, consider, confidence)
    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        new_state = VoteRecordState(
            jnp.where(update_mask, new_state.votes, state.votes),
            jnp.where(update_mask, new_state.consider, state.consider),
            jnp.where(update_mask, new_state.confidence, state.confidence),
        )
        any_changed = any_changed & update_mask
    return new_state, any_changed


def _register_packed_votes_skip(
    state: VoteRecordState,
    yes_pack: jax.Array,
    present_pack: jax.Array,
    k: int,
    cfg: AvalancheConfig,
    update_mask: jax.Array | None,
) -> Tuple[VoteRecordState, jax.Array]:
    """`register_packed_votes` with absent slots registering nothing.

    Plain per-slot `_apply_vote_bits` + select (no incremental-counter
    fusion): this path only activates for configs with non-responses
    (churn / drops / weighted self-draws) under `skip_absent_votes`, never
    for the flagship bench config, so clarity wins over the hand-fused
    form.  Present votes carry non_neutral=True — every batched responder
    commits to a preference; delivered-neutral semantics remain the
    default mode's job.
    """
    votes, consider, confidence = state
    any_changed = jnp.zeros(state.votes.shape, jnp.bool_)
    for j in range(k):
        bit = jnp.uint8(1 << j)
        present = (present_pack & bit) != 0
        yes_bit = (yes_pack & bit) != 0
        v2, c2, conf2, ch2 = _apply_vote_bits(
            votes, consider, confidence, yes_bit,
            jnp.ones_like(yes_bit), cfg)
        votes = jnp.where(present, v2, votes)
        consider = jnp.where(present, c2, consider)
        confidence = jnp.where(present, conf2, confidence)
        any_changed |= ch2 & present
    new_state = VoteRecordState(votes, consider, confidence)
    if update_mask is not None:
        update_mask = jnp.asarray(update_mask, jnp.bool_)
        new_state = VoteRecordState(
            jnp.where(update_mask, new_state.votes, state.votes),
            jnp.where(update_mask, new_state.consider, state.consider),
            jnp.where(update_mask, new_state.confidence, state.confidence),
        )
        any_changed = any_changed & update_mask
    return new_state, any_changed
